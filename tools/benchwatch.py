#!/usr/bin/env python
"""Replay the committed bench history through the regression sentinel.

``python tools/benchwatch.py`` rebuilds the per-(metric, config)
trajectories from the repo's ``BENCH_*.json`` files (round order, the
Emitter JSONL tail included when present) and prints the verdict each
line would have received at the moment it landed — the same
verdict-then-absorb sequence ``bench.py`` runs live. Three uses:

* **post-mortem**: rerun after a round to see which trajectories moved
  (``BENCH_r03``'s dead rounds show up as ``no_value`` lines carrying
  their error, not as silent gaps);
* **pre-merge**: point it at a candidate bench line (``--line file``)
  to judge it against committed history before the file is committed;
* **CI sentinel**: exit code 9 when the *latest* point of any
  trajectory is a confirmed regression, 0 otherwise — so a pipeline
  can gate on "history says we got slower" without parsing JSON.

Exit codes: 0 clean, 9 confirmed regression at head, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.telemetry import regress  # noqa: E402


def _fmt_value(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def _fmt_row(source, verdict):
    tag = verdict["verdict"]
    if verdict.get("confirmed"):
        tag = tag.upper()
    delta = verdict.get("delta_pct")
    delta_s = "%+.1f%%" % (delta * 100) if isinstance(delta, float) else ""
    return "%-28s %-42s %10s %-22s %8s  %s" % (
        source[:28], str(verdict.get("metric"))[:42],
        _fmt_value(verdict.get("value")), tag, delta_s,
        (verdict.get("error") or "")[:60])


def replay(paths, args):
    """Chronological replay: every line gets its at-the-time verdict.

    Returns ``(verdicts, head)`` where *verdicts* is the full list (in
    replay order, each tagged with its source file) and *head* maps each
    trajectory key to its final verdict — the rc gate judges only the
    head, so an old regression that later recovered does not fail a
    clean tree forever.
    """
    store = regress.TrajectoryStore()
    verdicts = []
    head = {}
    for path in paths:
        source = os.path.basename(path)
        for line in regress.iter_bench_lines(path):
            verdict = store.verdict(line)
            verdict["source"] = source
            key = store.add(line, source=source)
            verdicts.append(verdict)
            if key is not None:
                head[key] = verdict
    return verdicts, head


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="benchwatch",
        description="replay bench history through the regression sentinel")
    parser.add_argument("paths", nargs="*",
                        help="history files to replay in order "
                             "(default: the repo's BENCH_*.json, round "
                             "order, plus the Emitter JSONL if present)")
    parser.add_argument("--line", metavar="FILE", action="append",
                        default=[],
                        help="judge FILE's bench line(s) against the "
                             "replayed history (appended last, so its "
                             "verdicts see the full committed history)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON document instead of the table")
    parser.add_argument("--all", action="store_true",
                        help="print every verdict, not just "
                             "noteworthy ones (non-ok, or head of a "
                             "trajectory)")
    args = parser.parse_args(argv)

    paths = args.paths or regress.default_paths()
    missing = [p for p in list(paths) + list(args.line)
               if not os.path.exists(p)]
    if missing:
        parser.error("no such history file: %s" % ", ".join(missing))
    if not paths:
        parser.error("no history files found (no BENCH_*.json in repo "
                     "root and none given)")
    paths = list(paths) + list(args.line)

    verdicts, head = replay(paths, args)
    head_verdicts = set(map(id, head.values()))
    regressed = sorted("%s [%s]" % (v.get("metric"), v.get("config"))
                       for v in head.values() if v.get("confirmed"))

    if args.json:
        doc = {"paths": paths, "points": len(verdicts),
               "trajectories": len(head),
               "regressions_at_head": regressed,
               "verdicts": verdicts, "rc": 9 if regressed else 0}
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print("%-28s %-42s %10s %-22s %8s  %s" % (
            "source", "metric", "value", "verdict", "delta", "error"))
        shown = 0
        for v in verdicts:
            noteworthy = (v["verdict"] not in ("ok",)
                          or id(v) in head_verdicts)
            if args.all or noteworthy:
                print(_fmt_row(v["source"], v))
                shown += 1
        if shown < len(verdicts):
            print("(%d unremarkable verdict(s) hidden; --all shows them)"
                  % (len(verdicts) - shown))
        print("replayed %d point(s) across %d trajectorie(s) from %d "
              "file(s)" % (len(verdicts), len(head), len(paths)))
        if regressed:
            print("CONFIRMED REGRESSION at head of: %s"
                  % "; ".join(regressed))
        else:
            print("no confirmed regressions at head")

    return 9 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
