"""Persistent TPU-relay prober (VERDICT r4 item 1).

Loops for the whole session: every cycle it probes jax backend init in a
subprocess with a hard timeout, appending a timestamped line to
``TPU_ATTEMPTS.log``. The moment a probe sees a real TPU device it runs the
full ``bench.py`` (saving stdout to ``BENCH_TPU_LIVE.json``), then
``tests/test_operator_tpu.py`` and the ``__graft_entry__.entry()`` compile
check on the real chip, and keeps re-probing afterwards (cheap) so the log
proves relay state over the whole session.

Run:  python tools/tpu_probe.py [--interval 600] [--once]
"""
import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_ATTEMPTS.log")

PROBE_SRC = r"""
import json, sys
import jax
devs = jax.devices()
print(json.dumps({"platform": devs[0].platform, "n": len(devs),
                  "kind": getattr(devs[0], "device_kind", "?")}))
"""


def log(msg):
    line = "%s %s" % (datetime.datetime.utcnow().isoformat() + "Z", msg)
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s=90):
    """Probe backend init in a subprocess (a hung init can't wedge us)."""
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC], capture_output=True,
            text=True, timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, "timeout after %.0fs (relay down/wedged)" % (time.time() - t0)
    if out.returncode != 0:
        return None, "init raised rc=%d: %s" % (
            out.returncode, (out.stderr or "").strip()[-300:])
    try:
        info = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:
        return None, "unparseable probe output: %r" % out.stdout[-200:]
    return info, None


def run_bench(profile=False):
    """Headline bench runs UNPROFILED (the number of record); a second,
    shorter profiled run captures the device trace separately."""
    tag = "profiled " if profile else ""
    log("TPU UP — running %sbench.py" % tag)
    env = dict(os.environ, MXNET_BENCH_DEADLINE_S="600" if profile
               else "3300")  # remote compiles run minutes each; six phases
    if profile:
        env["MXNET_BENCH_PROFILE"] = os.path.join(REPO, "tpu_trace")
    out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                         text=True, timeout=3600, cwd=REPO, env=env)
    last = ""
    for ln in out.stdout.strip().splitlines():
        if ln.startswith("{"):
            last = ln
    log("%sbench rc=%d result=%s" % (tag, out.returncode, last[:400]))
    if not last or out.returncode != 0:
        # surface the failure cause, not just the rc (r5: a silent rc=1
        # with no JSON burned 23 min of relay uptime with zero evidence)
        tail = (out.stderr or "").strip().splitlines()[-8:]
        log("%sbench stderr tail: %s" % (tag, " | ".join(tail)[:1200]))
    ok = False
    if last:
        try:
            ok = json.loads(last).get("value") is not None
        except Exception:
            ok = False
    if ok:  # only persist/settle on a run with a real number — a
        # backend-init failure line must not stop future attempts
        name = "BENCH_TPU_PROFILED.json" if profile else "BENCH_TPU_LIVE.json"
        with open(os.path.join(REPO, name), "w") as f:
            f.write(last + "\n")
    return last if ok else ""


def run_entry_check():
    """__graft_entry__.entry() compile check on the real chip."""
    log("running entry() compile check on real chip")
    src = ("import __graft_entry__ as g, jax; fn, args = g.entry(); "
           "out = jax.jit(fn)(*args); jax.block_until_ready(out); "
           "print('ENTRY_OK', getattr(out, 'shape', None))")
    out = subprocess.run([sys.executable, "-c", src], capture_output=True,
                         text=True, timeout=900, cwd=REPO)
    log("entry check rc=%d out=%s" % (
        out.returncode, (out.stdout or out.stderr).strip()[-200:]))


def run_tpu_tests():
    log("running tests/test_operator_tpu.py on real chip")
    env = dict(os.environ, MXNET_TEST_DEVICE="tpu")  # conftest CPU opt-out
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_operator_tpu.py",
         "-q", "--no-header", "-x"],
        capture_output=True, text=True, timeout=3600, cwd=REPO, env=env)
    tail = (out.stdout or "").strip().splitlines()[-3:]
    # rc=0 with zero tests PASSED means the subprocess never saw the chip
    # (module-level skipif) — record that as a non-result, not a pass
    import re as _re

    m = _re.search(r"(\d+) passed", out.stdout or "")
    ran = bool(m and int(m.group(1)) > 0)
    verdict = ("PASS" if out.returncode == 0 and ran else
               "NO-TPU-VISIBLE (all skipped)" if out.returncode == 0 else
               "FAIL")
    log("tpu tests rc=%d verdict=%s tail=%s"
        % (out.returncode, verdict, " | ".join(tail)))
    with open(os.path.join(REPO, "TPU_TEST_RESULT.txt"), "w") as f:
        f.write("verdict=%s rc=%d\n%s\n%s" % (verdict, out.returncode,
                                              out.stdout[-4000:],
                                              out.stderr[-2000:]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()
    benched = os.path.exists(os.path.join(REPO, "BENCH_TPU_LIVE.json"))
    while True:
        info, err = probe()
        if info is None:
            log("probe FAILED: %s" % err)
        elif info.get("platform") != "tpu":
            log("probe ok but platform=%s (no TPU)" % info.get("platform"))
        else:
            log("probe OK: %s" % json.dumps(info))
            if not benched:
                # independent steps: one crashing must not skip the others
                # (and only a SUCCESSFUL bench stops future attempts)
                try:
                    benched = bool(run_bench())
                except Exception as e:  # noqa: BLE001
                    log("bench crashed: %r" % e)
                try:
                    run_bench(profile=True)  # device trace, separate run
                except Exception as e:  # noqa: BLE001
                    log("profiled bench crashed: %r" % e)
                try:
                    run_entry_check()
                except Exception as e:  # noqa: BLE001
                    log("entry check crashed: %r" % e)
                try:
                    run_tpu_tests()
                except Exception as e:  # noqa: BLE001
                    log("tpu tests crashed: %r" % e)
        if args.once:
            break
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
