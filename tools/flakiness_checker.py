#!/usr/bin/env python
"""Re-run a test many times with different seeds to expose flakiness.

Counterpart of the reference's ``tools/flakiness_checker.py``: takes a
pytest-style target (``tests/test_operator.py::test_softmax`` or
``test_operator.test_softmax``), runs it N times with a different
``MXNET_TEST_SEED`` each run (the seed the ``@with_seed`` fixture honors),
and reports the failing seeds for reproduction.

Example:
  python tools/flakiness_checker.py -n 20 tests/test_operator.py::test_dropout
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys


def normalize_target(t: str) -> str:
    if "::" in t or t.endswith(".py"):
        return t
    if "." in t:  # reference style: test_module.test_name
        mod, _, fn = t.rpartition(".")
        return os.path.join("tests", mod + ".py") + ("::" + fn if fn else "")
    return t


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("test", help="pytest target or module.test_name")
    parser.add_argument("-n", "--num-trials", type=int, default=10)
    parser.add_argument("-s", "--seed", type=int, default=None,
                        help="run every trial with this one seed")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    target = normalize_target(args.test)
    failures = []
    for trial in range(args.num_trials):
        seed = args.seed if args.seed is not None else random.randint(0, 2**31 - 1)
        env = dict(os.environ, MXNET_TEST_SEED=str(seed))
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "-x", "-q", target],
            capture_output=True, text=True, env=env)
        status = "PASS" if out.returncode == 0 else "FAIL"
        print("trial %3d seed %10d : %s" % (trial, seed, status))
        if out.returncode != 0:
            failures.append(seed)
            if args.verbose:
                print(out.stdout[-3000:])
    print("\n%d/%d trials failed" % (len(failures), args.num_trials))
    if failures:
        print("failing seeds:", failures)
        print("reproduce with: MXNET_TEST_SEED=%d python -m pytest %s"
              % (failures[0], target))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
