#!/usr/bin/env python
"""Pack an image dataset into RecordIO (.rec/.idx).

TPU-native re-design of the reference's ``tools/im2rec.py``: same CLI
contract and on-disk formats (``.lst`` = ``idx\\tlabel[\\tlabel...]\\tpath``;
``.rec`` = RecordIO of ``pack_img`` records readable by ``ImageIter`` /
``ImageRecordDataset``), implemented over ``mxnet_tpu.recordio`` — which
uses the native C++ writer (src/recordio.cc) when available — and
``mxnet_tpu.image`` for encode/resize. Multi-worker packing uses processes
feeding a single writer, mirroring the reference's ``--num-thread``.

Modes:
  --list  : walk an image directory and write a .lst file
  (default): read a .lst file and write .rec + .idx

Examples:
  python tools/im2rec.py --list --recursive data/train data/images
  python tools/im2rec.py --resize 256 data/train data/images
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive, exts):
    """Yield (relative_path, label) with labels assigned per sorted subdir
    (reference im2rec.py list_image)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and suffix in exts:
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                yield (i, fname, 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for idx, rel, label in image_list:
            fout.write("%d\t%f\t%s\n" % (idx, float(label), rel))


def read_list(path_in):
    """Parse a .lst file → (idx, labels, relpath) (reference read_list)."""
    with open(path_in) as fin:
        for lineno, line in enumerate(fin):
            parts = line.strip().split("\t")
            if len(parts) < 3:
                print("lst line %d malformed: %r" % (lineno, line), file=sys.stderr)
                continue
            idx = int(float(parts[0]))
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def pack_one(args, idx, labels, rel_path):
    from mxnet_tpu import image, recordio

    fullpath = os.path.join(args.root, rel_path)
    header = recordio.IRHeader(0, labels[0] if len(labels) == 1 else labels, idx, 0)
    if args.pass_through:
        with open(fullpath, "rb") as fin:
            return recordio.pack(header, fin.read())
    img = image.imread(fullpath, flag=1 if args.color != 0 else 0)
    img = img.asnumpy() if hasattr(img, "asnumpy") else img
    if args.resize:
        h, w = img.shape[:2]
        if min(h, w) > args.resize:
            if h > w:
                img = image.imresize(img, args.resize, int(h * args.resize / w))
            else:
                img = image.imresize(img, int(w * args.resize / h), args.resize)
            img = img.asnumpy() if hasattr(img, "asnumpy") else img
    if args.center_crop:
        h, w = img.shape[:2]
        s = min(h, w)
        y0, x0 = (h - s) // 2, (w - s) // 2
        img = img[y0:y0 + s, x0:x0 + s]
    return recordio.pack_img(header, img, quality=args.quality,
                             img_fmt=args.encoding)


def make_rec(args, lst_path):
    from mxnet_tpu import engine, recordio

    prefix = os.path.splitext(lst_path)[0]
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = [0]
    errors = [0]
    tic = time.time()

    if args.num_thread > 1:
        # parallel packing on the host dependency engine (reference
        # im2rec.py --num-thread): decode/resize/encode jobs run on worker
        # threads; each finished job pushes its write as an op mutating the
        # writer var, so file writes stay serialized while packing overlaps.
        import threading

        writer_var = engine.new_var()
        err_lock = threading.Lock()  # pack jobs run concurrently

        def make_job(idx, labels, rel):
            def pack_job():
                try:
                    packed = pack_one(args, idx, labels, rel)
                except Exception as exc:  # noqa: BLE001 - unreadable image
                    with err_lock:
                        errors[0] += 1
                    print("skipping %s: %s" % (rel, exc), file=sys.stderr)
                    return

                def write_job():
                    rec.write_idx(idx, packed)
                    count[0] += 1

                engine.push(write_job, mutable_vars=[writer_var])

            return pack_job

        for idx, labels, rel in read_list(lst_path):
            engine.push(make_job(idx, labels, rel))
        engine.wait_for_all()
        engine.delete_var(writer_var)
    else:
        for idx, labels, rel in read_list(lst_path):
            try:
                rec.write_idx(idx, pack_one(args, idx, labels, rel))
                count[0] += 1
            except Exception as exc:  # noqa: BLE001 - skip unreadable images
                errors[0] += 1
                print("skipping %s: %s" % (rel, exc), file=sys.stderr)
            if count[0] % 1000 == 0 and count[0]:
                print("packed %d images (%.1f img/s)"
                      % (count[0], count[0] / (time.time() - tic)))
    rec.close()
    print("wrote %s.rec: %d records, %d errors" % (prefix, count[0], errors[0]))
    return count[0]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("prefix", help=".lst/.rec path prefix (or a .lst file)")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="create a .lst instead of packing a .rec")
    parser.add_argument("--recursive", action="store_true",
                        help="walk subdirectories; each subdir is a label class")
    parser.add_argument("--exts", nargs="+", default=list(IMG_EXTS))
    parser.add_argument("--chunks", type=int, default=1,
                        help="split the .lst into N chunks (train on shards)")
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--test-ratio", type=float, default=0.0)
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--pass-through", action="store_true",
                        help="pack raw file bytes without re-encoding")
    parser.add_argument("--resize", type=int, default=0,
                        help="resize the shorter edge to this many pixels")
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    parser.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    parser.add_argument("--num-thread", type=int, default=1,
                        help="pack with this many host-engine workers")
    args = parser.parse_args(argv)
    if args.num_thread > 1:
        # the native engine sizes its pool from this env at first use
        os.environ.setdefault("MXNET_CPU_WORKER_NTHREADS",
                              str(args.num_thread))

    if args.list:
        images = list(list_images(args.root, args.recursive, tuple(args.exts)))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
            images = [(i, rel, lab) for i, (_, rel, lab) in enumerate(images)]
        n_test = int(len(images) * args.test_ratio)
        n_train = int(len(images) * args.train_ratio)
        if args.test_ratio:
            write_list(args.prefix + "_test.lst", images[:n_test])
        if args.train_ratio < 1.0 or args.test_ratio:
            write_list(args.prefix + "_train.lst", images[n_test:n_test + n_train])
        else:
            write_list(args.prefix + ".lst", images)
        print("listed %d images" % len(images))
        return 0

    lst = args.prefix if args.prefix.endswith(".lst") else args.prefix + ".lst"
    if not os.path.isfile(lst):
        print("no such .lst file: %s (run with --list first)" % lst, file=sys.stderr)
        return 1
    make_rec(args, lst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
