"""tpulint command line: ``python -m tools.tpulint [paths...]``.

Exit codes: 0 clean (or baseline written), 1 new findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from .cache import DEFAULT_CACHE_PATH, LintCache, baseline_sig
from .core import (DEFAULT_BASELINE, DEFAULT_ROOTS, REPO_ROOT, Finding,
                   all_passes, apply_baseline, baseline_counts, collect_files,
                   key_scope, lint_files, load_baseline, load_justifications,
                   relpath_of, write_baseline_counts)
from .reporters import render_json, render_stats, render_text


def changed_files(root: Path = REPO_ROOT) -> Optional[List[str]]:
    """Paths (repo-relative) touched in the working tree vs HEAD, plus
    untracked files — the quick local pre-push scope. None when git fails:
    a broken git must fail the gate loudly, not pass it as 'no changes'."""
    out: List[str] = []
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=str(root), capture_output=True,
                                  text=True, timeout=30, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
        out.extend(line.strip() for line in proc.stdout.splitlines() if line.strip())
    return sorted(set(out))


def filter_to_scope(changed: Sequence[str], scope: Sequence[Path],
                    root: Path = REPO_ROOT) -> List[Path]:
    """Intersect changed paths with the already-collected lint scope."""
    wanted = {str((root / c).resolve()) for c in changed if c.endswith(".py")}
    return [p for p in scope if str(p.resolve()) in wanted]


def lint_paths(paths: Sequence[str], baseline_path: Optional[Path] = DEFAULT_BASELINE,
               passes: Optional[Sequence[str]] = None, cache: bool = True,
               ) -> Tuple[List[Finding], List[Finding]]:
    """Lint `paths`; returns ``(new_findings, all_findings)`` where *new*
    means not covered by the baseline (all of them when ``baseline_path``
    is None). ``cache=True`` (default) shares the CLI's incremental
    cache — keyed by the baseline content like every other entry point —
    so programmatic callers (the tier-1 gate test, bench.py's per-line
    ``lint_clean`` stamp) pay ~20ms warm instead of a cold whole-program
    run."""
    files = collect_files(paths)
    lc = LintCache(DEFAULT_CACHE_PATH,
                   extra_sig=baseline_sig(baseline_path)) if cache else None
    findings = lint_files(files, passes=passes, cache=lc)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    return apply_baseline(findings, baseline), findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="AST-based TPU-correctness linter for mxnet_tpu.")
    parser.add_argument("paths", nargs="*", default=list(DEFAULT_ROOTS),
                        help="files or directories to lint (default: %s)"
                             % " ".join(DEFAULT_ROOTS))
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                        help="baseline file (default: tools/tpulint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write all current findings to the baseline and exit 0")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs HEAD (git diff + untracked)")
    parser.add_argument("--select", default=None, metavar="RULES",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="list available rules and exit")
    parser.add_argument("--stats", action="store_true",
                        help="print per-pass timing and cache hit rate")
    parser.add_argument("--cache", type=Path, default=DEFAULT_CACHE_PATH,
                        metavar="PATH",
                        help="incremental cache file (default: "
                             ".tpulint-cache.json at the repo root)")
    parser.add_argument("--no-cache", action="store_true",
                        help="run every pass from scratch, don't touch the cache")
    args = parser.parse_args(argv)

    registry = all_passes()
    if args.list_rules:
        for name in sorted(registry):
            print("%-14s %s" % (name, registry[name].description))
        return 0

    passes = None
    if args.select:
        passes = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in passes if r not in registry]
        if unknown:
            print("tpulint: unknown rule(s): %s (try --list-rules)"
                  % ", ".join(unknown), file=sys.stderr)
            return 2

    # an explicit path that matches nothing is a usage error, not a clean run
    missing = [p for p in args.paths
               if not (Path(p) if Path(p).is_absolute() else REPO_ROOT / p).exists()]
    if missing:
        print("tpulint: path(s) do not exist: %s" % ", ".join(missing),
              file=sys.stderr)
        return 2

    files = collect_files(args.paths)
    project_scope = None
    if args.changed_only:
        changed = changed_files()
        if changed is None:
            print("tpulint: --changed-only requires a working `git diff`; "
                  "run on explicit paths instead", file=sys.stderr)
            return 2
        # report only on changed files, but keep the WHOLE collected
        # scope as graph context: a traced/thread seed in an unchanged
        # file must still reach a hazard in a changed one
        project_scope = files
        files = filter_to_scope(changed, files)
        if not files:
            print("tpulint: no changed files in scope")
            return 0

    import time

    t0 = time.perf_counter()
    # the cache is keyed by the baseline CONTENT: editing the baseline
    # invalidates cached pass results, so a warm run re-runs and
    # re-reports instead of serving results computed in the old world
    cache = None if args.no_cache else LintCache(
        args.cache, extra_sig=baseline_sig(
            None if args.no_baseline else args.baseline))
    stats: dict = {}
    findings = lint_files(files, passes=passes, cache=cache, stats=stats,
                          project_scope=project_scope)
    stats["total_ms"] = round((time.perf_counter() - t0) * 1000, 1)

    def emit_stats():
        if args.stats:
            # stderr: --format json consumers must keep a parseable stdout
            print(render_stats(stats), file=sys.stderr)

    counts = baseline_counts(findings)
    # Scope actually covered by this run: baseline keys outside it (files
    # not linted, rules not selected) carry no evidence either way.
    linted = {relpath_of(p) for p in files}
    ran_rules = set(passes) if passes is not None else set(registry)

    def in_scope(key: str) -> bool:
        path, rule = key_scope(key)
        return path in linted and rule in ran_rules

    if args.write_baseline:
        merged = dict(counts)
        for k, v in load_baseline(args.baseline).items():
            if not in_scope(k):  # narrowed run must not drop other entries
                merged[k] = v
        # keep each surviving entry's one-line justification
        write_baseline_counts(merged, args.baseline,
                              justifications=load_justifications(args.baseline))
        if cache is not None:
            # the cache on disk is keyed by the PRE-write baseline: re-key
            # to the baseline just written so the next run starts warm
            cache.rekey(baseline_sig(args.baseline))
            cache.save(root=REPO_ROOT)
        print("tpulint: wrote %d finding(s) to %s (%d kept from outside this "
              "run's scope)" % (sum(merged.values()), args.baseline,
                               sum(merged.values()) - len(findings)))
        emit_stats()
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new = apply_baseline(findings, baseline)
    stale = [k for k in baseline if in_scope(k) and counts.get(k, 0) < baseline[k]]

    render = render_json if args.format == "json" else render_text
    print(render(new, len(findings), len(findings) - len(new), stale))
    emit_stats()
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__.py
    sys.exit(main())
