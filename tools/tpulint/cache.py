"""File-hash-keyed incremental result cache for tpulint.

The tier-1 gate runs the full linter on every test invocation; with the
whole-program layer (parse every file, build the call graph, propagate
two lattices) a from-scratch run costs seconds. The cache keeps the gate
negligible:

- **local passes** (one file in, findings out) are keyed by the file's
  content hash — an unchanged file never re-runs them;
- **project passes** (interprocedural: need the cross-file lattices) are
  additionally keyed by a *scope signature* — the hash of every file in
  the linted scope — because an edit anywhere can change reachability
  everywhere. Unchanged scope → every project result is a hit and the
  graph is never built (the warm run does hashing + JSON only);
- the whole cache is versioned by a hash of the linter's own sources
  (:data:`LINT_SOURCE_VERSION`), so editing a pass invalidates stale
  results without a manual version bump.

Cached findings are stored *post-suppression* (suppression comments live
in the hashed file content, so a hit is exact). Writes are atomic
(tmp + ``os.replace``) — concurrent runs at worst lose an update, never
corrupt the file.
"""
from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

DEFAULT_CACHE_PATH = Path(__file__).resolve().parent.parent.parent \
    / ".tpulint-cache.json"


def _source_version() -> str:
    """Hash of the linter's own source files — any edit to core, graph,
    cache or a pass invalidates every cached result."""
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for p in sorted(pkg.rglob("*.py")):
        h.update(p.as_posix().encode())
        try:
            h.update(p.read_bytes())
        except OSError:
            pass
    return h.hexdigest()[:16]


LINT_SOURCE_VERSION = _source_version()


def file_sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


def scope_signature(shas: Sequence[Tuple[str, str]]) -> str:
    """Signature of a whole lint scope: ``(relpath, sha)`` of every file,
    order-independent."""
    h = hashlib.sha256()
    h.update(LINT_SOURCE_VERSION.encode())
    for rel, sha in sorted(shas):
        h.update(rel.encode())
        h.update(sha.encode())
    return h.hexdigest()[:16]


def _finding_to_dict(f: Finding) -> dict:
    return f.as_dict()


def _finding_from_dict(d: dict) -> Finding:
    return Finding(d["rule"], d["path"], d["line"], d["col"], d["message"])


class LintCache:
    """On-disk cache of per-(file, pass) findings."""

    def __init__(self, path: Path = DEFAULT_CACHE_PATH):
        self.path = Path(path)
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._entries: Dict[str, dict] = {}
        try:
            data = json.loads(self.path.read_text(encoding="utf-8"))
            if data.get("version") == LINT_SOURCE_VERSION:
                self._entries = data.get("files", {})
        except (OSError, ValueError):
            pass

    # -- local passes -------------------------------------------------------

    def get_local(self, relpath: str, sha: str,
                  pass_name: str) -> Optional[List[Finding]]:
        ent = self._entries.get(relpath)
        if ent and ent.get("sha") == sha and pass_name in ent.get("local", {}):
            self.hits += 1
            return [_finding_from_dict(d) for d in ent["local"][pass_name]]
        self.misses += 1
        return None

    def put_local(self, relpath: str, sha: str, pass_name: str,
                  findings: Sequence[Finding]) -> None:
        ent = self._fresh_entry(relpath, sha)
        ent.setdefault("local", {})[pass_name] = \
            [_finding_to_dict(f) for f in findings]
        self._dirty = True

    # -- project (interprocedural) passes -----------------------------------

    def get_project(self, relpath: str, sha: str, scope_sig: str,
                    pass_name: str) -> Optional[List[Finding]]:
        ent = self._entries.get(relpath)
        if ent and ent.get("sha") == sha and ent.get("scope_sig") == scope_sig \
                and pass_name in ent.get("project", {}):
            self.hits += 1
            return [_finding_from_dict(d) for d in ent["project"][pass_name]]
        self.misses += 1
        return None

    def put_project(self, relpath: str, sha: str, scope_sig: str,
                    pass_name: str, findings: Sequence[Finding]) -> None:
        ent = self._fresh_entry(relpath, sha)
        if ent.get("scope_sig") != scope_sig:
            ent["scope_sig"] = scope_sig
            ent["project"] = {}
        ent.setdefault("project", {})[pass_name] = \
            [_finding_to_dict(f) for f in findings]
        self._dirty = True

    def _fresh_entry(self, relpath: str, sha: str) -> dict:
        ent = self._entries.get(relpath)
        if ent is None or ent.get("sha") != sha:
            ent = {"sha": sha}
            self._entries[relpath] = ent
        return ent

    def save(self, root: Optional[Path] = None) -> None:
        # prune entries whose file no longer exists under the lint root
        # (deleted/renamed — keeps the cache from growing monotonically
        # across refactors); out-of-scope but LIVE files are deliberately
        # kept, so a narrowed run never evicts the full-scope cache
        if root is not None:
            for rel in list(self._entries):
                p = Path(rel) if os.path.isabs(rel) else Path(root) / rel
                if not p.exists():
                    del self._entries[rel]
                    self._dirty = True
        if not self._dirty:
            return
        payload = {"version": LINT_SOURCE_VERSION, "files": self._entries}
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
