"""File-hash-keyed incremental result cache for tpulint.

The tier-1 gate runs the full linter on every test invocation; with the
whole-program layer (parse every file, build the call graph, propagate
two lattices) a from-scratch run costs seconds. The cache keeps the gate
negligible:

- **local passes** (one file in, findings out) are keyed by the file's
  content hash — an unchanged file never re-runs them;
- **project passes** (interprocedural: need the cross-file lattices) are
  additionally keyed by a *scope signature* — the hash of every file in
  the linted scope — because an edit anywhere can change reachability
  everywhere. Unchanged scope → every project result is a hit and the
  graph is never built (the warm run does hashing + JSON only);
- the whole cache is versioned by a hash of the linter's own sources
  (:data:`LINT_SOURCE_VERSION`), so editing a pass invalidates stale
  results without a manual version bump;
- the cache is additionally keyed by the **baseline content**
  (``extra_sig`` — every entry point hashes the active baseline file):
  editing ``baseline.json`` invalidates cached pass results, so no
  cached result can outlive the baseline it was computed under — the
  warm run after a baseline edit re-RUNS the passes and re-reports from
  fresh findings (the PR-12 contract; it also keeps any future
  baseline-consulting pass correct by construction). Each baseline
  signature owns its own *section* of entries: a ``--no-baseline`` run
  between gate runs doesn't evict the default section, so alternating
  modes each stay warm. ``--write-baseline`` moves the active section
  to the just-written baseline (:meth:`LintCache.rekey`) so the next
  run stays warm.

Cached findings are stored *post-suppression* (suppression comments live
in the hashed file content, so a hit is exact). Writes are atomic
(tmp + ``os.replace``) — concurrent runs at worst lose an update, never
corrupt the file.
"""
from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

DEFAULT_CACHE_PATH = Path(__file__).resolve().parent.parent.parent \
    / ".tpulint-cache.json"

#: Most-recently-used baseline-signature sections kept on save: enough
#: for the default baseline, a ``--no-baseline`` section and one
#: in-flight edit, without letting superseded baselines accumulate.
MAX_SECTIONS = 3


def _source_version() -> str:
    """Hash of the linter's own source files — any edit to core, graph,
    cache or a pass invalidates every cached result."""
    h = hashlib.sha256()
    pkg = Path(__file__).resolve().parent
    for p in sorted(pkg.rglob("*.py")):
        h.update(p.as_posix().encode())
        try:
            h.update(p.read_bytes())
        except OSError:
            pass
    return h.hexdigest()[:16]


LINT_SOURCE_VERSION = _source_version()


def file_sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


def baseline_sig(path: Optional[Path]) -> str:
    """Content hash of a baseline file (empty-string for a missing or
    unset baseline) — the ``extra_sig`` the CLI keys the cache by."""
    if path is None:
        return ""
    try:
        return file_sha(Path(path).read_bytes())
    except OSError:
        return ""


def scope_signature(shas: Sequence[Tuple[str, str]]) -> str:
    """Signature of a whole lint scope: ``(relpath, sha)`` of every file,
    order-independent."""
    h = hashlib.sha256()
    h.update(LINT_SOURCE_VERSION.encode())
    for rel, sha in sorted(shas):
        h.update(rel.encode())
        h.update(sha.encode())
    return h.hexdigest()[:16]


def _finding_to_dict(f: Finding) -> dict:
    return f.as_dict()


def _finding_from_dict(d: dict) -> Finding:
    return Finding(d["rule"], d["path"], d["line"], d["col"], d["message"])


class LintCache:
    """On-disk cache of per-(file, pass) findings."""

    def __init__(self, path: Path = DEFAULT_CACHE_PATH, extra_sig: str = ""):
        self.path = Path(path)
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._sig = extra_sig
        # one SECTION of entries per baseline signature: results under a
        # different baseline are invisible (the invalidation contract)
        # but not destroyed — alternating `--no-baseline`/default runs
        # each keep their own warm section instead of ping-ponging the
        # whole file cold. Sections carry an activation stamp; save()
        # keeps the MAX_SECTIONS most recently used, so superseded
        # baselines can't accumulate orphans forever.
        self._sections: Dict[str, dict] = {}
        try:
            data = json.loads(self.path.read_text(encoding="utf-8"))
            if data.get("version") == LINT_SOURCE_VERSION:
                self._sections = data.get("sections", {})
        except (OSError, ValueError):
            pass
        top = max((s.get("stamp", 0) for s in self._sections.values()),
                  default=0)
        section = self._sections.setdefault(self._sig, {"files": {}})
        if section.get("stamp", 0) != top or top == 0:
            # mark the bump dirty so fully-warm runs PERSIST their
            # recency — otherwise the LRU eviction would retire the
            # most-actively-used section on the next baseline edit
            section["stamp"] = top + 1
            self._dirty = True
        self._entries: Dict[str, dict] = section.setdefault("files", {})

    # -- local passes -------------------------------------------------------

    def get_local(self, relpath: str, sha: str,
                  pass_name: str) -> Optional[List[Finding]]:
        ent = self._entries.get(relpath)
        if ent and ent.get("sha") == sha and pass_name in ent.get("local", {}):
            self.hits += 1
            return [_finding_from_dict(d) for d in ent["local"][pass_name]]
        self.misses += 1
        return None

    def put_local(self, relpath: str, sha: str, pass_name: str,
                  findings: Sequence[Finding]) -> None:
        ent = self._fresh_entry(relpath, sha)
        ent.setdefault("local", {})[pass_name] = \
            [_finding_to_dict(f) for f in findings]
        self._dirty = True

    # -- project (interprocedural) passes -----------------------------------

    def get_project(self, relpath: str, sha: str, scope_sig: str,
                    pass_name: str) -> Optional[List[Finding]]:
        ent = self._entries.get(relpath)
        if ent and ent.get("sha") == sha and ent.get("scope_sig") == scope_sig \
                and pass_name in ent.get("project", {}):
            self.hits += 1
            return [_finding_from_dict(d) for d in ent["project"][pass_name]]
        self.misses += 1
        return None

    def put_project(self, relpath: str, sha: str, scope_sig: str,
                    pass_name: str, findings: Sequence[Finding]) -> None:
        ent = self._fresh_entry(relpath, sha)
        if ent.get("scope_sig") != scope_sig:
            ent["scope_sig"] = scope_sig
            ent["project"] = {}
        ent.setdefault("project", {})[pass_name] = \
            [_finding_to_dict(f) for f in findings]
        self._dirty = True

    def _fresh_entry(self, relpath: str, sha: str) -> dict:
        ent = self._entries.get(relpath)
        if ent is None or ent.get("sha") != sha:
            ent = {"sha": sha}
            self._entries[relpath] = ent
        return ent

    def rekey(self, extra_sig: str = "") -> None:
        """Move the active section under a new extra signature (the
        just-written baseline's hash) — without this, a
        ``--write-baseline`` run would leave its fresh results keyed by
        the OLD baseline that the very next run cannot use (a silently
        cold 'warm' lap)."""
        if extra_sig == self._sig:
            return
        section = self._sections.pop(self._sig)
        self._sections[extra_sig] = section
        self._sig = extra_sig
        self._dirty = True

    def save(self, root: Optional[Path] = None) -> None:
        # prune entries whose file no longer exists under the lint root
        # (deleted/renamed — keeps the cache from growing monotonically
        # across refactors); out-of-scope but LIVE files are deliberately
        # kept, so a narrowed run never evicts the full-scope cache
        if root is not None:
            for section in self._sections.values():
                files = section.get("files", {})
                for rel in list(files):
                    p = Path(rel) if os.path.isabs(rel) else Path(root) / rel
                    if not p.exists():
                        del files[rel]
                        self._dirty = True
        # superseded baseline signatures would otherwise accumulate one
        # orphaned full-scope section per baseline edit: keep only the
        # most recently used few (the active one holds the top stamp)
        if len(self._sections) > MAX_SECTIONS:
            by_age = sorted(self._sections,
                            key=lambda s: self._sections[s].get("stamp", 0))
            for sig in by_age[:len(self._sections) - MAX_SECTIONS]:
                del self._sections[sig]
                self._dirty = True
        if not self._dirty:
            return
        payload = {"version": LINT_SOURCE_VERSION,
                   "sections": self._sections}
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
