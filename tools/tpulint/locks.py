r"""tpulint concurrency & resource-lifecycle interpreter (v4).

PRs 13-15 tripled the threaded, lock-holding, resource-owning surface of
the serving stack, and the failure shapes that surface grows are exactly
the ones a file-local pass cannot see: a lock-order inversion between
``DecodeEngine._cv`` and a ``TenantBreaker`` lock two call frames away, a
``fetch_host()`` stalling a whole tick because a helper runs under a
condition variable, an error path that returns without ``free()``-ing the
pages it reserved. This module is the whole-program layer those hazards
live in — the concurrency analogue of the v3 shape interpreter, built on
the same PR-10 :class:`~tools.tpulint.graph.ProjectGraph` and memoized
per graph the same way (:func:`analyze`), so the four passes riding it
share one interpretation per lint scope.

What it computes
----------------

**Lock identities.** Every ``with self._lock:`` / ``.acquire()`` site is
resolved to a per-class identity (``DecodeEngine._cv``, ``Tenant._lock``,
``slo._ENGINE_LOCK`` for module globals). Resolution goes beyond the
call graph's own symbol table with a light type-inference layer:
``self.X = ClassName(...)`` attribute construction, annotated parameters
(``tenant: Tenant``, string annotations included), annotated dataclass
fields, and ``@property`` access (``tenant.breaker.state`` resolves to
the property method, whose lock acquisition then counts). Two runtime
locks of the same class on *different instances* share one identity —
the analysis tracks ordering between lock *classes*, so a self-edge is
never reported (``t1._lock`` then ``t2._lock`` is legal).

**The lock-acquisition graph.** An edge ``A -> B`` is added when lock B
is taken while A is lexically held — directly (nested ``with``) or
through a call that *transitively* acquires B, via call-edge propagation
bounded by :data:`~tools.tpulint.graph.DEFAULT_DEPTH`. Callback
references passed as arguments (``self._wfq.pop(self._admit_guard)``)
count as may-be-invoked, because the weighted-fair pick really does run
the guard under the engine CV. A cycle in this graph across any two
classes is a *static deadlock*: two threads acquiring in opposite orders
need only interleave once (lock-order-cycle pass — the finding carries
both witness paths).

**The held-lock context lattice.** The dual of v2's traced/thread
contexts: each function's entry set of possibly-held locks, seeded at
call sites inside ``with`` blocks and closed over call edges. It powers
the blocking-under-lock pass (a ``fetch_host`` / jit dispatch /
``queue.get(timeout=None)`` / ``Thread.join`` / ``time.sleep`` reachable
with a lock held serializes every waiter — the tick-stall shape the
flight recorder only sees post-mortem) and the cv-protocol pass's
"notify without the CV's lock held" check.

**Resource protocols.** :data:`PROTOCOLS` declares the repo's paired
acquire/release disciplines — KV pages (``reserve``/``admit_prefix`` vs
``free``, with the PR-14 CoW refcounts), tenant page budgets
(``charge_pages``/``release_pages``), token buckets
(``take_tokens``/``refund_tokens``), breaker probe leases (``allow()``
vs ``on_success``/``on_failure``), decode slots and the flight-recorder
ring (declared for documentation; their ownership is engine-internal).
The resource-lifecycle pass runs a path-sensitive paired checker over
each function: an acquire that can leak through an exception edge or an
early return — no ``finally``, no owner transfer — is flagged. Transfer
follows the ``donation_prep`` idiom from the use-after-donate pass: a
*consuming call is the sanctioned last touch*. Recognized transfers:
declared transfer tails (the fleet/disagg PRs register page-export
hand-offs here as first-class), a store into a ``self`` container
(``self._slots[slot] = req`` — ownership moves to the object), and
**caller protection** — every resolved call site of the leaking
function sits in a ``try`` whose handler/finally transitively releases
the protocol (the ``_admit`` catch-all that evicts-then-frees protects
``_prefill``). Guard polarity is modeled: ``if not take_tokens(): return``
acquires only *after* the guard; ``if take_tokens():`` holds only inside
the body. Protocol implementation files audit their own internals and
are exempt, like use-after-donate exempts ``fastpath/fused.py``.

Pure stdlib ``ast`` — no JAX import, no device work, and the same
deliberate conservatism as the rest of the whole-program layer: an
unresolvable receiver contributes nothing, so no context spreads through
a speculative edge. The runtime twin of the static lifecycle story is
``MXNET_KVCACHE_AUDIT=1`` (``PagedKVCache.audit_check``), which re-proves
the refcount invariant every engine tick.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import dotted_name
from .graph import ProjectGraph, ClassInfo, FuncInfo, _own_nodes

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: Attribute/name tokens that mean "this object is a lock" — shared with
#: the v2 races pass, plus semaphores.
_LOCKISH = ("lock", "mutex", "cond", "_cv", "_mu", "sem")
#: The subset that means "condition variable" (wait/notify protocol).
_CVISH = ("cond", "cv")
#: Predicate names through which a shutdown can wake an untimed wait.
_SHUTDOWNISH = ("closed", "shutdown", "stop", "running", "done", "exit",
                "quit", "alive", "dead", "drain")

#: Device->host syncs and unbounded waits that must not run under a lock.
_BLOCKING_CALL_TAILS = {
    "fetch_host": "`fetch_host()` (device->host transfer)",
    "device_get": "`device_get()` (device->host transfer)",
    "sleep": "`time.sleep()`",
    "jit_call": "jit dispatch (`telemetry.jit_call`)",
}
_BLOCKING_METHOD_TAILS = {
    "asnumpy": "`.asnumpy()` (device->host transfer)",
    "item": "`.item()` (device->host transfer)",
    "tolist": "`.tolist()` (device->host transfer)",
    "block_until_ready": "`.block_until_ready()`",
    "wait_to_read": "`.wait_to_read()`",
}
#: `.join()` is blocking only on thread-ish receivers (str.join is not).
_THREADISH = ("thread", "worker", "proc")
#: `.get()` with no timeout is blocking only on queue-ish receivers.
_QUEUEISH = ("queue", "_q")


def _lockish(name: Optional[str]) -> bool:
    low = (name or "").lower()
    return any(t in low for t in _LOCKISH)


def _cvish(name: Optional[str]) -> bool:
    low = (name or "").lower()
    return any(t in low for t in _CVISH)


# ---------------------------------------------------------------------------
# Resource protocols
# ---------------------------------------------------------------------------

class Protocol:
    """One paired acquire/release resource discipline.

    ``receiver_tokens`` gate the tail-name match to receivers that look
    like the owning object (``self._cache.free()`` matches the KV
    protocol, ``pool.free()`` does not). ``transfer_tails`` are the
    sanctioned consuming last touches — the extension point where the
    fleet/disagg PRs (ROADMAP 2b/4) register page-export hand-offs as
    first-class transfers instead of leaks. ``impl_files`` audit their
    own internals and are exempt from the checker.
    """

    __slots__ = ("name", "what", "acquire_tails", "release_tails",
                 "transfer_tails", "receiver_tokens", "impl_files")

    def __init__(self, name: str, what: str,
                 acquire_tails: Tuple[str, ...],
                 release_tails: Tuple[str, ...],
                 transfer_tails: Tuple[str, ...],
                 receiver_tokens: Tuple[str, ...],
                 impl_files: Tuple[str, ...]):
        self.name = name
        self.what = what
        self.acquire_tails = acquire_tails
        self.release_tails = release_tails
        self.transfer_tails = transfer_tails
        self.receiver_tokens = receiver_tokens
        self.impl_files = impl_files


PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol("kv-page", "KV cache pages (CoW-refcounted)",
             acquire_tails=("reserve", "admit_prefix"),
             release_tails=("free", "reset_pools"),
             # fleet/disagg page-export hand-offs register here
             transfer_tails=("export_pages", "import_pages"),
             receiver_tokens=("cache", "kv"),
             impl_files=("mxnet_tpu/serving/kvcache.py",)),
    Protocol("page-budget", "tenant page-budget charge",
             acquire_tails=("charge_pages",),
             release_tails=("release_pages",),
             transfer_tails=(),
             receiver_tokens=("tenant",),
             impl_files=("mxnet_tpu/serving/tenancy.py",)),
    Protocol("token-bucket", "tenant token-bucket charge",
             acquire_tails=("take_tokens",),
             release_tails=("refund_tokens",),
             transfer_tails=(),
             receiver_tokens=("tenant",),
             impl_files=("mxnet_tpu/serving/tenancy.py",)),
    Protocol("probe-lease", "breaker half-open probe lease",
             acquire_tails=("allow",),
             release_tails=("on_success", "on_failure"),
             transfer_tails=(),
             receiver_tokens=("breaker",),
             impl_files=("mxnet_tpu/serving/tenancy.py",)),
    # Declared for the protocol table (docs/resilience.md) but not
    # checkable by paired call tails: decode slots are owned through
    # `self._slots[i] = req` stores (the store IS the transfer) and the
    # flight-recorder ring is an append-only atomic deque (no release).
    Protocol("decode-slot", "decode engine slot",
             acquire_tails=(), release_tails=("_release_slot",),
             transfer_tails=(), receiver_tokens=(),
             impl_files=("mxnet_tpu/serving/decode.py",)),
    Protocol("flightrec-ring", "flight-recorder ring slot",
             acquire_tails=(), release_tails=(),
             transfer_tails=(), receiver_tokens=(),
             impl_files=("mxnet_tpu/telemetry/flightrec.py",)),
    Protocol("replica-lease", "fleet replica routing lease",
             acquire_tails=("acquire_lease",),
             release_tails=("release_lease",),
             # a re-route moves the lease WITH the request to the next
             # replica: a consuming last touch, not a leak
             transfer_tails=("transfer_lease",),
             receiver_tokens=("replica", "rep"),
             impl_files=("mxnet_tpu/serving/fleet.py",)),
)


# ---------------------------------------------------------------------------
# Findings (thin records the four passes turn into core.Finding objects)
# ---------------------------------------------------------------------------

class Rec:
    """One reportable site: an ast node (for the line) + a message that
    is stable under refactors (no line numbers, no full chains — the
    baseline keys embed the message)."""

    __slots__ = ("node", "_msg")

    def __init__(self, node: ast.AST, msg: str):
        self.node = node
        self._msg = msg

    def message(self) -> str:
        return self._msg


def _fname(info: FuncInfo) -> str:
    return info.name if info.cls is None else "%s.%s" % (info.cls, info.name)


def _is_property(fn_node: ast.AST) -> bool:
    for dec in getattr(fn_node, "decorator_list", ()):
        d = dotted_name(dec) or ""
        if d.rsplit(".", 1)[-1] in ("property", "cached_property"):
            return True
    return False


def _ann_class_name(ann: Optional[ast.AST]) -> Optional[str]:
    """The class name an annotation points at: ``Tenant``,
    ``"_DecodeRequest"`` (string form), ``Optional[Tenant]``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.rsplit(".", 1)[-1].strip("'\" ")
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value) or ""
        if base.rsplit(".", 1)[-1] in ("Optional", "Final", "ClassVar"):
            return _ann_class_name(ann.slice)
        return None
    d = dotted_name(ann)
    return d.rsplit(".", 1)[-1] if d else None


class LockAnalysis:
    """One whole-program concurrency/lifecycle interpretation. Results
    are per-relpath lists of :class:`Rec`, consumed by the four thin
    passes (lock-order-cycle, blocking-under-lock, cv-protocol,
    resource-lifecycle)."""

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        self.cycle_findings: Dict[str, List[Rec]] = {}
        self.blocking_findings: Dict[str, List[Rec]] = {}
        self.cv_findings: Dict[str, List[Rec]] = {}
        self.lifecycle_findings: Dict[str, List[Rec]] = {}

        # type layer
        self._attr_types: Dict[Tuple[str, str], str] = {}   # (cls, attr) -> cls
        self._fn_env: Dict[ast.AST, Dict[str, str]] = {}    # name -> cls
        # extended call resolution
        self._call_targets: Dict[ast.AST, List[FuncInfo]] = {}
        self._cb_targets: Dict[ast.AST, List[FuncInfo]] = {}
        self._prop_targets: Dict[ast.AST, FuncInfo] = {}
        self._callers: Dict[ast.AST, List[Tuple[FuncInfo, ast.AST]]] = {}
        # per-function facts from the lexical walk
        self._direct_acquires: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self._direct_blocks: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self._calls_held: Dict[ast.AST, List[Tuple[ast.AST, Tuple[str, ...]]]] = {}
        self._with_edges: List[Tuple[str, str, ast.AST, FuncInfo]] = []
        # propagated summaries
        self._may_acquire: Dict[ast.AST, Dict[str, Optional[FuncInfo]]] = {}
        self._may_block: Dict[ast.AST, Dict[str, Optional[FuncInfo]]] = {}
        self._may_release: Dict[ast.AST, Set[str]] = {}
        self._entry_held: Dict[ast.AST, Set[str]] = {}
        # acquisition graph: (src, dst) -> (witness node, holder FuncInfo,
        #                                   description of how dst is taken)
        self.lock_edges: Dict[Tuple[str, str], Tuple[ast.AST, FuncInfo, str]] = {}

        self._funcs = sorted(graph.funcs.values(), key=lambda i: i.qname)
        self._collect_attr_types()
        self._resolve_calls()
        self._walk_all()
        self._propagate_summaries()
        self._build_call_edges()
        self._propagate_entry_held()
        self._find_cycles()
        self._find_blocking()
        self._find_cv()
        self._find_lifecycle()

    # -- type layer ---------------------------------------------------------

    def _cinfo(self, cls_name: Optional[str],
               module: Optional[str] = None) -> Optional[ClassInfo]:
        if not cls_name:
            return None
        cands = self.graph.classes_by_name.get(cls_name, ())
        if not cands:
            return None
        if module:
            for c in cands:
                if c.module == module:
                    return c
        return cands[0]

    def _collect_attr_types(self) -> None:
        """``(class, attr) -> class`` from constructor stores
        (``self.X = ClassName(...)``), annotated-parameter stores
        (``self.X = param`` with ``param: Cls``) and annotated class
        fields (dataclass rows)."""
        for cands in self.graph.classes_by_name.values():
            for cinfo in cands:
                for stmt in cinfo.node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        cname = _ann_class_name(stmt.annotation)
                        if self._cinfo(cname) is not None:
                            self._attr_types[(cinfo.name, stmt.target.id)] \
                                = cname
                for m in cinfo.methods.values():
                    params = self._param_anns(m.node)
                    for node in _own_nodes(m.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        for tgt in node.targets:
                            if not (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                continue
                            cname = None
                            if isinstance(node.value, ast.Call):
                                cname = self._ctor_class(node.value, m)
                            elif isinstance(node.value, ast.Name):
                                cname = params.get(node.value.id)
                            if cname and self._cinfo(cname) is not None:
                                self._attr_types.setdefault(
                                    (cinfo.name, tgt.attr), cname)

    @staticmethod
    def _param_anns(fn_node: ast.AST) -> Dict[str, str]:
        out: Dict[str, str] = {}
        args = getattr(fn_node, "args", None)
        if args is None:
            return out
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            cname = _ann_class_name(a.annotation)
            if cname:
                out[a.arg] = cname
        return out

    def _ctor_class(self, call: ast.Call, info: FuncInfo) -> Optional[str]:
        d = dotted_name(call.func)
        if not d:
            return None
        tail = d.rsplit(".", 1)[-1]
        return tail if tail in self.graph.classes_by_name else None

    def _env_of(self, info: FuncInfo) -> Dict[str, str]:
        env = self._fn_env.get(info.node)
        if env is None:
            env = dict(self._param_anns(info.node))
            for node in _own_nodes(info.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    cname = self._ctor_class(node.value, info)
                    if cname:
                        env.setdefault(node.targets[0].id, cname)
            self._fn_env[info.node] = env
        return env

    def _class_of_expr(self, expr: ast.AST, info: FuncInfo) -> Optional[str]:
        """The class NAME of an expression's value, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls"):
                return info.cls
            return self._env_of(info).get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._class_of_expr(expr.value, info)
            if base is None:
                return None
            return self._attr_types.get((base, expr.attr))
        if isinstance(expr, ast.Call):
            return self._ctor_class(expr, info)
        return None

    # -- extended call resolution -------------------------------------------

    def _resolve_calls(self) -> None:
        g = self.graph
        for info in self._funcs:
            minfo = g.modules.get(info.module)
            if minfo is None:
                continue
            fstack = g._enclosing_stack(info.node)
            for node in _own_nodes(info.node):
                if isinstance(node, ast.Call):
                    targets = list(g._resolve_ref(minfo, info.cls, fstack,
                                                  node.func, as_call=True))
                    if not targets and isinstance(node.func, ast.Attribute):
                        cname = self._class_of_expr(node.func.value, info)
                        cinfo = self._cinfo(cname, info.module)
                        if cinfo is not None:
                            m = g._method_of(cinfo, node.func.attr)
                            if m is not None:
                                targets = [m]
                    if targets:
                        self._call_targets[node] = targets
                    # callback-reference arguments: a method handed to a
                    # call may be invoked by it (the weighted-fair pick
                    # runs `_admit_guard` under the engine CV)
                    cbs: List[FuncInfo] = []
                    for arg in list(node.args) \
                            + [kw.value for kw in node.keywords]:
                        if isinstance(arg, (ast.Name, ast.Attribute)):
                            cbs.extend(g._resolve_ref(minfo, info.cls, fstack,
                                                      arg, as_call=False))
                    if cbs:
                        self._cb_targets[node] = cbs
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    parent = getattr(node, "tpulint_parent", None)
                    if isinstance(parent, ast.Call) and parent.func is node:
                        continue  # call receiver, handled above
                    cname = self._class_of_expr(node.value, info)
                    cinfo = self._cinfo(cname, info.module)
                    if cinfo is not None:
                        m = g._method_of(cinfo, node.attr)
                        if m is not None and _is_property(m.node):
                            self._prop_targets[node] = m
        # reverse map for caller-protection analysis
        for info in self._funcs:
            for node in _own_nodes(info.node):
                for t in self._targets_at(node):
                    self._callers.setdefault(t.node, []).append((info, node))

    def _targets_at(self, node: ast.AST) -> List[FuncInfo]:
        out = list(self._call_targets.get(node, ()))
        out.extend(self._cb_targets.get(node, ()))
        prop = self._prop_targets.get(node)
        if prop is not None:
            out.append(prop)
        return out

    # -- lock identity ------------------------------------------------------

    def _lock_id(self, dotted: str, info: FuncInfo) -> str:
        parts = dotted.split(".")
        if parts[0] in ("self", "cls") and info.cls:
            if len(parts) == 2:
                return "%s.%s" % (info.cls, parts[1])
            # self.a.b -> type of self.a
            base: Optional[str] = info.cls
            for attr in parts[1:-1]:
                base = self._attr_types.get((base, attr)) if base else None
            if base:
                return "%s.%s" % (base, parts[-1])
        elif len(parts) >= 2:
            base = self._class_of_expr_path(parts[:-1], info)
            if base:
                return "%s.%s" % (base, parts[-1])
        # module-scoped fallback: `with _ENGINE_LOCK:` / unresolved recv
        return "%s.%s" % (info.module.rsplit(".", 1)[-1], dotted)

    def _class_of_expr_path(self, parts: Sequence[str],
                            info: FuncInfo) -> Optional[str]:
        base = self._env_of(info).get(parts[0])
        for attr in parts[1:]:
            if base is None:
                return None
            base = self._attr_types.get((base, attr))
        return base

    def _with_lock_ids(self, node: ast.AST,
                       info: FuncInfo) -> List[Tuple[str, str]]:
        """``(lock_id, dotted_text)`` for each lockish item of a With."""
        out = []
        for item in node.items:
            d = dotted_name(item.context_expr)
            if d and _lockish(d.rsplit(".", 1)[-1]):
                out.append((self._lock_id(d, info), d))
        return out

    # -- lexical walk: direct acquires, blocks, calls-under-lock ------------

    def _walk_all(self) -> None:
        for info in self._funcs:
            acquires: Dict[str, ast.AST] = {}
            blocks: Dict[str, ast.AST] = {}
            calls: List[Tuple[ast.AST, Tuple[str, ...]]] = []

            def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    ids = self._with_lock_ids(node, info)
                    for lid, _d in ids:
                        acquires.setdefault(lid, node)
                        for h in held:
                            if h != lid:
                                self._add_edge(h, lid, node, info,
                                               "`with` block")
                    inner = held + tuple(lid for lid, _d in ids
                                         if lid not in held)
                    for item in node.items:
                        visit(item.context_expr, held)
                    for stmt in node.body:
                        visit(stmt, inner)
                    return
                if isinstance(node, ast.Call):
                    desc = self._blocking_desc(node, info)
                    if desc is not None:
                        blocks.setdefault(desc, node)
                    tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                    if tail == "acquire" and isinstance(node.func,
                                                       ast.Attribute):
                        recv = dotted_name(node.func.value)
                        if recv and _lockish(recv.rsplit(".", 1)[-1]):
                            lid = self._lock_id(recv, info)
                            acquires.setdefault(lid, node)
                            for h in held:
                                if h != lid:
                                    self._add_edge(h, lid, node, info,
                                                   "`.acquire()`")
                    if held and (self._targets_at(node)
                                 or desc is not None):
                        calls.append((node, held))
                elif isinstance(node, ast.Attribute) \
                        and node in self._prop_targets and held:
                    calls.append((node, held))
                visit_children(node, held)

            def visit_children(node: ast.AST, held: Tuple[str, ...]) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    visit(child, held)

            body = info.node.body if isinstance(info.node, _FUNC_DEFS) \
                else [info.node.body]
            for stmt in body:
                visit(stmt, ())
            if acquires:
                self._direct_acquires[info.node] = acquires
            if blocks:
                self._direct_blocks[info.node] = blocks
            if calls:
                self._calls_held[info.node] = calls

    def _blocking_desc(self, node: ast.Call,
                       info: FuncInfo) -> Optional[str]:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_METHOD_TAILS:
            return _BLOCKING_METHOD_TAILS[node.func.attr]
        d = dotted_name(node.func) or ""
        tail = d.rsplit(".", 1)[-1]
        if tail in _BLOCKING_CALL_TAILS:
            # `sleep` must be time.sleep or a bare sleep, not e.g.
            # `backoff.sleep` helpers with their own discipline
            if tail == "sleep" and "." in d and not d.startswith("time."):
                return None
            return _BLOCKING_CALL_TAILS[tail]
        if isinstance(node.func, ast.Attribute):
            recv = (dotted_name(node.func.value) or "").rsplit(".", 1)[-1]
            low = recv.lower()
            if tail == "join" and any(t in low for t in _THREADISH):
                return "`.join()` on a thread"
            if tail == "get" and (any(t in low for t in _QUEUEISH)
                                  or low == "q"):
                timed = any(kw.arg == "timeout" for kw in node.keywords) \
                    or len(node.args) >= 2
                if not timed:
                    return "`queue.get()` with no timeout"
        # dispatch of a directly jit-wrapped project function
        for t in self._call_targets.get(node, ()):
            tup = self.graph._traced.get(t.node)
            if tup is not None and tup[1] is None and tup[2] == 0:
                return "jit dispatch (traced `%s`)" % _fname(t)
        return None

    # -- propagation --------------------------------------------------------

    def _propagate_summaries(self) -> None:
        """Bottom-up may-acquire / may-block / may-release closure over
        call edges, iterated to the graph's depth bound. ``via`` records
        the first callee that leads to the fact, for witness chains."""
        callees: Dict[ast.AST, List[FuncInfo]] = {}
        for info in self._funcs:
            outs: List[FuncInfo] = []
            seen: Set[ast.AST] = set()
            for node in _own_nodes(info.node):
                for t in self._targets_at(node):
                    if t.node not in seen:
                        seen.add(t.node)
                        outs.append(t)
            callees[info.node] = outs

        for info in self._funcs:
            self._may_acquire[info.node] = {
                lid: None for lid in self._direct_acquires.get(info.node, ())}
            self._may_block[info.node] = {
                d: None for d in self._direct_blocks.get(info.node, ())}
            rel: Set[str] = set()
            for node in _own_nodes(info.node):
                if isinstance(node, ast.Call):
                    for proto in PROTOCOLS:
                        if self._proto_call(node, proto, "release") \
                                or self._proto_call(node, proto, "transfer"):
                            rel.add(proto.name)
            self._may_release[info.node] = rel

        for _round in range(self.graph.depth):
            changed = False
            for info in self._funcs:
                acq = self._may_acquire[info.node]
                blk = self._may_block[info.node]
                rel = self._may_release[info.node]
                for callee in callees[info.node]:
                    for lid in self._may_acquire.get(callee.node, ()):
                        if lid not in acq:
                            acq[lid] = callee
                            changed = True
                    for d in self._may_block.get(callee.node, ()):
                        if d not in blk:
                            blk[d] = callee
                            changed = True
                    new_rel = self._may_release.get(callee.node, set()) - rel
                    if new_rel:
                        rel |= new_rel
                        changed = True
            if not changed:
                break

    def _chain(self, start: FuncInfo, key: str,
               table: Dict[ast.AST, Dict[str, Optional[FuncInfo]]]
               ) -> List[str]:
        names = [_fname(start)]
        cur = start
        for _ in range(self.graph.depth):
            via = table.get(cur.node, {}).get(key)
            if via is None:
                break
            names.append(_fname(via))
            cur = via
        return names

    def _build_call_edges(self) -> None:
        """Acquisition-graph edges through calls: target (or callback)
        transitively acquires a lock while another is lexically held."""
        for info in self._funcs:
            for node, held in self._calls_held.get(info.node, ()):
                for t in self._targets_at(node):
                    for lid, _via in sorted(
                            self._may_acquire.get(t.node, {}).items()):
                        for h in held:
                            if h != lid:
                                chain = self._chain(t, lid,
                                                    self._may_acquire)
                                self._add_edge(
                                    h, lid, node, info,
                                    "call into `%s`" % " -> ".join(chain))

    def _add_edge(self, src: str, dst: str, node: ast.AST,
                  info: FuncInfo, how: str) -> None:
        if (src, dst) not in self.lock_edges:
            self.lock_edges[(src, dst)] = (node, info, how)

    def _propagate_entry_held(self) -> None:
        """The held-lock context lattice: locks possibly held on entry to
        each function, seeded at call sites inside ``with`` blocks and
        closed over call edges (monotone; bounded by lock count)."""
        for info in self._funcs:
            self._entry_held.setdefault(info.node, set())
        for _round in range(self.graph.depth):
            changed = False
            for info in self._funcs:
                base = self._entry_held[info.node]
                for node, held in self._calls_held.get(info.node, ()):
                    out = base | set(held)
                    for t in self._targets_at(node):
                        tgt = self._entry_held.get(t.node)
                        if tgt is None:
                            continue
                        new = out - tgt
                        if new:
                            tgt |= new
                            changed = True
                # calls NOT under a lexical lock still propagate the
                # caller's entry context
                if base:
                    for node in _own_nodes(info.node):
                        for t in self._targets_at(node):
                            tgt = self._entry_held.get(t.node)
                            if tgt is not None and not base <= tgt:
                                tgt |= base
                                changed = True
            if not changed:
                break

    # -- lock-order-cycle ---------------------------------------------------

    def _find_cycles(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.lock_edges:
            adj.setdefault(a, set()).add(b)
        reported: Set[Tuple[str, ...]] = set()
        for (a, b) in sorted(self.lock_edges):
            path = self._path(adj, b, a)
            if path is None:
                continue
            cyc = tuple(sorted(set([a, b] + path)))
            if cyc in reported:
                continue
            reported.add(cyc)
            n1, i1, how1 = self.lock_edges[(a, b)]
            # `path` is the return route b -> ... -> a; the witness for
            # the reverse direction is b's first hop along it
            back = path[1] if len(path) > 1 else a
            n2, i2, how2 = self.lock_edges[(b, back)]
            msg = ("lock-order cycle: `%s` -> `%s` in `%s` (%s) but "
                   "`%s` -> `%s` in `%s` (%s) — two threads acquiring in "
                   "opposite orders deadlock on first interleave"
                   % (a, b, _fname(i1), how1,
                      b, back, _fname(i2), how2))
            self.cycle_findings.setdefault(i1.relpath, []).append(
                Rec(n1, msg))

    @staticmethod
    def _path(adj: Dict[str, Set[str]], src: str,
              dst: str) -> Optional[List[str]]:
        """Shortest src->dst node path (edge targets only), or None."""
        from collections import deque
        q = deque([(src, [])])
        seen = {src}
        while q:
            cur, path = q.popleft()
            if cur == dst:
                return path + [cur] if path or src == dst else [cur]
            for nxt in sorted(adj.get(cur, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    q.append((nxt, path + [cur]))
        return None

    # -- blocking-under-lock ------------------------------------------------

    def _find_blocking(self) -> None:
        for info in self._funcs:
            for node, held in self._calls_held.get(info.node, ()):
                lock = held[-1]  # innermost guard
                direct = self._blocking_desc(node, info) \
                    if isinstance(node, ast.Call) else None
                if direct is not None:
                    msg = ("%s runs with `%s` held — every thread waiting "
                           "on the lock stalls for the full device/host "
                           "round trip" % (direct, lock))
                    self.blocking_findings.setdefault(
                        info.relpath, []).append(Rec(node, msg))
                    continue
                for t in self._targets_at(node):
                    blk = self._may_block.get(t.node)
                    if not blk:
                        continue
                    desc = sorted(blk)[0]
                    chain = self._chain(t, desc, self._may_block)
                    msg = ("%s is reachable with `%s` held (via `%s`) — "
                           "a blocking call inside the critical section "
                           "stalls every waiter"
                           % (desc, lock, " -> ".join(chain)))
                    self.blocking_findings.setdefault(
                        info.relpath, []).append(Rec(node, msg))
                    break  # one finding per call site

    # -- cv-protocol --------------------------------------------------------

    def _find_cv(self) -> None:
        for info in self._funcs:
            entry = self._entry_held.get(info.node, set())
            for node in _own_nodes(info.node):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                recv = dotted_name(node.func.value)
                if not recv or not _cvish(recv.rsplit(".", 1)[-1]):
                    continue
                tail = node.func.attr
                if tail == "wait":
                    self._check_wait(node, recv, info)
                elif tail in ("notify", "notify_all"):
                    lid = self._lock_id(recv, info)
                    held = entry | set(self._lexical_held(node, info))
                    if lid not in held:
                        msg = ("`%s.%s()` without `%s` held — notify "
                               "requires the CV's lock; an unlocked "
                               "notify races the predicate check and "
                               "loses wakeups" % (recv, tail, lid))
                        self.cv_findings.setdefault(
                            info.relpath, []).append(Rec(node, msg))

    def _check_wait(self, node: ast.Call, recv: str, info: FuncInfo) -> None:
        loop = None
        cur = getattr(node, "tpulint_parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, ast.While):
                loop = cur
                break
            cur = getattr(cur, "tpulint_parent", None)
        if loop is None:
            msg = ("bare `%s.wait()` outside a `while`-predicate loop — "
                   "spurious wakeups and missed notifies make an "
                   "unlooped wait return with the predicate false"
                   % recv)
            self.cv_findings.setdefault(info.relpath, []).append(
                Rec(node, msg))
            return
        timed = bool(node.args) or any(kw.arg == "timeout"
                                       for kw in node.keywords)
        if timed:
            return
        toks: Set[str] = set()
        for sub in ast.walk(loop.test):
            if isinstance(sub, ast.Name):
                toks.add(sub.id.lower())
            elif isinstance(sub, ast.Attribute):
                toks.add(sub.attr.lower())
        if not any(any(s in t for s in _SHUTDOWNISH) for t in toks):
            msg = ("untimed `%s.wait()` whose loop predicate observes no "
                   "shutdown flag — close() cannot wake it and the "
                   "owning thread never joins" % recv)
            self.cv_findings.setdefault(info.relpath, []).append(
                Rec(node, msg))

    def _lexical_held(self, node: ast.AST, info: FuncInfo) -> List[str]:
        held: List[str] = []
        cur = getattr(node, "tpulint_parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                held.extend(lid for lid, _d in
                            self._with_lock_ids(cur, info))
            cur = getattr(cur, "tpulint_parent", None)
        return held

    # -- resource-lifecycle -------------------------------------------------

    def _proto_call(self, node: ast.Call, proto: Protocol,
                    kind: str) -> bool:
        tails = {"acquire": proto.acquire_tails,
                 "release": proto.release_tails,
                 "transfer": proto.transfer_tails}[kind]
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in tails:
            return False
        if not proto.receiver_tokens:
            return True
        recv = (dotted_name(node.func.value) or "").rsplit(".", 1)[-1]
        low = recv.lower()
        return any(t in low for t in proto.receiver_tokens)

    def _find_lifecycle(self) -> None:
        for info in self._funcs:
            for proto in PROTOCOLS:
                if not proto.acquire_tails:
                    continue
                if info.relpath in proto.impl_files:
                    continue
                self._check_protocol(info, proto)

    def _check_protocol(self, info: FuncInfo, proto: Protocol) -> None:
        acquires: List[ast.Call] = []
        releases: List[ast.AST] = []
        transfers: List[ast.AST] = []
        for node in _own_nodes(info.node):
            if isinstance(node, ast.Call):
                if self._proto_call(node, proto, "acquire"):
                    acquires.append(node)
                elif self._proto_call(node, proto, "release") \
                        or self._proto_call(node, proto, "transfer"):
                    releases.append(node)
                else:
                    for t in self._call_targets.get(node, ()):
                        if proto.name in self._may_release.get(t.node, ()):
                            releases.append(node)
                            break
            elif isinstance(node, ast.Assign):
                # `self._slots[slot] = req`: ownership moves into a
                # container the object releases later (the sanctioned
                # consuming last touch)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) \
                            and isinstance(tgt.value, ast.Attribute) \
                            and isinstance(tgt.value.value, ast.Name) \
                            and tgt.value.value.id == "self" \
                            and not (isinstance(node.value, ast.Constant)
                                     and node.value.value is None):
                        transfers.append(node)
        if not acquires:
            return
        for acq in acquires:
            self._check_acquire(info, proto, acq, releases, transfers)

    def _check_acquire(self, info: FuncInfo, proto: Protocol,
                       acq: ast.Call, releases: List[ast.AST],
                       transfers: List[ast.AST]) -> None:
        if self._try_protected(acq, proto):
            return
        eff = self._effective_line(acq)
        rel_after = sorted(n.lineno for n in releases + transfers
                           if n.lineno >= eff)
        if not rel_after:
            if self._caller_protected(info, proto):
                return
            msg = ("`%s.%s()` acquires %s released on no path of `%s` — "
                   "an exception or return here leaks the resource; "
                   "release in `finally` or hand off through a declared "
                   "transfer" % (self._recv_text(acq), acq.func.attr,
                                 proto.what, _fname(info)))
            self.lifecycle_findings.setdefault(info.relpath, []).append(
                Rec(acq, msg))
            return
        first_rel = rel_after[0]
        hazard = self._hazard_between(info, proto, eff, first_rel,
                                      releases, transfers)
        if hazard is None:
            return
        if self._caller_protected(info, proto):
            return
        msg = ("%s between `%s.%s()` and its release leaks %s on the "
               "exception edge in `%s` — wrap the release in `finally` "
               "or let a caller-side handler own the cleanup"
               % (hazard, self._recv_text(acq), acq.func.attr,
                  proto.what, _fname(info)))
        self.lifecycle_findings.setdefault(info.relpath, []).append(
            Rec(acq, msg))

    @staticmethod
    def _recv_text(acq: ast.Call) -> str:
        return dotted_name(acq.func.value) or "<recv>"

    @staticmethod
    def _effective_line(acq: ast.Call) -> int:
        """Guard polarity: in ``if not take(): return`` the resource is
        live only after the If; in ``if take(): ...`` only inside the
        body (approximated by the call line)."""
        cur = getattr(acq, "tpulint_parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, ast.If) and _contains(cur.test, acq):
                if isinstance(cur.test, ast.UnaryOp) \
                        and isinstance(cur.test.op, ast.Not):
                    return getattr(cur, "end_lineno", cur.lineno)
                return acq.lineno
            cur = getattr(cur, "tpulint_parent", None)
        return acq.lineno

    def _hazard_between(self, info: FuncInfo, proto: Protocol, eff: int,
                        first_rel: int, releases: List[ast.AST],
                        transfers: List[ast.AST]) -> Optional[str]:
        """A raiser/early-exit strictly between the (effective) acquire
        and the first release — the leak window."""
        rel_lines = {n.lineno for n in releases + transfers}
        for node in _own_nodes(info.node):
            line = getattr(node, "lineno", None)
            if line is None or not (eff < line < first_rel):
                continue
            if isinstance(node, (ast.Return, ast.Raise)) \
                    and not self._in_try_with_cleanup(node, proto):
                return "an early `%s`" % type(node).__name__.lower()
            if isinstance(node, ast.Call) and line not in rel_lines \
                    and not self._is_cleanup_call(node) \
                    and not self._in_try_with_cleanup(node, proto):
                return "a call that may raise"
        return None

    def _is_cleanup_call(self, node: ast.Call) -> bool:
        """Release/transfer of ANY protocol — a handler's
        evict-then-free sequence is cleanup, not a new hazard."""
        for p in PROTOCOLS:
            if self._proto_call(node, p, "release") \
                    or self._proto_call(node, p, "transfer"):
                return True
        for t in self._call_targets.get(node, ()):
            if self._may_release.get(t.node):
                return True
        return False

    def _try_protected(self, node: ast.AST, proto: Protocol) -> bool:
        """Acquire inside a try whose finally/handler (transitively)
        releases the protocol."""
        cur = getattr(node, "tpulint_parent", None)
        prev = node
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, ast.Try) and self._stmt_in(cur.body, prev):
                if self._cleanup_releases(cur, proto):
                    return True
            prev = cur
            cur = getattr(cur, "tpulint_parent", None)
        return False

    _in_try_with_cleanup = _try_protected

    @staticmethod
    def _stmt_in(body: Sequence[ast.AST], node: ast.AST) -> bool:
        cur = node
        while cur is not None:
            if cur in body:
                return True
            cur = getattr(cur, "tpulint_parent", None)
        return False

    def _cleanup_releases(self, try_node: ast.Try, proto: Protocol) -> bool:
        bodies = [try_node.finalbody] + [h.body for h in try_node.handlers]
        for body in bodies:
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if self._proto_call(node, proto, "release") \
                            or self._proto_call(node, proto, "transfer"):
                        return True
                    for t in self._call_targets.get(node, ()):
                        if proto.name in self._may_release.get(t.node, ()):
                            return True
        return False

    def _caller_protected(self, info: FuncInfo, proto: Protocol) -> bool:
        """Every resolved call site of `info` sits in a try whose
        handler/finally transitively releases the protocol — the
        ``_admit`` catch-all-evict-then-free idiom."""
        sites = self._callers.get(info.node)
        if not sites:
            return False
        return all(self._try_protected(node, proto)
                   for _caller, node in sites)


def _contains(root: ast.AST, target: ast.AST) -> bool:
    for node in ast.walk(root):
        if node is target:
            return True
    return False


def analyze(graph: ProjectGraph) -> LockAnalysis:
    """The memoized entry point: one interpretation per ProjectGraph,
    shared by the four concurrency passes (the shape-engine pattern)."""
    ana = getattr(graph, "_tpulint_lock_analysis", None)
    if ana is None:
        ana = LockAnalysis(graph)
        graph._tpulint_lock_analysis = ana
    return ana
