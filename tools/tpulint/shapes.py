r"""tpulint abstract shape/sharding interpreter — the static model of the
system's hottest runtime invariant: *shapes decide compiles*.

Every jit/pallas call site compiles one executable per distinct operand
shape tuple. The framework's whole serving/training discipline (fixed
decode slots, padded bucket ladders, knob-sized pools) exists to make
that set finite and warmup-precompilable; a single data-dependent
dimension reaching a jit operand turns the steady state into a
recompile storm that the bench's runtime gauge (PR 3) only catches a
full round later — and only with a chip. This module makes the property
*statically checkable* by abstract interpretation over the PR-10
project graph (TVM/Relay's lesson, PAPERS.md: carry an abstract shape
domain through the program, decide layout/compile questions before
execution).

The dimension domain (a finite-height lattice, ⊥ below, ⊤ on top)::

        ⊤  (top)        unbounded / data-dependent: len() of host data,
         |               .shape of queue contents, python-loop accumulators
      bounded           a finite-but-unlisted set: bucket-ladder rungs
       /    \            (select_bucket, *_ladder constructors), joins of
    const   knob         distinct constants, loop indices over a knob range
       \    /
        ⊥  (unknown)    no information — NEVER reported (the pass flags
                         only positively-derived ⊤, not ignorance)

``const`` is one compile; ``knob`` (``MXNET_DECODE_SLOTS``-style
``get_env`` reads) is one compile per process; ``bounded`` is one
compile per rung — all warmup-precompilable, all clean by construction.
Only ``⊤`` predicts a steady-state recompile.

Abstract values carry a dim (int-like scalars used as dimensions), a
shape (tuple of dims), tuple/list element values, a symbolic sequence
length, and a tag (``jit`` callables, ``bounded-seq`` ladders,
``host-seq`` accumulators, ``knob-str`` raw knob reads, ``host`` queue
payloads). The interpreter evaluates each function body in source
order, propagates values interprocedurally (parameter/return/attribute
summaries joined over call sites, iterated to a bounded fixpoint over
the call graph) and records every jit dispatch site together with the
abstract shapes of its operands. Nested functions are evaluated inline
with their closure environment (the decode plane's ``attempt()``
retry-closure idiom), and ``telemetry.jit_call(site, fn, *args)`` /
``resilience.call(site, fn, *args)`` wrappers are unwrapped to the real
operands.

Pure stdlib ``ast`` — no JAX import, no device work. Deliberately
conservative: an ⊥-shaped operand never spreads into a finding,
sequential branch evaluation under-approximates joins, and resolution
failures degrade to ⊥ — with ONE deliberate escalation: ``len()`` of a
value the interpreter cannot classify is ⊤ (the "len() of host data"
rule). A python ``len()`` feeding a *dimension* is the exact storm
shape this analysis exists for, and host lists are indistinguishable
from arrays without provenance; route such sizes through
``select_bucket`` or suppress per-line where the value is provably an
array of pre-warmed shape. The analysis is memoized per :class:`ProjectGraph`, so
the three passes riding it (recompile-risk, pallas-kernel-check,
sharding-flow) share one interpretation per lint scope.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import dotted_name

#: Fixpoint bound: interprocedural summaries are iterated at most this
#: many sweeps (the lattice is finite-height, so this is a cost cap,
#: not a correctness requirement).
MAX_ROUNDS = 4

# dim kinds, in lattice order
UNKNOWN_K, CONST_K, KNOB_K, BOUNDED_K, TOP_K = \
    "unknown", "const", "knob", "bounded", "top"

_RANK = {UNKNOWN_K: 0, CONST_K: 1, KNOB_K: 1, BOUNDED_K: 2, TOP_K: 3}


class Dim:
    """One abstract dimension. Immutable; ``origin`` is a short human
    phrase naming where a non-const value came from (rides into finding
    messages — keep it line-number-free so baseline keys are stable)."""

    __slots__ = ("kind", "value", "origin")

    def __init__(self, kind: str, value: Optional[int] = None,
                 origin: str = ""):
        self.kind = kind
        self.value = value
        self.origin = origin

    # -- constructors -------------------------------------------------------
    @staticmethod
    def const(n: int) -> "Dim":
        return Dim(CONST_K, int(n))

    @staticmethod
    def knob(name: str) -> "Dim":
        return Dim(KNOB_K, None, name)

    @staticmethod
    def bounded(origin: str) -> "Dim":
        return Dim(BOUNDED_K, None, origin)

    @staticmethod
    def top(origin: str) -> "Dim":
        return Dim(TOP_K, None, origin)

    @staticmethod
    def unknown() -> "Dim":
        return _UNKNOWN_DIM

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if self.kind == CONST_K:
            return "Dim(%d)" % self.value
        return "Dim(%s%s)" % (self.kind,
                              ", %s" % self.origin if self.origin else "")


_UNKNOWN_DIM = Dim(UNKNOWN_K)


def join_dims(a: Optional[Dim], b: Optional[Dim]) -> Dim:
    """Least upper bound. ``unknown`` is ⊥ (join-identity); distinct
    constants/knobs join to ``bounded`` (a finite set of sizes — the
    bucket-ladder shape), anything with ⊤ is ⊤."""
    a = a or _UNKNOWN_DIM
    b = b or _UNKNOWN_DIM
    if a.kind == TOP_K:
        return a
    if b.kind == TOP_K:
        return b
    if a.kind == UNKNOWN_K:
        return b
    if b.kind == UNKNOWN_K:
        return a
    if a.kind == b.kind and a.value == b.value and a.origin == b.origin:
        return a
    origin = a.origin or b.origin or "joined sizes"
    return Dim.bounded(origin)


def derived(*dims: Optional[Dim]) -> Dim:
    """Result kind of arithmetic over dims (``pad_up``, ``rung - p``,
    ``n * 2``): ⊤ taints, ``unknown`` stays unknown (ignorance does not
    become evidence), else the strongest bounded-ness survives."""
    dims = tuple(d or _UNKNOWN_DIM for d in dims)
    for d in dims:
        if d.kind == TOP_K:
            return d
    if any(d.kind == UNKNOWN_K for d in dims):
        return _UNKNOWN_DIM
    for kind in (BOUNDED_K, KNOB_K):
        for d in dims:
            if d.kind == kind:
                return Dim(kind, None, d.origin)
    return Dim(BOUNDED_K, None, "derived size")  # mixed consts w/o folding


def fold_binop(op: ast.AST, a: Dim, b: Dim) -> Dim:
    """Constant-fold ``a op b`` when both are consts, else :func:`derived`."""
    if a.kind == CONST_K and b.kind == CONST_K:
        try:
            if isinstance(op, ast.Add):
                return Dim.const(a.value + b.value)
            if isinstance(op, ast.Sub):
                return Dim.const(a.value - b.value)
            if isinstance(op, ast.Mult):
                return Dim.const(a.value * b.value)
            if isinstance(op, ast.FloorDiv):
                return Dim.const(a.value // b.value)
            if isinstance(op, ast.Mod):
                return Dim.const(a.value % b.value)
            if isinstance(op, ast.Pow):
                return Dim.const(a.value ** b.value)
        except (ZeroDivisionError, OverflowError, ValueError):
            return _UNKNOWN_DIM
    return derived(a, b)


class AbsValue:
    """One abstract runtime value.

    ``dim``    — the value used as an int-like dimension;
    ``shape``  — tuple of :class:`Dim` when the value is array-like;
    ``elts``   — element values of a tuple/list literal;
    ``length`` — symbolic sequence length (``[None] * knob``);
    ``tag``    — provenance marker: ``jit`` (compiled callable),
    ``bounded-seq`` (ladder), ``host-seq`` (loop accumulator),
    ``knob-str`` (raw string knob), ``host`` (queue payload — its
    ``.shape`` is data-dependent ⊤).
    """

    __slots__ = ("dim", "shape", "elts", "length", "tag")

    def __init__(self, dim: Optional[Dim] = None,
                 shape: Optional[Tuple[Dim, ...]] = None,
                 elts: Optional[Tuple["AbsValue", ...]] = None,
                 length: Optional[Dim] = None, tag: Optional[str] = None):
        self.dim = dim
        self.shape = shape
        self.elts = elts
        self.length = length
        self.tag = tag

    def top_dim(self) -> Optional[Dim]:
        """The first ⊤ dim of this value's shape, if any."""
        for d in self.shape or ():
            if d.kind == TOP_K:
                return d
        return None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        bits = []
        if self.dim is not None:
            bits.append("dim=%r" % self.dim)
        if self.shape is not None:
            bits.append("shape=%r" % (self.shape,))
        if self.tag:
            bits.append("tag=%s" % self.tag)
        return "AbsValue(%s)" % ", ".join(bits)


UNKNOWN = AbsValue()


def join_values(a: AbsValue, b: AbsValue) -> AbsValue:
    """Join two abstract values (parameter summaries over call sites).
    Structure that disagrees degrades to the weaker side; ⊤ provenance
    survives."""
    if a is UNKNOWN:
        return b
    if b is UNKNOWN:
        return a
    dim = join_dims(a.dim, b.dim) if (a.dim or b.dim) else None
    if dim is not None and dim.kind == UNKNOWN_K:
        dim = None
    shape = None
    if a.shape is not None and b.shape is not None:
        if len(a.shape) == len(b.shape):
            shape = tuple(join_dims(x, y) for x, y in zip(a.shape, b.shape))
        else:
            # rank disagreement: keep any ⊤ evidence, drop the rest
            td = next((d for d in a.shape + b.shape if d.kind == TOP_K), None)
            shape = (td,) if td is not None else None
    elif a.shape is not None or b.shape is not None:
        shape = a.shape if a.shape is not None else b.shape
    # element-wise join keeps the lattice monotone: identical tuples stay
    # intact and a ⊤-carrying element survives being joined against a
    # const one (AbsValue has no __eq__, so `!=` would be identity and
    # degrade EVERY multi-call-site summary)
    if a.elts is not None and b.elts is not None:
        elts = tuple(join_values(x, y) for x, y in zip(a.elts, b.elts)) \
            if len(a.elts) == len(b.elts) else None
    else:
        elts = a.elts if a.elts is not None else b.elts
    tag = a.tag if a.tag == b.tag else (a.tag or b.tag)
    length = join_dims(a.length, b.length) if (a.length or b.length) else None
    return AbsValue(dim=dim, shape=shape, elts=elts, length=length, tag=tag)


def shape_str(shape: Sequence[Dim]) -> str:
    """``(5, S=MXNET_DECODE_SLOTS, ⊤)`` — the message rendering."""
    out = []
    for d in shape:
        if d.kind == CONST_K:
            out.append(str(d.value))
        elif d.kind == KNOB_K:
            out.append(d.origin or "knob")
        elif d.kind == BOUNDED_K:
            out.append("{rungs}")
        elif d.kind == TOP_K:
            out.append("⊤")
        else:
            out.append("?")
    return "(%s)" % ", ".join(out)


class JitRisk:
    """One ⊤-shaped operand reaching a jit/pallas dispatch site."""

    __slots__ = ("node", "relpath", "fn_label", "operand", "shape", "origin")

    def __init__(self, node: ast.AST, relpath: str, fn_label: str,
                 operand: str, shape: Tuple[Dim, ...], origin: str):
        self.node = node
        self.relpath = relpath
        self.fn_label = fn_label
        self.operand = operand
        self.shape = shape
        self.origin = origin

    def message(self) -> str:
        return ("jit-compiled call `%s` takes operand `%s` with statically "
                "unbounded shape %s (⊤ from %s) — every distinct runtime "
                "size compiles a new executable: a predicted steady-state "
                "recompile storm. Route the size through a bucket ladder "
                "(`select_bucket`) or a MXNET_* knob so warmup can "
                "pre-compile every rung"
                % (self.fn_label, self.operand, shape_str(self.shape),
                   self.origin or "a data-dependent size"))


class DispatchSite:
    """One jit/pallas dispatch site the interpreter saw — wrapped (routed
    through ``telemetry.jit_call``, so its recompiles and sampled device
    time are attributed) or not. The unattributed-dispatch pass consumes
    the unwrapped ones; JitRisk above stays the recompile-risk view of
    the same sites."""

    __slots__ = ("node", "relpath", "fn_label", "wrapped", "via")

    def __init__(self, node: ast.AST, relpath: str, fn_label: str,
                 wrapped: bool, via: str):
        self.node = node
        self.relpath = relpath
        self.fn_label = fn_label
        self.wrapped = wrapped
        self.via = via  # "jit_call" | "resilience.call" | "direct" | "decorated"


# ---------------------------------------------------------------------------
# const-expression helpers shared with the pallas pass
# ---------------------------------------------------------------------------

def module_const_env(tree: ast.AST) -> Dict[str, AbsValue]:
    """Top-level ``NAME = <int | tuple-of-int | jax.jit(...)>`` bindings of
    a module — the ``LANES = 128`` / module-level-jit idiom."""
    env: Dict[str, AbsValue] = {}
    for node in tree.body if hasattr(tree, "body") else ():
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = node.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                and not isinstance(v.value, bool):
            env[tgt.id] = AbsValue(dim=Dim.const(v.value))
        elif isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            env[tgt.id] = AbsValue(elts=tuple(
                AbsValue(dim=Dim.const(e.value)) for e in v.elts),
                length=Dim.const(len(v.elts)))
        elif isinstance(v, ast.Call) and _is_jit_wrap(v):
            env[tgt.id] = AbsValue(tag="jit")
    # fold simple const chains (`HALF = LANES // 2`) over a few rounds
    for _ in range(3):
        changed = False
        for node in tree.body if hasattr(tree, "body") else ():
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name) or tgt.id in env:
                continue
            val = const_int(node.value, env)
            if val is not None:
                env[tgt.id] = AbsValue(dim=Dim.const(val))
                changed = True
        if not changed:
            break
    return env


def resolve_name(expr: ast.AST, fn: Optional[ast.AST]) -> ast.AST:
    """Follow a Name to its assignment inside the enclosing function —
    the ``grid_spec = pltpu.PrefetchScalarGridSpec(...)`` /
    ``out_spec = P("dp")`` idiom shared by the pallas and sharding
    passes. A name assigned MORE than once (conditional reassignment)
    stays unresolved: picking either branch's value could manufacture a
    finding about code no execution path contains — callers treat the
    returned Name as unprovable and bail."""
    if not isinstance(expr, ast.Name) or fn is None:
        return expr
    hits: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == expr.id \
                and getattr(node, "lineno", 0) <= getattr(expr, "lineno",
                                                          1 << 30):
            hits.append(node.value)
    return hits[0] if len(hits) == 1 else expr


def const_int(node: ast.AST, env: Dict[str, AbsValue],
              _depth: int = 0) -> Optional[int]:
    """Resolve an expression to a python int using ``env`` (module/local
    consts) — the pallas pass's block-shape evaluator. None = not const."""
    if _depth > 8:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        v = env.get(node.id)
        if v is not None and v.dim is not None and v.dim.kind == CONST_K:
            return v.dim.value
        return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = const_int(node.operand, env, _depth + 1)
        return -inner if inner is not None else None
    if isinstance(node, ast.BinOp):
        lo = const_int(node.left, env, _depth + 1)
        ro = const_int(node.right, env, _depth + 1)
        if lo is None or ro is None:
            return None
        d = fold_binop(node.op, Dim.const(lo), Dim.const(ro))
        return d.value if d.kind == CONST_K else None
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func) or ""
        tail = fname.rsplit(".", 1)[-1]
        vals = [const_int(a, env, _depth + 1) for a in node.args]
        if tail == "len" and len(node.args) == 1:
            if isinstance(node.args[0], (ast.Tuple, ast.List)):
                return len(node.args[0].elts)
            v = env.get(node.args[0].id) \
                if isinstance(node.args[0], ast.Name) else None
            if v is not None and v.length is not None \
                    and v.length.kind == CONST_K:
                return v.length.value
            return None
        if tail in ("min", "max") and vals and all(v is not None
                                                   for v in vals):
            return min(vals) if tail == "min" else max(vals)
    return None


# -- jit wrap detection (value level, complements core.jit_functions) -------

_JIT_WRAP_TAILS = {"jit", "pjit", "filter_jit", "pallas_call"}
_JIT_CALL_WRAPPERS = {"jit_call"}          # telemetry.jit_call(site, fn, *a)
_RESILIENCE_CALL = {"call"}                # resilience.call(site, fn, *a)

_NP_FACTORY = {"zeros", "ones", "empty", "full"}
_NP_LIKE = {"zeros_like", "ones_like", "empty_like", "full_like"}
_NP_PASSTHRU = {"asarray", "ascontiguousarray", "copy", "astype",
                "asanyarray"}
_NP_COLLECT = {"stack", "array", "vstack", "column_stack"}
_SEQ_PASSTHRU = {"sorted", "tuple", "list", "reversed", "set"}
_LADDER_CALLS = {"select_bucket"}
_SEQ_APPEND = {"append", "extend", "insert", "add", "appendleft"}
_QUEUE_GET = {"get", "get_nowait", "popleft", "pop"}
_DIM_FOLD = {"min", "max", "abs", "int", "round"}


def _is_jit_wrap(call: ast.Call) -> bool:
    fname = dotted_name(call.func) or ""
    tail = fname.rsplit(".", 1)[-1]
    if tail in _JIT_WRAP_TAILS:
        return True
    if tail in ("partial",) and call.args:
        inner = dotted_name(call.args[0]) or ""
        return inner.rsplit(".", 1)[-1] in _JIT_WRAP_TAILS
    return False


def _queueish(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in ("queue", "_q", "deque", "inbox", "pending"))


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

class ShapeAnalysis:
    """Whole-program result: jit-dispatch risks per file, plus the
    per-module const environments the interpreter seeds each function
    with. (The file-local pallas pass computes its own per-file const
    env via :func:`module_const_env` — it must work without a project
    graph, e.g. under ``--select pallas-kernel-check``.)"""

    def __init__(self, graph):
        self.graph = graph
        self.jit_risks: Dict[str, List[JitRisk]] = {}
        self.dispatch_sites: Dict[str, List[DispatchSite]] = {}
        self.module_envs: Dict[str, Dict[str, AbsValue]] = {}
        self._param_summaries: Dict[object, Dict[str, AbsValue]] = {}
        self._return_summaries: Dict[object, AbsValue] = {}
        self._attr_tables: Dict[Tuple[str, str], Dict[str, AbsValue]] = {}
        self._jitted_defs: Set[ast.AST] = set()
        self._risks_by_fn: Dict[object, List[JitRisk]] = {}
        self._sites_by_fn: Dict[object, List[DispatchSite]] = {}
        self._run()

    # -- summaries ----------------------------------------------------------

    def _join_param(self, info, name: str, value: AbsValue) -> bool:
        summ = self._param_summaries.setdefault(info, {})
        old = summ.get(name, UNKNOWN)
        new = join_values(old, value)
        if _widened(old, new):
            summ[name] = new
            return True
        return False

    def _join_return(self, info, value: AbsValue) -> bool:
        old = self._return_summaries.get(info, UNKNOWN)
        new = join_values(old, value)
        if _widened(old, new):
            self._return_summaries[info] = new
            return True
        return False

    def _attr_table(self, module: str, cls: Optional[str]
                    ) -> Dict[str, AbsValue]:
        return self._attr_tables.setdefault((module, cls or ""), {})

    def _join_attr(self, module: str, cls: Optional[str], name: str,
                   value: AbsValue) -> bool:
        table = self._attr_table(module, cls)
        old = table.get(name, UNKNOWN)
        new = join_values(old, value)
        if _widened(old, new):
            table[name] = new
            return True
        return False

    def _attr_get(self, module: str, cls: Optional[str],
                  name: str) -> AbsValue:
        v = self._attr_table(module, cls).get(name)
        if v is not None:
            return v
        # by-name base-class chain (same bounded walk as graph._method_of)
        graph = self.graph
        seen: Set[str] = set()
        frontier = [cls] if cls else []
        for _ in range(6):
            nxt: List[str] = []
            for cname in frontier:
                if not cname or cname in seen:
                    continue
                seen.add(cname)
                for cinfo in graph.classes_by_name.get(cname, ()):
                    hit = self._attr_tables.get(
                        (cinfo.module, cinfo.name), {}).get(name)
                    if hit is not None:
                        return hit
                    nxt.extend(cinfo.base_names)
            frontier = nxt
            if not frontier:
                break
        return UNKNOWN

    # -- driver -------------------------------------------------------------

    def _run(self) -> None:
        graph = self.graph
        for module, minfo in graph.modules.items():
            self.module_envs[module] = module_const_env(minfo.tree)
            for node in ast.walk(minfo.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and any(_is_jit_wrap(d) if isinstance(d, ast.Call)
                                else (dotted_name(d) or "").rsplit(".", 1)[-1]
                                in _JIT_WRAP_TAILS
                                for d in node.decorator_list):
                    self._jitted_defs.add(node)

        # top-level functions/methods only: nested defs are evaluated
        # inline with their closure environment
        nested = set()
        for info in graph.funcs.values():
            stack = graph._enclosing_stack(info.node)
            if len(stack) > 1:
                nested.add(info.node)
        order = sorted((i for i in graph.funcs.values()
                        if i.node not in nested),
                       key=lambda i: (i.relpath,
                                      0 if i.name == "__init__" else 1,
                                      i.qname))
        for _round in range(MAX_ROUNDS):
            changed = False
            for info in order:
                try:
                    changed |= self._eval_function(info)
                except RecursionError:  # adversarial nesting: skip the fn
                    continue
            if not changed:
                break

        risks: Dict[str, List[JitRisk]] = {}
        for info, items in self._risks_by_fn.items():
            for r in items:
                risks.setdefault(r.relpath, []).append(r)
        for rel in risks:
            risks[rel].sort(key=lambda r: (getattr(r.node, "lineno", 0),
                                           getattr(r.node, "col_offset", 0)))
        self.jit_risks = risks

        # dispatch sites mirror the risks plumbing, deduped per location
        # (one call node can be recorded for several resolved targets)
        sites: Dict[str, List[DispatchSite]] = {}
        seen: Set[Tuple[str, int, int, bool]] = set()
        for info, sitems in self._sites_by_fn.items():
            for s in sitems:
                key = (s.relpath, getattr(s.node, "lineno", 0),
                       getattr(s.node, "col_offset", 0), s.wrapped)
                if key in seen:
                    continue
                seen.add(key)
                sites.setdefault(s.relpath, []).append(s)
        for rel in sites:
            sites[rel].sort(key=lambda s: (getattr(s.node, "lineno", 0),
                                           getattr(s.node, "col_offset", 0)))
        self.dispatch_sites = sites

    def _eval_function(self, info) -> bool:
        graph = self.graph
        env: Dict[str, AbsValue] = dict(self.module_envs.get(info.module, {}))
        node = info.node
        args = node.args
        params = [a.arg for a in getattr(args, "posonlyargs", []) +
                  args.args + args.kwonlyargs]
        summ = self._param_summaries.get(info, {})
        for p in params:
            env[p] = summ.get(p, UNKNOWN)
        ev = _FuncEval(self, info, env)
        body = node.body if isinstance(node.body, list) else [node.body]
        ev.exec_body(body)
        self._risks_by_fn[info] = ev.risks
        self._sites_by_fn[info] = ev.sites
        return ev.changed


def _widened(old: AbsValue, new: AbsValue) -> bool:
    """Whether `new` carries information `old` did not (drives the
    fixpoint). Compares the rendered structure — cheap and total."""
    return _sig(new) != _sig(old)


def _sig(v: AbsValue):
    def dsig(d):
        return (d.kind, d.value, d.origin) if d is not None else None
    return (dsig(v.dim),
            tuple(dsig(d) for d in v.shape) if v.shape is not None else None,
            tuple(_sig(e) for e in v.elts) if v.elts is not None else None,
            dsig(v.length), v.tag)


class _FuncEval:
    """Evaluate one function body (statements in source order)."""

    def __init__(self, ana: ShapeAnalysis, info, env: Dict[str, AbsValue]):
        self.ana = ana
        self.info = info
        self.env = env
        self.graph = ana.graph
        self.minfo = ana.graph.modules.get(info.module)
        self.risks: List[JitRisk] = []
        self.sites: List[DispatchSite] = []
        self.changed = False
        #: one entry per enclosing loop: True when its trip count is
        #: bounded (iter over a literal/ladder/knob-range), False for
        #: while-loops and iteration over data of unknown extent
        self._loop_stack: List[bool] = []
        self._fstack = ana.graph._enclosing_stack(info.node)

    @property
    def _loop_depth(self) -> int:
        return len(self._loop_stack)

    # -- statements ---------------------------------------------------------

    def exec_body(self, body: Sequence[ast.AST]) -> None:
        for stmt in body:
            self.exec_stmt(stmt)

    def exec_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value)
            for tgt in stmt.targets:
                self.bind(tgt, val)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.bind(stmt.target, self.eval(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            self._aug_assign(stmt)
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.changed |= self.ana._join_return(
                    self.info, self.eval(stmt.value))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval(stmt.iter)
            self.bind(stmt.target, self._element_of(it))
            self._loop_stack.append(self._iter_bounded(it))
            try:
                self.exec_body(stmt.body)
            finally:
                self._loop_stack.pop()
            self.exec_body(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self.eval(stmt.test)
            self._loop_stack.append(False)  # trip count unknowable
            try:
                self.exec_body(stmt.body)
            finally:
                self._loop_stack.pop()
            self.exec_body(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self.eval(stmt.test)
            # sequential branch evaluation: the else-branch binding wins.
            # Under-approximate by design — a mis-join would manufacture
            # findings, a missed one only hides them.
            self.exec_body(stmt.body)
            self.exec_body(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            self.exec_body(stmt.body)
            for h in stmt.handlers:
                self.exec_body(h.body)
            self.exec_body(stmt.orelse)
            self.exec_body(stmt.finalbody)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, UNKNOWN)
            self.exec_body(stmt.body)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: evaluate inline with the CLOSURE environment —
            # the decode plane's retry-closure (`def attempt(): ...
            # jit_call(...)`) is where the real jit sites live
            self.env[stmt.name] = AbsValue(tag="localfn")
            saved = dict(self.env)
            for a in (getattr(stmt.args, "posonlyargs", [])
                      + stmt.args.args + stmt.args.kwonlyargs):
                self.env[a.arg] = UNKNOWN
            for va in (stmt.args.vararg, stmt.args.kwarg):
                if va is not None:
                    self.env[va.arg] = UNKNOWN
            try:
                self.exec_body(stmt.body)
            finally:
                self.env = saved
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.env.pop(tgt.id, None)

    def _aug_assign(self, stmt: ast.AugAssign) -> None:
        tgt = stmt.target
        val = self.eval(stmt.value)
        if isinstance(tgt, ast.Name):
            cur = self.env.get(tgt.id, UNKNOWN)
            if self._loop_depth and (cur.elts is not None
                                     or cur.tag in ("host-seq", "bounded-seq")
                                     or cur.length is not None) \
                    and isinstance(stmt.op, ast.Add):
                # `out += [row]` inside a loop: a python accumulator —
                # its length inherits the loop's bound
                self.env[tgt.id] = self._accumulator()
            elif self._loop_depth and cur.dim is not None:
                # a loop-carried scalar (`n += 1`): folding it once would
                # claim a positively-WRONG constant — the value depends
                # on the trip count, so it inherits the loop's bound
                if all(self._loop_stack):
                    self.env[tgt.id] = AbsValue(
                        dim=Dim.bounded("a bounded loop counter"))
                else:
                    self.env[tgt.id] = AbsValue(
                        dim=Dim.top("a python-loop counter"))
            elif cur.dim is not None or val.dim is not None:
                self.env[tgt.id] = AbsValue(
                    dim=fold_binop(stmt.op, cur.dim or _UNKNOWN_DIM,
                                   val.dim or _UNKNOWN_DIM))
            else:
                self.env[tgt.id] = UNKNOWN

    def bind(self, tgt: ast.AST, val: AbsValue) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val
        elif isinstance(tgt, ast.Starred):
            self.bind(tgt.value, UNKNOWN)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if val.elts is not None and len(val.elts) == len(tgt.elts):
                for t, v in zip(tgt.elts, val.elts):
                    self.bind(t, v)
            else:
                for t in tgt.elts:
                    self.bind(t, UNKNOWN)
        elif isinstance(tgt, ast.Attribute):
            base = dotted_name(tgt.value)
            if base in ("self", "cls") and self.info.cls is not None:
                self.changed |= self.ana._join_attr(
                    self.info.module, self.info.cls, tgt.attr, val)
        # Subscript stores mutate in place — shape unchanged, ignore.

    def _accumulator(self) -> AbsValue:
        """A sequence grown inside the current loop nest: its length is
        the trip count — bounded when every enclosing loop is (the
        per-rung warmup accumulate), ⊤ otherwise (the host-batch
        collate)."""
        if self._loop_stack and all(self._loop_stack):
            return AbsValue(tag="bounded-seq",
                            length=Dim.bounded("a bounded-loop accumulator"))
        return AbsValue(tag="host-seq",
                        length=Dim.top("a python-loop accumulator"))

    def _iter_bounded(self, it: AbsValue) -> bool:
        """Whether a for-loop over `it` has a bounded trip count (a
        literal, a ladder, a knob-sized range) — loop-carried counters
        inside inherit this instead of widening straight to ⊤."""
        if it.elts is not None or it.tag == "bounded-seq":
            return True
        if it.length is not None and it.length.kind in (CONST_K, KNOB_K,
                                                        BOUNDED_K):
            return True
        if it.shape is not None and it.shape \
                and it.shape[0].kind in (CONST_K, KNOB_K, BOUNDED_K):
            return True
        return False

    def _element_of(self, it: AbsValue) -> AbsValue:
        if it.elts is not None:
            out = UNKNOWN
            for e in it.elts:
                out = join_values(out, e)
            return out
        if it.tag == "bounded-seq":
            return AbsValue(dim=Dim.bounded("a bucket-ladder rung"))
        if it.tag == "host-seq":
            return UNKNOWN  # the items are data, not sizes
        if it.shape is not None and len(it.shape) >= 1:
            return AbsValue(shape=it.shape[1:]) if len(it.shape) > 1 \
                else AbsValue(dim=_UNKNOWN_DIM)
        if it.length is not None:
            # range(n)-like: the loop index is one of finitely many values
            # per process when n is const/knob/bounded — warmup covers it
            if it.length.kind == TOP_K:
                return AbsValue(dim=Dim.top(it.length.origin))
            if it.length.kind in (CONST_K, KNOB_K, BOUNDED_K):
                return AbsValue(dim=Dim.bounded("a bounded loop index"))
        return UNKNOWN

    # -- expressions --------------------------------------------------------

    def eval(self, node: ast.AST) -> AbsValue:
        try:
            return self._eval(node)
        except RecursionError:
            raise
        except Exception:  # noqa: BLE001 - a lint must not crash on odd code
            return UNKNOWN

    def _eval(self, node: ast.AST) -> AbsValue:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool) or node.value is None:
                return UNKNOWN
            if isinstance(node.value, int):
                return AbsValue(dim=Dim.const(node.value))
            return UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, (ast.Tuple, ast.List)):
            if any(isinstance(e, ast.Starred) for e in node.elts):
                return AbsValue(tag="seq")
            elts = tuple(self.eval(e) for e in node.elts)
            return AbsValue(elts=elts, length=Dim.const(len(elts)))
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand)
            if isinstance(node.op, ast.USub) and v.dim is not None \
                    and v.dim.kind == CONST_K:
                return AbsValue(dim=Dim.const(-v.dim.value))
            return v if isinstance(node.op, ast.UAdd) else UNKNOWN
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return join_values(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._eval_comp(node)
        if isinstance(node, ast.Lambda):
            return AbsValue(tag="localfn")
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.cmpop, ast.boolop)):
                    self.eval(child)
            return UNKNOWN
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        return UNKNOWN

    def _eval_attribute(self, node: ast.Attribute) -> AbsValue:
        base_name = dotted_name(node.value)
        if base_name in ("self", "cls") and self.info.cls is not None:
            return self.ana._attr_get(self.info.module, self.info.cls,
                                      node.attr)
        base = self.eval(node.value)
        if node.attr == "shape":
            if base.shape is not None:
                return AbsValue(
                    elts=tuple(AbsValue(dim=d) for d in base.shape),
                    length=Dim.const(len(base.shape)))
            if base.tag == "host":
                return AbsValue(tag="host-shape")
            return UNKNOWN
        if node.attr == "size":
            if base.shape is not None:
                return AbsValue(dim=_product(base.shape))
            if base.tag == "host":
                return AbsValue(dim=Dim.top(".size of host/queue data"))
            return UNKNOWN
        if node.attr == "T" and base.shape is not None:
            return AbsValue(shape=tuple(reversed(base.shape)))
        return UNKNOWN

    def _eval_subscript(self, node: ast.Subscript) -> AbsValue:
        base = self.eval(node.value)
        idx = node.slice
        const_idx = None
        if isinstance(idx, ast.Constant) and isinstance(idx.value, int) \
                and not isinstance(idx.value, bool):
            const_idx = idx.value
        if base.tag == "host-shape":
            return AbsValue(dim=Dim.top(".shape of host/queue data"))
        if base.elts is not None and const_idx is not None \
                and -len(base.elts) <= const_idx < len(base.elts):
            return base.elts[const_idx]
        if base.shape is not None and not isinstance(idx, ast.Slice) \
                and not isinstance(idx, ast.Tuple):
            if len(base.shape) > 1:
                return AbsValue(shape=base.shape[1:])
            return AbsValue(dim=_UNKNOWN_DIM)
        return UNKNOWN

    def _eval_binop(self, node: ast.BinOp) -> AbsValue:
        a = self.eval(node.left)
        b = self.eval(node.right)
        # tuple concat / repeat: the shape-building idiom
        if isinstance(node.op, ast.Add) and a.elts is not None \
                and b.elts is not None:
            elts = a.elts + b.elts
            return AbsValue(elts=elts, length=Dim.const(len(elts)))
        if isinstance(node.op, ast.Mult):
            for seq, n in ((a, b), (b, a)):
                if (seq.elts is not None or seq.tag == "seq") \
                        and n.dim is not None:
                    if seq.elts is not None and n.dim.kind == CONST_K \
                            and 0 <= n.dim.value <= 64:
                        elts = seq.elts * n.dim.value
                        return AbsValue(elts=elts,
                                        length=Dim.const(len(elts)))
                    base_len = seq.length or (
                        Dim.const(len(seq.elts))
                        if seq.elts is not None else _UNKNOWN_DIM)
                    return AbsValue(tag="seq",
                                    length=derived(base_len, n.dim))
        if a.dim is not None and b.dim is not None:
            return AbsValue(dim=fold_binop(node.op, a.dim, b.dim))
        if a.dim is not None or b.dim is not None:
            d = a.dim or b.dim
            other = b if a.dim is not None else a
            if other.shape is not None:
                return AbsValue(shape=other.shape)  # array op scalar
            return AbsValue(dim=derived(d, _UNKNOWN_DIM))
        # elementwise array arithmetic preserves (the known) shape
        if a.shape is not None:
            return AbsValue(shape=a.shape)
        if b.shape is not None:
            return AbsValue(shape=b.shape)
        return UNKNOWN

    def _eval_comp(self, node) -> AbsValue:
        # each generator binds ITS OWN iterator's element — the first
        # iterator only classifies the comprehension's resulting length
        it = None
        for g in node.generators:
            g_it = self.eval(g.iter)
            if it is None:
                it = g_it
            self.bind(g.target, self._element_of(g_it))
        self.eval(node.elt)
        if it.tag in ("bounded-seq", "knob-str"):
            return AbsValue(tag="bounded-seq")
        if it.tag == "host-seq":
            return AbsValue(tag="host-seq",
                            length=Dim.top("a python-loop accumulator"))
        if it.elts is not None and not any(g.ifs for g in node.generators):
            return AbsValue(tag="seq", length=Dim.const(len(it.elts)))
        if it.length is not None:
            return AbsValue(tag="seq", length=it.length)
        return AbsValue(tag="seq")

    # -- calls --------------------------------------------------------------

    def _eval_call(self, node: ast.Call) -> AbsValue:
        fname = dotted_name(node.func) or ""
        tail = fname.rsplit(".", 1)[-1]
        if not tail and isinstance(node.func, ast.Attribute):
            # chained receiver (`get_env(...).split(",")`): dotted_name
            # can't render the base Call, but the method name still
            # classifies — without this the ladder-parse idiom loses its
            # knob-str provenance and manufactures a ⊤
            tail = node.func.attr

        # mutation-style sequence growth: `out.append(x)` in a loop makes
        # `out` a host accumulator whose length is data-dependent
        if isinstance(node.func, ast.Attribute) and tail in _SEQ_APPEND:
            recv = node.func.value
            for a in node.args:
                self.eval(a)
            if self._loop_depth and isinstance(recv, ast.Name):
                cur = self.env.get(recv.id)
                if cur is not None and (cur.elts is not None
                                        or cur.tag in ("seq", "host-seq")):
                    self.env[recv.id] = self._accumulator()
            return UNKNOWN

        # queue payloads: data (and shapes) of unknowable provenance
        if isinstance(node.func, ast.Attribute) and tail in _QUEUE_GET \
                and _queueish(dotted_name(node.func.value) or ""):
            return AbsValue(tag="host")

        # jit wrapping produces a compiled callable VALUE
        if _is_jit_wrap(node):
            for a in node.args[1:]:
                self.eval(a)
            for kw in node.keywords:
                self.eval(kw.value)
            return AbsValue(tag="jit")

        args = [self.eval(a) for a in node.args]

        # dispatch *through* the telemetry/resilience wrappers:
        # jit_call("site", fn, *operands) / resilience.call("site", fn, *a)
        if (tail in _JIT_CALL_WRAPPERS
                or (tail in _RESILIENCE_CALL and "policy" not in fname)) \
                and len(node.args) >= 2:
            fn_val = args[1]
            if fn_val.tag == "jit" or self._is_jitted_ref(node.args[1]):
                # only telemetry.jit_call ATTRIBUTES the dispatch
                # (recompile accounting + sampled device time); a bare
                # resilience.call around a jitted fn retries it but
                # leaves it invisible to the perf plane
                wrapped = tail in _JIT_CALL_WRAPPERS
                self._record_jit_site(node, node.args[1], node.args[2:],
                                      args[2:], node.keywords,
                                      wrapped=wrapped,
                                      via="jit_call" if wrapped
                                      else "resilience.call")
            return UNKNOWN

        # direct call of a compiled callable: `self._step(...)`,
        # `fn(...)` with fn = jax.jit(...), `pl.pallas_call(...)(args)`
        fn_val = self.eval(node.func)
        if fn_val.tag == "jit":
            self._record_jit_site(node, node.func, node.args, args,
                                  node.keywords)
            return UNKNOWN

        # numpy/jnp shape algebra
        v = self._eval_numpy_call(node, tail, args)
        if v is not None:
            return v

        # knob reads: the int-typed read is a per-process-constant dim;
        # the raw string read feeds the ladder parse
        if tail == "get_env" and node.args:
            name = node.args[0]
            knob = name.value if isinstance(name, ast.Constant) \
                and isinstance(name.value, str) else "MXNET_*"
            is_str = any(dotted_name(a) == "str" for a in
                         list(node.args) + [kw.value for kw in
                                            node.keywords])
            if is_str:
                return AbsValue(tag="knob-str")
            return AbsValue(dim=Dim.knob(knob))
        if tail in ("split", "rsplit") and isinstance(node.func,
                                                     ast.Attribute):
            recv = self.eval(node.func.value)
            if recv.tag in ("knob-str", "bounded-seq"):
                return AbsValue(tag="bounded-seq")
            return AbsValue(tag="seq")
        if tail == "str" and len(node.args) == 1:
            return args[0]
        if tail in _LADDER_CALLS:
            return AbsValue(dim=Dim.bounded("a bucket-ladder rung"))
        if tail == "pad_to_bucket" and len(node.args) >= 2 \
                and args[1].dim is not None:
            return AbsValue(shape=(args[1].dim,))
        if ("ladder" in tail or "bucket_ladder" in tail) \
                and tail not in _LADDER_CALLS:
            return AbsValue(tag="bounded-seq")
        if tail in _SEQ_PASSTHRU and len(node.args) == 1:
            return args[0]
        if tail == "len" and len(node.args) == 1:
            return AbsValue(dim=self._len_of(args[0]))
        if tail == "range":
            if args and args[-1 if len(args) < 3 else 1].dim is not None:
                d = args[1].dim if len(args) >= 2 else args[0].dim
                return AbsValue(tag="seq", length=d)
            return AbsValue(tag="seq")
        if tail in _DIM_FOLD:
            dims = [a.dim for a in args if a.dim is not None]
            if len(dims) == len(args) and dims:
                if len(dims) == 1:
                    return AbsValue(dim=dims[0])
                if tail == "min" and any(
                        d.kind in (CONST_K, KNOB_K, BOUNDED_K)
                        for d in dims):
                    # min(len(data), CAP) CLAMPS: a finitely-capped dim
                    # takes finitely many values — the bucket-cap idiom,
                    # warmup-precompilable, never a storm
                    return AbsValue(dim=Dim.bounded("a min()-clamped size"))
                return AbsValue(dim=derived(*dims))
            return UNKNOWN

        # project-function calls: propagate arguments into the callee's
        # parameter summary, use its return summary
        return self._eval_project_call(node, args)

    def _len_of(self, v: AbsValue) -> Dim:
        if v.length is not None:
            return v.length
        if v.elts is not None:
            return Dim.const(len(v.elts))
        if v.tag == "bounded-seq":
            return Dim.bounded("a bucket ladder")
        if v.tag == "host-seq":
            return Dim.top("len() of a python-loop accumulator")
        if v.tag == "host":
            return Dim.top("len() of host/queue data")
        if v.shape is not None and v.shape:
            return v.shape[0]
        if v.tag in ("jit", "localfn", "knob-str"):
            return _UNKNOWN_DIM
        return Dim.top("len() of data of statically unknown size")

    def _eval_numpy_call(self, node: ast.Call, tail: str,
                         args: List[AbsValue]) -> Optional[AbsValue]:
        if tail in _NP_FACTORY and args:
            return AbsValue(shape=self._shape_from(args[0]))
        if tail in _NP_LIKE and args:
            return args[0]
        if tail in _NP_PASSTHRU and args:
            src = args[0]
            if src.elts is not None:
                return AbsValue(shape=(Dim.const(len(src.elts)),))
            if src.tag == "host-seq":
                return AbsValue(shape=(
                    Dim.top("an array stacked from a python-loop "
                            "accumulator"),))
            if src.shape is not None or src.dim is not None:
                return src
            return UNKNOWN
        if tail in _NP_COLLECT and args:
            src = args[0]
            if src.tag == "host-seq":
                return AbsValue(shape=(
                    Dim.top("an array stacked from a python-loop "
                            "accumulator"),))
            if src.elts is not None:
                first = src.elts[0] if src.elts else UNKNOWN
                rest = first.shape if first.shape is not None else ()
                if tail == "stack":
                    return AbsValue(shape=(Dim.const(len(src.elts)),) + rest)
                return UNKNOWN
            if src.tag == "host":
                return AbsValue(shape=(Dim.top("host/queue data"),))
            return UNKNOWN
        if tail == "concatenate" and args:
            src = args[0]
            if src.tag == "host-seq":
                return AbsValue(shape=(
                    Dim.top("an array concatenated from a python-loop "
                            "accumulator"),))
            return UNKNOWN
        if tail == "arange":
            if args and args[0].dim is not None and len(args) == 1:
                return AbsValue(shape=(args[0].dim,))
            return UNKNOWN
        if tail == "reshape":
            # x.reshape(a, b) | x.reshape((a, b)) | jnp.reshape(x, shape)
            if isinstance(node.func, ast.Attribute) and \
                    dotted_name(node.func.value) not in ("np", "jnp",
                                                         "numpy", "onp"):
                vals = args
            else:
                vals = args[1:]
            if len(vals) == 1 and vals[0].elts is not None:
                return AbsValue(shape=self._shape_from(vals[0]))
            if vals and all(v.dim is not None for v in vals):
                return AbsValue(shape=tuple(v.dim for v in vals))
            return UNKNOWN
        if tail == "ravel" and isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            if recv.shape is not None:
                return AbsValue(shape=(_product(recv.shape),))
            return UNKNOWN
        if tail == "fetch_host" and args:
            return args[0]
        return None

    def _shape_from(self, v: AbsValue) -> Tuple[Dim, ...]:
        if v.elts is not None:
            return tuple(e.dim or _UNKNOWN_DIM for e in v.elts)
        if v.dim is not None:
            return (v.dim,)
        return (_UNKNOWN_DIM,)

    def _is_jitted_ref(self, expr: ast.AST) -> bool:
        """Whether `expr` names a @jax.jit-decorated project function."""
        if not isinstance(expr, (ast.Name, ast.Attribute)):
            return False
        for target in self.graph._resolve_ref(self.minfo, self.info.cls,
                                              self._fstack, expr,
                                              as_call=False):
            if target.node in self.ana._jitted_defs:
                return True
        return False

    def _eval_project_call(self, node: ast.Call,
                           args: List[AbsValue]) -> AbsValue:
        if self.minfo is None:
            return UNKNOWN
        targets = self.graph._resolve_ref(self.minfo, self.info.cls,
                                          self._fstack, node.func,
                                          as_call=True)
        if not targets:
            # decorated-jitted function called by name: a dispatch site
            if self._is_jitted_ref(node.func):
                self._record_jit_site(node, node.func, node.args, args,
                                      node.keywords, via="decorated")
            return UNKNOWN
        result = UNKNOWN
        for target in targets:
            if target.node in self.ana._jitted_defs:
                self._record_jit_site(node, node.func, node.args, args,
                                      node.keywords, via="decorated")
            t_args = target.node.args if hasattr(target.node, "args") \
                else None
            if t_args is not None:
                params = [a.arg for a in
                          getattr(t_args, "posonlyargs", []) + t_args.args]
                offset = 1 if params and params[0] in ("self", "cls") \
                    and target.cls is not None else 0
                for i, v in enumerate(args):
                    pi = i + offset
                    if pi < len(params) and v is not UNKNOWN:
                        self.changed |= self.ana._join_param(
                            target, params[pi], v)
                for kw in node.keywords:
                    if kw.arg and kw.arg in params:
                        v = self.eval(kw.value)
                        if v is not UNKNOWN:
                            self.changed |= self.ana._join_param(
                                target, kw.arg, v)
            ret = self.ana._return_summaries.get(target)
            if ret is not None:
                result = join_values(result, ret)
        return result

    def _record_jit_site(self, call: ast.Call, fn_expr: ast.AST,
                         operand_nodes: Sequence[ast.AST],
                         operand_vals: Sequence[AbsValue],
                         keywords: Sequence[ast.keyword] = (),
                         wrapped: bool = False,
                         via: str = "direct") -> None:
        label = dotted_name(fn_expr) or "jit(...)"
        # every dispatch site is recorded (wrapped or not) for the
        # unattributed-dispatch pass; the ⊤-operand filter below only
        # gates the recompile-RISK records
        self.sites.append(DispatchSite(call, self.info.relpath, label,
                                       wrapped, via))
        pairs: List[Tuple[object, ast.AST, AbsValue]] = [
            (i, onode, oval) for i, (onode, oval)
            in enumerate(zip(operand_nodes, operand_vals))]
        # keyword operands trace exactly like positional ones — a
        # ⊤-shaped `step(x=...)` storms the same as `step(...)`
        for kw in keywords:
            if kw.arg is not None:
                pairs.append((kw.arg, kw.value, self.eval(kw.value)))
        for key, onode, oval in pairs:
            td = oval.top_dim()
            if td is None:
                continue
            name = dotted_name(onode)
            if name is None and isinstance(onode, ast.Call):
                inner = onode.args[0] if onode.args else None
                name = dotted_name(inner) if inner is not None else None
            if name is None:
                name = key if isinstance(key, str) else "operand %d" % key
            self.risks.append(JitRisk(
                call, self.info.relpath, label,
                name, oval.shape or (td,), td.origin))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def analyze(graph) -> ShapeAnalysis:
    """The (memoized) whole-program shape analysis of a project graph."""
    ana = getattr(graph, "_tpulint_shape_analysis", None)
    if ana is None:
        ana = ShapeAnalysis(graph)
        graph._tpulint_shape_analysis = ana
    return ana


def _product(shape: Sequence[Dim]) -> Dim:
    out = Dim.const(1)
    for d in shape:
        out = fold_binop(ast.Mult(), out, d) if out.kind == CONST_K \
            and d.kind == CONST_K else derived(out, d)
    return out
