"""tpulint whole-program layer: symbol table, call graph, context lattices.

The 11 file-local passes see one :class:`~tools.tpulint.core.FileContext`
at a time, so any hazard that crosses a function call is invisible to
them: a host sync buried two frames below a traced ``_leaf_step``, a
field mutated on the serving worker thread and read by the caller, a
``get_env(cache=False)`` re-read reached from inside a jit trace. This
module builds the project-wide structure those hazards live in:

- a **symbol table** per module (top-level functions, classes with their
  methods, ``import``/``from-import`` aliases, relative imports resolved
  against the package path derived from the file's repo-relative path);
- a **call graph**: for every function (top-level, method, nested def,
  lambda) the set of project functions it calls, resolved through local
  scopes, module scope, import aliases, ``self.``/``cls.``/``Class.``
  method binding (including base classes by name), and dotted
  module-attribute chains;
- two **context lattices** propagated over that graph with a bounded
  depth (:data:`DEFAULT_DEPTH` — the recursion/blow-up cutoff):

  * **traced context** — functions whose bodies run under jax tracing:
    seeded at ``jax.jit``/``pl.pallas_call`` wrap sites (including
    factory calls ``jax.jit(self._build_step(...))``, which seed the
    nested functions the factory *returns*) and at the framework's
    known kernel entry points (``_leaf_step``, ``tree_kernel``), then
    closed over call edges — tracing inlines the whole call tree;
  * **thread context** — functions that run off the main thread: seeded
    at ``threading.Thread(target=...)`` sites, ``run`` methods of
    ``threading.Thread`` subclasses (the telemetry Emitter), and
    callbacks pushed onto the host engine (``engine.push(fn)``,
    ``self._engine.push(fn)`` — the elastic async-checkpoint commit
    path), then closed over call edges.

Pure stdlib ``ast`` — no JAX import, no device work. Resolution is
deliberately *conservative*: an attribute call on an object of unknown
type resolves to nothing rather than to every same-named method in the
project, so context never spreads through a speculative edge. The cost
is under-approximation (a hazard behind a duck-typed call is missed);
the gate's job is to make the common hazard shapes impossible, not to
prove the program race-free.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import dotted_name, jit_functions

#: Propagation/search depth bound: call chains longer than this from a
#: seed are not marked (recursion and adversarial chains cut off here).
DEFAULT_DEPTH = 10

#: Functions that are traced by construction — the per-param optimizer
#: kernel every fused/graph-plane jit traces, and the shared whole-tree
#: kernel both step compilers consume.
TRACED_SEED_NAMES = ("_leaf_step", "tree_kernel")

_JIT_TAILS = {"jit", "pjit", "filter_jit"}
_PALLAS_TAILS = {"pallas_call"}
_THREAD_TAILS = {"Thread"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_ANY_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def module_name_of(relpath: str) -> str:
    """``mxnet_tpu/fastpath/fused.py`` → ``mxnet_tpu.fastpath.fused``;
    ``pkg/__init__.py`` → ``pkg``."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FuncInfo:
    """One function in the project graph (top-level, method, nested def
    or lambda)."""

    __slots__ = ("qname", "relpath", "module", "node", "name", "cls",
                 "callees", "returned_inner")

    def __init__(self, qname: str, relpath: str, module: str,
                 node: ast.AST, name: str, cls: Optional[str]):
        self.qname = qname
        self.relpath = relpath
        self.module = module
        self.node = node
        self.name = name
        self.cls = cls
        self.callees: List["FuncInfo"] = []
        self.returned_inner: List["FuncInfo"] = []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "FuncInfo(%s)" % self.qname


class ClassInfo:
    __slots__ = ("name", "qname", "module", "node", "base_names", "methods")

    def __init__(self, name: str, qname: str, module: str, node: ast.ClassDef):
        self.name = name
        self.qname = qname
        self.module = module
        self.node = node
        self.base_names: List[str] = []
        self.methods: Dict[str, FuncInfo] = {}


class _ModuleInfo:
    __slots__ = ("relpath", "module", "tree", "top", "is_pkg")

    def __init__(self, relpath: str, module: str, tree: ast.AST):
        self.relpath = relpath
        self.module = module
        self.tree = tree
        self.is_pkg = relpath.endswith("/__init__.py") \
            or relpath == "__init__.py"
        # name -> FuncInfo | ClassInfo | ("mod", module_name)
        #                  | ("sym", module_name, symbol_name)
        self.top: Dict[str, object] = {}


def _own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions — those are separate graph nodes with their own edges."""
    body = fn_node.body if isinstance(fn_node, _FUNC_DEFS) else [fn_node.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _ANY_FUNC):
                continue
            stack.append(child)


class ProjectGraph:
    """Symbol table + call graph + context lattices over a file set."""

    def __init__(self, files: Sequence[Tuple[str, ast.AST]],
                 depth: int = DEFAULT_DEPTH):
        self.depth = depth
        self.modules: Dict[str, _ModuleInfo] = {}
        self.funcs: Dict[ast.AST, FuncInfo] = {}       # def node -> info
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self._locals: Dict[Tuple[ast.AST, str], FuncInfo] = {}
        self._traced: Dict[ast.AST, Tuple[FuncInfo, Optional[FuncInfo], int]] = {}
        self._threaded: Dict[ast.AST, Tuple[FuncInfo, Optional[FuncInfo], int]] = {}

        # key on relpath only: trees don't compare, and duplicate relpaths
        # (possible through lint_sources) must not crash the sort
        ordered = sorted(files, key=lambda pair: pair[0])
        for relpath, tree in ordered:
            self._index_file(relpath, tree)
        for relpath, tree in ordered:
            self._build_edges(relpath, tree)
        traced_seeds, thread_seeds = self._collect_seeds(ordered)
        self._traced = self._propagate(traced_seeds)
        self._threaded = self._propagate(thread_seeds)

    # -- indexing -----------------------------------------------------------

    def _index_file(self, relpath: str, tree: ast.AST) -> None:
        module = module_name_of(relpath)
        minfo = _ModuleInfo(relpath, module, tree)
        self.modules[module] = minfo

        def add_func(node, name, cls, prefix):
            qname = "%s::%s" % (relpath, prefix + name)
            info = FuncInfo(qname, relpath, module, node, name, cls)
            self.funcs[node] = info
            return info

        def index_body(body, cls, prefix, owner_top):
            for node in body:
                if isinstance(node, _FUNC_DEFS):
                    info = add_func(node, node.name, cls, prefix)
                    if owner_top is not None:
                        owner_top[node.name] = info
                    self._index_nested(node, prefix + node.name + ".")
                elif isinstance(node, ast.ClassDef):
                    cinfo = ClassInfo(node.name, "%s::%s" % (relpath, node.name),
                                      module, node)
                    for base in node.bases:
                        d = dotted_name(base)
                        if d:
                            cinfo.base_names.append(d.rsplit(".", 1)[-1])
                    if owner_top is not None:
                        owner_top[node.name] = cinfo
                    self.classes_by_name.setdefault(node.name, []).append(cinfo)
                    for sub in node.body:
                        if isinstance(sub, _FUNC_DEFS):
                            m = add_func(sub, sub.name, node.name,
                                         prefix + node.name + ".")
                            cinfo.methods[sub.name] = m
                            self._index_nested(
                                sub, prefix + node.name + "." + sub.name + ".")
                elif isinstance(node, (ast.Import, ast.ImportFrom)):
                    self._index_import(minfo, node)
                elif isinstance(node, (ast.If, ast.Try)):
                    # conditionally-defined top-level symbols (compat shims)
                    for sub_body in _stmt_bodies(node):
                        index_body(sub_body, cls, prefix, owner_top)

        index_body(tree.body, None, "", minfo.top)

    def _index_nested(self, fn_node: ast.AST, prefix: str) -> None:
        """Nested defs/lambdas inside a function: graph nodes + local-scope
        bindings keyed by their *enclosing* function node."""
        minfo_mod = self.funcs[fn_node].module
        relpath = self.funcs[fn_node].relpath
        counter = [0]

        def visit(owner, body, pfx):
            for node in _iter_direct(body):
                if isinstance(node, _FUNC_DEFS):
                    qname = "%s::%s" % (relpath, pfx + node.name)
                    info = FuncInfo(qname, relpath, minfo_mod, node,
                                    node.name, self.funcs[fn_node].cls)
                    self.funcs[node] = info
                    self._locals[(owner, node.name)] = info
                    visit(node, node.body, pfx + node.name + ".")
                elif isinstance(node, ast.Lambda):
                    counter[0] += 1
                    qname = "%s::%s<lambda%d>" % (relpath, pfx, counter[0])
                    info = FuncInfo(qname, relpath, minfo_mod, node,
                                    "<lambda>", self.funcs[fn_node].cls)
                    self.funcs[node] = info
                    visit(node, [node.body], pfx)

        visit(fn_node, fn_node.body, prefix)

    def _index_import(self, minfo: _ModuleInfo, node) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    minfo.top[alias.asname] = ("mod", alias.name)
                else:
                    # `import a.b` binds `a`; the resolver walks the chain
                    minfo.top[alias.name.split(".")[0]] = \
                        ("mod", alias.name.split(".")[0])
        else:  # ImportFrom
            if node.level:
                # level=1 resolves against the module's own package: for
                # `pkg/mod.py` that strips the module name, but for a
                # package `pkg/__init__.py` the module name IS the
                # package — strip nothing; each extra level strips one
                # more package
                pkg_parts = minfo.module.split(".")
                if not minfo.is_pkg:
                    pkg_parts = pkg_parts[:-1]
                cut = node.level - 1
                base = pkg_parts[:len(pkg_parts) - cut] if cut else pkg_parts
                target = ".".join(base + ([node.module] if node.module else []))
            else:
                target = node.module or ""
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "*":
                    continue
                full = ("%s.%s" % (target, alias.name)) if target else alias.name
                if full in self.modules:
                    minfo.top[bound] = ("mod", full)
                else:
                    minfo.top[bound] = ("sym", target, alias.name)

    # -- resolution ---------------------------------------------------------

    def _resolve_in_module(self, module: str, name: str,
                           _depth: int = 0) -> Optional[object]:
        """A top-level symbol of `module`, following from-import aliases
        up to a small re-export depth."""
        minfo = self.modules.get(module)
        if minfo is None or _depth > 4:
            return None
        ent = minfo.top.get(name)
        if isinstance(ent, tuple) and ent[0] == "sym":
            sub = self._resolve_in_module(ent[1], ent[2], _depth + 1)
            return sub if sub is not None else ent
        return ent

    def _method_of(self, cinfo: ClassInfo, name: str,
                   _depth: int = 0) -> Optional[FuncInfo]:
        """Method lookup through the by-name base-class chain (same-module
        base preferred; bounded against cycles)."""
        if name in cinfo.methods:
            return cinfo.methods[name]
        if _depth >= 6:
            return None
        for base in cinfo.base_names:
            cands = self.classes_by_name.get(base, ())
            same = [c for c in cands if c.module == cinfo.module]
            for cand in same or cands:
                found = self._method_of(cand, name, _depth + 1)
                if found is not None:
                    return found
        return None

    def _resolve_ref(self, minfo: _ModuleInfo, cls: Optional[str],
                     fstack: Sequence[ast.AST], expr: ast.AST,
                     as_call: bool) -> List[FuncInfo]:
        """Resolve a Name/Attribute reference to project function(s).
        ``as_call=True`` maps a class to its ``__init__``."""
        def from_entity(ent) -> List[FuncInfo]:
            if isinstance(ent, FuncInfo):
                return [ent]
            if isinstance(ent, ClassInfo):
                if as_call:
                    init = self._method_of(ent, "__init__")
                    return [init] if init is not None else []
                return []
            return []

        if isinstance(expr, ast.Name):
            for owner in reversed(fstack):
                hit = self._locals.get((owner, expr.id))
                if hit is not None:
                    return [hit]
            ent = self._resolve_in_module(minfo.module, expr.id)
            if isinstance(ent, tuple):
                if ent[0] == "sym":
                    return from_entity(
                        self._resolve_in_module(ent[1], ent[2]))
                return []
            return from_entity(ent)

        dotted = dotted_name(expr)
        if not dotted:
            return []
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]

        if head in ("self", "cls") and cls is not None and len(rest) == 1:
            for cinfo in self.classes_by_name.get(cls, ()):
                if cinfo.module != minfo.module:
                    continue
                m = self._method_of(cinfo, rest[0])
                if m is not None:
                    return [m]
            return []

        ent = self._resolve_in_module(minfo.module, head)
        if isinstance(ent, ClassInfo) and len(rest) == 1:
            m = self._method_of(ent, rest[0])
            return [m] if m is not None else []
        if isinstance(ent, tuple) and ent[0] == "mod":
            # walk the module chain: `a.b.f()` with `import a.b`
            mod = ent[1]
            while len(rest) > 1 and ("%s.%s" % (mod, rest[0])) in self.modules:
                mod = "%s.%s" % (mod, rest[0])
                rest = rest[1:]
            if len(rest) == 1:
                return from_entity(self._resolve_in_module(mod, rest[0]))
            if len(rest) == 2:
                sub = self._resolve_in_module(mod, rest[0])
                if isinstance(sub, ClassInfo):
                    m = self._method_of(sub, rest[1])
                    return [m] if m is not None else []
        return []

    # -- edges --------------------------------------------------------------

    def _build_edges(self, relpath: str, tree: ast.AST) -> None:
        minfo = self.modules[module_name_of(relpath)]
        for fn_node, info in list(self.funcs.items()):
            if info.relpath != relpath:
                continue
            fstack = self._enclosing_stack(fn_node)
            seen: Set[ast.AST] = set()
            for node in _own_nodes(fn_node):
                if isinstance(node, ast.Call):
                    for target in self._resolve_ref(minfo, info.cls, fstack,
                                                    node.func, as_call=True):
                        if target.node not in seen:
                            seen.add(target.node)
                            info.callees.append(target)
                elif isinstance(node, ast.Return) and node.value is not None:
                    for t in self._returned_funcs(fn_node, node.value):
                        info.returned_inner.append(t)

    def _returned_funcs(self, owner: ast.AST, expr: ast.AST) -> List[FuncInfo]:
        out = []
        if isinstance(expr, ast.Name):
            hit = self._locals.get((owner, expr.id))
            if hit is not None:
                out.append(hit)
        elif isinstance(expr, ast.Tuple):
            for elt in expr.elts:
                out.extend(self._returned_funcs(owner, elt))
        return out

    def _enclosing_stack(self, fn_node: ast.AST) -> List[ast.AST]:
        """Function nodes lexically enclosing `fn_node` (outer→inner,
        inclusive) — the scopes local-name resolution may search."""
        stack: List[ast.AST] = []
        node = fn_node
        while node is not None:
            if isinstance(node, _ANY_FUNC):
                stack.append(node)
            node = getattr(node, "tpulint_parent", None)
        stack.reverse()
        return stack

    # -- seeds --------------------------------------------------------------

    def _collect_seeds(self, files) -> Tuple[List[FuncInfo], List[FuncInfo]]:
        traced: List[FuncInfo] = []
        threaded: List[FuncInfo] = []

        for info in sorted(self.funcs.values(), key=lambda i: i.qname):
            if info.name in TRACED_SEED_NAMES:
                traced.append(info)

        for relpath, tree in files:
            minfo = self.modules[module_name_of(relpath)]
            # same-file jit closure (decorators, jax.jit(fn), partial wraps)
            for fn_node in jit_functions(tree):
                info = self.funcs.get(fn_node)
                if info is not None:
                    traced.append(info)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                owner = self._nearest_func(node)
                fstack = self._enclosing_stack(node)
                cls = self.funcs[owner].cls if owner in self.funcs else None
                if tail in _JIT_TAILS or tail in _PALLAS_TAILS:
                    if not node.args:
                        continue
                    arg0 = node.args[0]
                    if isinstance(arg0, (ast.Name, ast.Attribute)):
                        traced.extend(self._resolve_ref(
                            minfo, cls, fstack, arg0, as_call=False))
                    elif isinstance(arg0, ast.Call):
                        # jax.jit(self._build_step(...)): the factory's
                        # RETURNED nested functions are what gets traced
                        for factory in self._resolve_ref(
                                minfo, cls, fstack, arg0.func, as_call=True):
                            traced.extend(factory.returned_inner)
                    elif isinstance(arg0, ast.Lambda) and arg0 in self.funcs:
                        traced.append(self.funcs[arg0])
                elif tail in _THREAD_TAILS:
                    for kw in node.keywords:
                        if kw.arg == "target" and isinstance(
                                kw.value, (ast.Name, ast.Attribute)):
                            threaded.extend(self._resolve_ref(
                                minfo, cls, fstack, kw.value, as_call=False))
                elif tail == "push" and node.args:
                    recv = (dotted_name(node.func) or "")[:-len(".push")]
                    if "engine" in recv.lower():
                        arg0 = node.args[0]
                        if isinstance(arg0, (ast.Name, ast.Attribute)):
                            threaded.extend(self._resolve_ref(
                                minfo, cls, fstack, arg0, as_call=False))
                        elif isinstance(arg0, ast.Lambda) and arg0 in self.funcs:
                            threaded.append(self.funcs[arg0])

        for cands in self.classes_by_name.values():
            for cinfo in cands:
                if any(b == "Thread" for b in cinfo.base_names):
                    run = cinfo.methods.get("run")
                    if run is not None:
                        threaded.append(run)
        return traced, threaded

    def _nearest_func(self, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "tpulint_parent", None)
        while cur is not None:
            if isinstance(cur, _ANY_FUNC):
                return cur
            cur = getattr(cur, "tpulint_parent", None)
        return None

    # -- lattice propagation ------------------------------------------------

    def _propagate(self, seeds: Sequence[FuncInfo]
                   ) -> Dict[ast.AST, Tuple[FuncInfo, Optional[FuncInfo], int]]:
        """BFS closure over call edges, bounded by :attr:`depth`.
        Value per reached def node: ``(seed, parent, depth)`` — enough to
        reconstruct a seed→site chain for the finding message."""
        reached: Dict[ast.AST, Tuple[FuncInfo, Optional[FuncInfo], int]] = {}
        frontier: List[FuncInfo] = []
        for seed in sorted(set(seeds), key=lambda i: i.qname):
            if seed.node not in reached:
                reached[seed.node] = (seed, None, 0)
                frontier.append(seed)
        depth = 0
        while frontier and depth < self.depth:
            depth += 1
            nxt: List[FuncInfo] = []
            for info in frontier:
                seed = reached[info.node][0]
                for callee in info.callees:
                    if callee.node not in reached:
                        reached[callee.node] = (seed, info, depth)
                        nxt.append(callee)
            frontier = nxt
        return reached

    # -- queries ------------------------------------------------------------

    def info_of(self, fn_node: ast.AST) -> Optional[FuncInfo]:
        return self.funcs.get(fn_node)

    def is_traced(self, fn_node: ast.AST) -> bool:
        return fn_node in self._traced

    def is_threaded(self, fn_node: ast.AST) -> bool:
        return fn_node in self._threaded

    def _chain(self, table, fn_node) -> Optional[List[str]]:
        if fn_node not in table:
            return None
        names: List[str] = []
        cur = fn_node
        while cur is not None:
            seed, parent_info, _d = table[cur]
            this = self.funcs.get(cur)
            if this is not None:
                names.append(this.name if this.cls is None
                             else "%s.%s" % (this.cls, this.name))
            if parent_info is None:
                break
            cur = parent_info.node
        names.reverse()
        return names

    def traced_chain(self, fn_node: ast.AST) -> Optional[List[str]]:
        """``[seed, ..., fn]`` names when `fn_node` is in traced context."""
        return self._chain(self._traced, fn_node)

    def threaded_chain(self, fn_node: ast.AST) -> Optional[List[str]]:
        """``[entry, ..., fn]`` names when `fn_node` runs on a worker
        thread."""
        return self._chain(self._threaded, fn_node)

    def thread_entry(self, fn_node: ast.AST) -> Optional[str]:
        tup = self._threaded.get(fn_node)
        if tup is None:
            return None
        seed = tup[0]
        return seed.name if seed.cls is None else "%s.%s" % (seed.cls, seed.name)


def _stmt_bodies(node) -> Iterator[list]:
    for field in ("body", "orelse", "finalbody"):
        body = getattr(node, field, None)
        if body:
            yield body
    for h in getattr(node, "handlers", ()):
        yield h.body


def _iter_direct(body) -> Iterator[ast.AST]:
    """All nodes in `body` reachable without crossing a nested function
    boundary — used to find nested defs/lambdas owned by one function."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _ANY_FUNC):
            continue
        stack.extend(ast.iter_child_nodes(node))


def build_graph(files: Sequence[Tuple[str, ast.AST]],
                depth: int = DEFAULT_DEPTH) -> ProjectGraph:
    """Build a :class:`ProjectGraph` over ``(relpath, parsed-tree)`` pairs.
    Trees must already carry ``tpulint_parent`` links
    (:func:`tools.tpulint.core.attach_parents`)."""
    return ProjectGraph(files, depth=depth)
