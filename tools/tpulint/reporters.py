"""Output formatting for tpulint: human text and machine JSON."""
from __future__ import annotations

import json
from typing import Dict, List, Sequence

from .core import Finding


def render_text(new: Sequence[Finding], total: int, baselined: int,
                stale_keys: Sequence[str] = ()) -> str:
    lines: List[str] = [str(f) for f in new]
    lines.append("")
    lines.append("tpulint: %d finding(s): %d baselined, %d new"
                 % (total, baselined, len(new)))
    if stale_keys:
        lines.append("tpulint: %d stale baseline entr%s (fixed since the "
                     "baseline was written — regenerate with --write-baseline):"
                     % (len(stale_keys), "y" if len(stale_keys) == 1 else "ies"))
        lines.extend("  %s" % k for k in sorted(stale_keys))
    return "\n".join(lines)


def render_stats(stats: Dict) -> str:
    """`--stats`: per-pass timing, parse/graph cost, cache hit rate."""
    lines: List[str] = ["", "tpulint --stats:"]
    lines.append("  files linted: %d" % stats.get("files", 0))
    for key in ("parse_ms", "graph_ms"):
        if key in stats:
            lines.append("  %-18s %8.1f ms" % (key[:-3], stats[key]))
    for name, ms in sorted(stats.get("pass_ms", {}).items(),
                           key=lambda kv: -kv[1]):
        lines.append("  pass %-22s %8.1f ms" % (name, ms))
    hits = stats.get("cache_hits", 0)
    misses = stats.get("cache_misses", 0)
    if hits or misses:
        lines.append("  cache: %d hit(s), %d miss(es) (%.1f%% hit rate)"
                     % (hits, misses, 100.0 * hits / (hits + misses)))
    if "total_ms" in stats:
        lines.append("  total: %.1f ms" % stats["total_ms"])
    return "\n".join(lines)


def render_json(new: Sequence[Finding], total: int, baselined: int,
                stale_keys: Sequence[str] = ()) -> str:
    payload: Dict = {
        "total": total,
        "baselined": baselined,
        "new": [f.as_dict() for f in new],
        "stale_baseline_keys": sorted(stale_keys),
    }
    return json.dumps(payload, indent=2, sort_keys=True)
