"""Output formatting for tpulint: human text and machine JSON."""
from __future__ import annotations

import json
from typing import Dict, List, Sequence

from .core import Finding


def render_text(new: Sequence[Finding], total: int, baselined: int,
                stale_keys: Sequence[str] = ()) -> str:
    lines: List[str] = [str(f) for f in new]
    lines.append("")
    lines.append("tpulint: %d finding(s): %d baselined, %d new"
                 % (total, baselined, len(new)))
    if stale_keys:
        lines.append("tpulint: %d stale baseline entr%s (fixed since the "
                     "baseline was written — regenerate with --write-baseline):"
                     % (len(stale_keys), "y" if len(stale_keys) == 1 else "ies"))
        lines.extend("  %s" % k for k in sorted(stale_keys))
    return "\n".join(lines)


def render_json(new: Sequence[Finding], total: int, baselined: int,
                stale_keys: Sequence[str] = ()) -> str:
    payload: Dict = {
        "total": total,
        "baselined": baselined,
        "new": [f.as_dict() for f in new],
        "stale_baseline_keys": sorted(stale_keys),
    }
    return json.dumps(payload, indent=2, sort_keys=True)
