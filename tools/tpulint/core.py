"""tpulint core: findings, per-file analysis context, pass registry, baseline.

TPU-correctness static analysis for mxnet_tpu. The reference framework's
async engine made ordering hazards *loud* (a missed WaitForVar deadlocks or
races immediately); on JAX/XLA the equivalent hazard class is *silent* —
an implicit device->host sync, a side effect swallowed by `jit` tracing, or
float64 creep all run fine on the CPU tier-1 suite and only show up as a
TPU throughput cliff or a wrong number. tpulint walks the source with the
stdlib `ast` module (no new deps, no JAX import, no device work) and flags
those hazards mechanically before a PR lands.

Design:

- a :class:`Pass` inspects one :class:`FileContext` and yields
  :class:`Finding`\\ s; passes self-register into :data:`REGISTRY`;
- per-line suppression with ``# tpulint: disable=<rule>[,<rule>...]``
  (``disable=all`` silences every rule on that line);
- a committed baseline (``tools/tpulint/baseline.json``) keyed by
  ``path::rule::message`` — deliberately *not* by line number, so unrelated
  edits that shift lines don't invalidate it — lets pre-existing findings
  ride while any new finding fails the gate.
"""
from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
DEFAULT_ROOTS = ("mxnet_tpu", "tools")

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message")

    def __init__(self, rule: str, path: str, line: int, col: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message

    def baseline_key(self) -> str:
        # No line number: baselines must survive unrelated edits above them.
        # Known tradeoff: keys collide per (file, rule, message), so fixing
        # one baselined site while adding an identical new one in the same
        # file cancels out and the new site rides the old entry. Accepted —
        # the alternative (line keys) invalidates the whole baseline on any
        # edit; burn-down shrinks the counts over time either way.
        return "%s::%s::%s" % (self.path, self.rule, self.message)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def __str__(self) -> str:
        return "%s:%d:%d: [%s] %s" % (self.path, self.line, self.col, self.rule, self.message)

    def __repr__(self) -> str:
        return "Finding(%s)" % self


class FileContext:
    """Parsed source plus the lookups every pass needs (parents, comments)."""

    def __init__(self, relpath: str, source: str, filename: str = "<string>"):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=filename)
        attach_parents(self.tree)
        #: the whole-program :class:`tools.tpulint.graph.ProjectGraph` when
        #: this file is linted as part of a project scope (None for a
        #: lone-snippet lint); project passes read their lattices from it.
        self.project = None
        self._suppressions: Dict[int, set] = {}
        for lineno, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._suppressions[lineno] = {r.strip() for r in m.group(1).split(",")}
        self._jit_functions: Optional[set] = None

    def jit_functions(self) -> set:
        """Cached :func:`jit_functions` of this file's tree — several passes
        need it and the transitive-closure walk is the expensive part."""
        if self._jit_functions is None:
            self._jit_functions = jit_functions(self.tree)
        return self._jit_functions

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self._suppressions.get(line)
        return rules is not None and (rule in rules or "all" in rules)

    def finding(self, node: ast.AST, rule: str, message: str) -> Finding:
        return Finding(rule, self.relpath, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# AST helpers shared by passes
# ---------------------------------------------------------------------------

def attach_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.tpulint_parent`` (None at the root)."""
    tree.tpulint_parent = None  # type: ignore[attr-defined]
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child.tpulint_parent = parent  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "tpulint_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``'jax.numpy.float64'`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def enclosing_scope(node: ast.AST) -> ast.AST:
    """Nearest function def, else the module."""
    cur: ast.AST = node
    for anc in ancestors(node):
        cur = anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return cur


_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.comprehension)


def in_loop(node: ast.AST) -> bool:
    """True when `node` sits inside a loop body *within its own function* —
    a loop in an outer function does not make a nested def per-iteration."""
    for anc in ancestors(node):
        if isinstance(anc, _LOOPS) or isinstance(anc, (ast.ListComp, ast.SetComp,
                                                       ast.DictComp, ast.GeneratorExp)):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
    return False


# -- jit detection ----------------------------------------------------------

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit", "eqx.filter_jit"}


def _is_jit_expr(node: ast.AST) -> bool:
    """True for an expression denoting jax.jit or a configured jit:
    ``jax.jit``, ``jit``, ``jax.jit(...)``, ``partial(jax.jit, ...)``."""
    if dotted_name(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in _JIT_NAMES:
            return True
        if fname in ("partial", "functools.partial") and node.args \
                and dotted_name(node.args[0]) in _JIT_NAMES:
            return True
    return False


def jit_functions(tree: ast.AST) -> set:
    """Function/lambda nodes whose bodies run under jax.jit *tracing*:

    - ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs;
    - lambdas or same-file named functions passed to ``jax.jit(...)``;
    - plus the transitive closure of same-file functions *called by name*
      from any of the above (tracing inlines the whole call tree).
    """
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    jitted: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(dec) for dec in node.decorator_list):
                jitted.add(node)
        elif isinstance(node, ast.Call) and node.args:
            f = node.func
            direct = dotted_name(f) in _JIT_NAMES
            # partial(jax.jit, ...)(fn) — but NOT jax.jit(f)(x), where
            # args[0] is data, not a function being compiled
            curried = (isinstance(f, ast.Call)
                       and dotted_name(f.func) in ("partial", "functools.partial")
                       and f.args and dotted_name(f.args[0]) in _JIT_NAMES)
            if not (direct or curried):
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                jitted.add(target)
            elif isinstance(target, ast.Name):
                jitted.update(defs_by_name.get(target.id, ()))

    # Transitive closure over same-file calls-by-name.
    changed = True
    while changed:
        changed = False
        for fn in list(jitted):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    for callee in defs_by_name.get(node.func.id, ()):
                        if callee not in jitted:
                            jitted.add(callee)
                            changed = True
    return jitted


def in_jit(node: ast.AST, jitted: set) -> bool:
    return any(anc in jitted for anc in ancestors(node))


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------

class Pass:
    """One analysis. Subclasses set ``name``/``description`` and implement
    :meth:`run`; ``applies`` restricts a pass to part of the tree (e.g.
    env-knob only polices the framework package, not user-facing tools).
    ``project = True`` marks an *interprocedural* pass: it reads the
    whole-program lattices from ``ctx.project`` and its results depend on
    every file in the lint scope (the incremental cache keys them by the
    scope signature, not just the file hash)."""

    name = ""
    description = ""
    project = False

    def applies(self, relpath: str) -> bool:
        return True

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


REGISTRY: Dict[str, Pass] = {}


def register(cls):
    """Class decorator: instantiate and add to :data:`REGISTRY`."""
    inst = cls()
    if not inst.name:
        raise ValueError("pass %r has no name" % cls)
    REGISTRY[inst.name] = inst
    return cls


def all_passes() -> Dict[str, Pass]:
    from . import passes  # noqa: F401  - importing populates REGISTRY
    return REGISTRY


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------

def collect_files(paths: Sequence[str], root: Path = REPO_ROOT) -> List[Path]:
    """Expand path arguments into a sorted list of ``.py`` files."""
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if not path.is_absolute():
            path = root / path
        if path.is_dir():
            # hidden-dir check is relative to the scanned dir: an absolute
            # path with a dotted ancestor (~/.work/repo) must not empty the scope
            out.extend(f for f in path.rglob("*.py")
                       if not any(part.startswith(".")
                                  for part in f.relative_to(path).parts))
        elif path.suffix == ".py" and path.exists():
            out.append(path)
    return sorted(set(out))


def relpath_of(path: Path, root: Path = REPO_ROOT) -> str:
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


def _run_pass(ctx: FileContext, p: Pass) -> List[Finding]:
    """Run one pass on one file, suppression-filtered and sorted."""
    out: List[Finding] = []
    if p.applies(ctx.relpath):
        for f in p.run(ctx):
            if not ctx.suppressed(f.rule, f.line):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _parse_error(rel: str, exc: BaseException) -> Finding:
    if isinstance(exc, SyntaxError):
        return Finding("parse-error", rel, exc.lineno or 1, 0,
                       "file does not parse: %s" % exc.msg)
    if isinstance(exc, UnicodeDecodeError):
        return Finding("parse-error", rel, 1, 0,
                       "file is not UTF-8: %s" % exc.reason)
    return Finding("parse-error", rel, 1, 0, "file does not parse: %s" % exc)


def lint_sources(pairs: Sequence[tuple],
                 passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint in-memory ``(relpath, source)`` blobs as ONE project scope:
    all files join the same symbol table / call graph, so interprocedural
    passes see cross-file reachability. Returns suppression-filtered
    findings. (The multi-file entry point for tests and tools; the CLI
    path goes through :func:`lint_files`.)"""
    from . import graph as graph_mod

    registry = all_passes()
    names = list(passes) if passes is not None else sorted(registry)
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for relpath, source in pairs:
        try:
            contexts.append(FileContext(relpath, source, filename=relpath))
        except (SyntaxError, ValueError) as exc:
            findings.append(_parse_error(relpath, exc))
    project = graph_mod.build_graph([(c.relpath, c.tree) for c in contexts])
    for ctx in contexts:
        ctx.project = project
        for name in names:
            findings.extend(_run_pass(ctx, registry[name]))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_source(relpath: str, source: str,
                passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one in-memory source blob; returns suppression-filtered
    findings. Interprocedural passes see a single-file project graph."""
    return lint_sources([(relpath, source)], passes=passes)


def lint_files(files: Sequence[Path], root: Path = REPO_ROOT,
               passes: Optional[Sequence[str]] = None,
               cache=None, stats: Optional[Dict] = None,
               project_scope: Optional[Sequence[Path]] = None,
               ) -> List[Finding]:
    """Lint files from disk as one project scope.

    ``cache`` is an optional :class:`tools.tpulint.cache.LintCache`:
    local-pass results are reused per file hash, interprocedural results
    per (file hash, scope signature) — an unchanged scope runs no pass
    and never parses a file. ``stats`` (a dict, filled in place) collects
    per-pass timings and cache hit counts for ``--stats``.

    ``project_scope`` widens the symbol-table/call-graph scope beyond the
    reported files: findings come only from ``files``, but the context
    lattices (and the cache's scope signature) are computed over the
    union — so a ``--changed-only`` run still sees traced/thread seeds
    living in unchanged files, and its project results share cache
    entries with the full run.
    """
    import time

    from . import graph as graph_mod
    from .cache import file_sha, scope_signature

    registry = all_passes()
    names = list(passes) if passes is not None else sorted(registry)
    local_names = [n for n in names if not registry[n].project]
    project_names = [n for n in names if registry[n].project]
    stats = stats if stats is not None else {}
    pass_ms = stats.setdefault("pass_ms", {})
    findings: List[Finding] = []

    # 1. read + hash every file in scope
    def read_blob(path, report_errors):
        rel = relpath_of(path, root)
        try:
            raw = path.read_bytes()
            return (rel, raw.decode("utf-8"), file_sha(raw))
        except OSError:
            return None
        except UnicodeDecodeError as exc:
            if report_errors:
                findings.append(_parse_error(rel, exc))
            return None

    blobs: List[tuple] = []  # (rel, source, sha) — the files we REPORT on
    for path in files:
        blob = read_blob(path, report_errors=True)
        if blob is not None:
            blobs.append(blob)
    reported = {rel for rel, _s, _h in blobs}
    extra_blobs: List[tuple] = []  # graph-only context, never reported
    for path in project_scope or ():
        blob = read_blob(path, report_errors=False)
        if blob is not None and blob[0] not in reported:
            extra_blobs.append(blob)
    stats["files"] = len(blobs)
    scope_sig = scope_signature(
        [(rel, sha) for rel, _s, sha in blobs + extra_blobs])

    # 2. consult the cache; decide what must actually run
    todo: Dict[str, List[str]] = {}  # rel -> pass names to run
    for rel, _source, sha in blobs:
        for name in local_names:
            hit = cache.get_local(rel, sha, name) if cache is not None else None
            if hit is None:
                todo.setdefault(rel, []).append(name)
            else:
                findings.extend(hit)
        for name in project_names:
            hit = (cache.get_project(rel, sha, scope_sig, name)
                   if cache is not None else None)
            if hit is None:
                todo.setdefault(rel, []).append(name)
            else:
                findings.extend(hit)

    # 3. parse what's needed: files with work, plus — when any
    # interprocedural pass must run anywhere — the WHOLE scope including
    # graph-only context files (the lattices are only sound over all of it)
    need_graph = project_names and any(
        any(n in project_names for n in ns) for ns in todo.values())
    t0 = time.perf_counter()
    contexts: Dict[str, FileContext] = {}
    for rel, source, sha in blobs + (extra_blobs if need_graph else []):
        if rel not in todo and not need_graph:
            continue
        try:
            contexts[rel] = FileContext(rel, source, filename=rel)
        except (SyntaxError, ValueError) as exc:
            if rel in todo:
                findings.append(_parse_error(rel, exc))
                del todo[rel]
    stats["parse_ms"] = round((time.perf_counter() - t0) * 1000, 1)

    project = None
    if need_graph:
        t0 = time.perf_counter()
        project = graph_mod.build_graph(
            [(c.relpath, c.tree) for c in contexts.values()])
        for ctx in contexts.values():
            ctx.project = project
        stats["graph_ms"] = round((time.perf_counter() - t0) * 1000, 1)

    # 4. run the missing (file, pass) pairs; store results back
    sha_of = {rel: sha for rel, _s, sha in blobs}
    for rel in sorted(todo):
        ctx = contexts.get(rel)
        if ctx is None:
            continue
        for name in todo[rel]:
            p = registry[name]
            t0 = time.perf_counter()
            result = _run_pass(ctx, p)
            pass_ms[name] = pass_ms.get(name, 0.0) \
                + (time.perf_counter() - t0) * 1000
            findings.extend(result)
            if cache is not None:
                if p.project:
                    cache.put_project(rel, sha_of[rel], scope_sig, name, result)
                else:
                    cache.put_local(rel, sha_of[rel], name, result)

    if cache is not None:
        cache.save(root=root)
        stats["cache_hits"] = cache.hits
        stats["cache_misses"] = cache.misses
    for name, ms in list(pass_ms.items()):
        pass_ms[name] = round(ms, 1)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def baseline_counts(findings: Sequence[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    return counts


def write_baseline(findings: Sequence[Finding], path: Path) -> None:
    write_baseline_counts(baseline_counts(findings), path)


def write_baseline_counts(counts: Dict[str, int], path: Path,
                          justifications: Optional[Dict[str, str]] = None,
                          ) -> None:
    data = {"version": 1, "counts": dict(sorted(counts.items()))}
    justs = {k: v for k, v in (justifications or {}).items() if k in counts}
    if justs:
        data["justifications"] = dict(sorted(justs.items()))
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n",
                    encoding="utf-8")


def key_scope(key: str) -> tuple:
    """``(path, rule)`` of a baseline key."""
    parts = key.split("::", 2)
    return parts[0], parts[1] if len(parts) > 1 else ""


def load_baseline(path: Path) -> Dict[str, int]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def load_justifications(path: Path) -> Dict[str, str]:
    """The optional per-entry one-line justifications riding next to the
    baseline counts (same keys)."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return {str(k): str(v) for k, v in data.get("justifications", {}).items()}


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, int]) -> List[Finding]:
    """Findings NOT covered by the baseline. When a key appears more often
    than its baselined count, the surplus (highest line numbers — the likely
    newest occurrences) is reported."""
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.baseline_key(), []).append(f)
    new: List[Finding] = []
    for key, group in by_key.items():
        allowed = baseline.get(key, 0)
        if len(group) > allowed:
            group.sort(key=lambda f: (f.line, f.col))
            new.extend(group[allowed:])
    new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return new
