"""use-after-donate: reading a value after its buffer was donated.

Buffer donation (``donate_argnums`` on the fused/zero/decode jits,
``fastpath.fused_apply``'s whole-tree donation) hands the argument's
device memory to XLA for reuse — after the call the old handle points at
freed (or silently recycled) storage. The fastpath discipline is
``donation_prep`` → jit → ``invalidate_consumed``, which makes a stale
read *raise*; the bug class this pass guards is the silent one: code
that keeps using the Python variable after passing it to a donating
call, without a rebind. That read works on CPU (donation is a no-op
there), and on TPU returns garbage or a use-after-free — the PR-5/8
stale-handle guards exist because it happened.

A **local data-flow pass** (per function, statements in source order,
both branches of a conditional taken — a deliberate over-approximation):

- a call to a *donating callee* marks its plain-name and ``self.attr``
  arguments donated: ``fused_apply`` (the fastpath donation surface)
  and any name bound in the same scope from
  ``jax.jit(..., donate_argnums=...)`` (the pool-donating decode/zero
  jits); ``donation_prep(X, ...)`` marks its arguments *pending* — the
  prep only probes buffers — and the next call that receives a pending
  name is its consumer: the donation window opens there;
- a later ``Load`` of a donated name is the finding;
- rebinding the name (assignment, tuple unpack, for-target, with-as),
  ``del``, or an intervening ``invalidate_consumed(...)`` /
  ``.delete()`` call clears it — the discipline is in place.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import (FileContext, Finding, Pass, dotted_name,
                    enclosing_function, register)

_DONATING_TAILS = {"fused_apply"}
_PREP_TAILS = {"donation_prep"}
_CLEARING_TAILS = {"invalidate_consumed", "delete"}
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
# calls that can receive a donation_prep'd name WITHOUT consuming its
# buffer: introspection, logging, container plumbing — only a real
# compute call opens the donation window
_NON_CONSUMING_TAILS = {
    "len", "print", "str", "repr", "format", "isinstance", "type", "id",
    "hash", "zip", "enumerate", "sorted", "reversed", "list", "tuple",
    "dict", "set", "sum", "min", "max", "any", "all", "getattr",
    "hasattr", "range", "debug", "info", "warning", "error", "exception",
    "append", "extend", "inc", "set_", "observe", "add",
}


def _name_of(expr: ast.AST) -> str:
    """A trackable key for a donated argument: a bare name or a short
    ``self.x`` attribute; '' for anything else."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return "%s.%s" % (expr.value.id, expr.attr)
    return ""


def _jit_donating_names(scope_body: List[ast.stmt]) -> Set[str]:
    """Names bound (in this statement list) from a ``jax.jit(...)`` call
    carrying ``donate_argnums`` — calls through them donate."""
    out: Set[str] = set()
    for stmt in scope_body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            tail = (dotted_name(call.func) or "").rsplit(".", 1)[-1]
            if tail not in ("jit", "pjit"):
                continue
            if not any(kw.arg == "donate_argnums" for kw in call.keywords):
                continue
            for tgt in node.targets:
                key = _name_of(tgt)
                if key:
                    out.add(key)
    return out


def _assigned_keys(target: ast.AST) -> Iterator[str]:
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_keys(elt)
    elif isinstance(target, ast.Starred):
        yield from _assigned_keys(target.value)
    else:
        key = _name_of(target)
        if key:
            yield key


@register
class UseAfterDonatePass(Pass):
    name = "use-after-donate"
    description = ("a variable is read after being passed to a donating "
                   "call (fused_apply/donation_prep/donate_argnums jit) "
                   "with no rebind or invalidate_consumed between")

    def applies(self, relpath: str) -> bool:
        # fastpath/fused.py IS the donation discipline: it probes, deletes
        # and re-reads handles deliberately, under its own guards
        return relpath.startswith("mxnet_tpu/") \
            and relpath != "mxnet_tpu/fastpath/fused.py"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        # donating jits installed as instance attrs in ONE method
        # (`self._step = jax.jit(..., donate_argnums=...)` in __init__)
        # donate when called from ANY method of the class
        class_attrs: Dict[ast.AST, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                acc: Set[str] = set()
                for sub in node.body:
                    if isinstance(sub, _FUNCS):
                        acc |= {k for k in _jit_donating_names(sub.body)
                                if "." in k}
                class_attrs[node] = acc
        for node in ast.walk(ctx.tree):
            # only scope roots: nested defs are scanned (with inherited
            # donating names) by the recursive walk below
            if isinstance(node, _FUNCS) and enclosing_function(node) is None:
                parent = getattr(node, "tpulint_parent", None)
                extra = class_attrs.get(parent, set())
                yield from self._scan_function(ctx, node, extra)

    # -- per-function linear data flow --------------------------------------

    def _scan_function(self, ctx: FileContext, fn, extra=()) -> Iterator[Finding]:
        donating = set(_DONATING_TAILS) | set(extra) \
            | _jit_donating_names(fn.body)
        donated: Dict[str, Tuple[int, str]] = {}  # key -> (line, callee)
        pending: Dict[str, int] = {}              # donation_prep'd, unconsumed
        yield from self._scan_body(ctx, fn.body, donating, donated, pending)

    def _scan_body(self, ctx, body, donating, donated, pending
                   ) -> Iterator[Finding]:
        for stmt in body:
            yield from self._scan_stmt(ctx, stmt, donating, donated, pending)

    def _scan_stmt(self, ctx, stmt, donating, donated, pending
                   ) -> Iterator[Finding]:
        # a nested def's body runs when *called*, not here — scan it as
        # its own scope, inheriting the enclosing donating names (closure)
        if isinstance(stmt, _FUNCS):
            yield from self._scan_function(ctx, stmt, extra=donating)
            return

        def clear(key):
            donated.pop(key, None)
            pending.pop(key, None)

        sub_bodies: List[list] = []
        exprs: List[ast.AST] = []
        if isinstance(stmt, (ast.If, ast.While)):
            exprs.append(stmt.test)
            sub_bodies += [stmt.body, stmt.orelse]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs.append(stmt.iter)
            for key in _assigned_keys(stmt.target):
                clear(key)
            sub_bodies += [stmt.body, stmt.orelse]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                exprs.append(item.context_expr)
                if item.optional_vars is not None:
                    for key in _assigned_keys(item.optional_vars):
                        clear(key)
            sub_bodies.append(stmt.body)
        elif isinstance(stmt, ast.Try):
            sub_bodies += [stmt.body, stmt.orelse, stmt.finalbody]
            sub_bodies += [h.body for h in stmt.handlers]
        else:
            exprs.append(stmt)

        for expr in exprs:
            yield from self._scan_expr(ctx, expr, donating, donated, pending)

        # statement-level effects AFTER its expressions were evaluated
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for key in _assigned_keys(tgt):
                    clear(key)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            for key in _assigned_keys(stmt.target):
                clear(key)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                clear(_name_of(tgt))

        for body in sub_bodies:
            yield from self._scan_body(ctx, body, donating, donated, pending)

    def _scan_expr(self, ctx, expr, donating, donated, pending
                   ) -> Iterator[Finding]:
        """Reads first (a read and a donation in one statement is the
        donation call itself), then new donations/preps/clears."""
        donation_calls: List[Tuple[ast.Call, List[str]]] = []
        prep_calls: List[ast.Call] = []
        arg_nodes: Set[ast.AST] = set()
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            full = _name_of(node.func)
            args = list(node.args) + [kw.value for kw in node.keywords]
            keys = [k for k in (_name_of(a) for a in args) if k]
            if tail in _DONATING_TAILS or tail in donating or full in donating:
                donation_calls.append((node, keys))
                arg_nodes.update(a for a in args if _name_of(a))
            elif tail in _PREP_TAILS:
                prep_calls.append(node)
            elif tail in _CLEARING_TAILS:
                # discipline call: the stale window is closed for every
                # tracked handle (args are trees/containers of them)
                donated.clear()
                pending.clear()
            elif tail not in _NON_CONSUMING_TAILS \
                    and any(k in pending for k in keys):
                # the consumer of a donation_prep'd buffer: the donation
                # window opens HERE (args of this very call are the
                # sanctioned last read); introspection/logging calls
                # touching the name first do not consume it
                consumed = [k for k in keys if k in pending]
                donation_calls.append((node, consumed))
                arg_nodes.update(a for a in args if _name_of(a) in consumed)

        # same-statement donations: a read lexically AFTER the donating
        # call (`fused_apply(..., w) + w[0]`) evaluates after the buffer
        # is gone — positional order approximates evaluation order
        stmt_donated: Dict[str, ast.Call] = {}
        for call, keys in donation_calls:
            for k in keys:
                stmt_donated.setdefault(k, call)

        for node in ast.walk(expr):
            key = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                key = node.id
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name):
                key = "%s.%s" % (node.value.id, node.attr)
            if key is None or node in arg_nodes:
                continue
            # `self.x.attr` reads route through the Attribute node whose
            # .value is the donated self.x — those hit the key above;
            # a bare donated Name inside its own donation call is exempt
            if any(node is a or _contains(a, node) for a in arg_nodes):
                continue
            if key in donated:
                line, callee = donated.pop(key)
            elif key in stmt_donated:
                call = stmt_donated[key]
                if _contains(call, node):
                    continue  # part of the donating call itself
                if (node.lineno, node.col_offset) \
                        <= (call.lineno, call.col_offset):
                    continue  # evaluated before the donation
                line = call.lineno
                callee = (dotted_name(call.func) or "").rsplit(".", 1)[-1] \
                    or "donating call"
            else:
                continue
            yield ctx.finding(
                node, self.name,
                "`%s` is read after being donated to `%s()` (line %d has "
                "no rebind/invalidate_consumed between) — the buffer may "
                "be freed or reused on TPU" % (key, callee, line))

        for call, keys in donation_calls:
            tail = (dotted_name(call.func) or "").rsplit(".", 1)[-1]
            callee = tail if tail else "donating call"
            for key in keys:
                pending.pop(key, None)
                donated[key] = (call.lineno, callee)
        for call in prep_calls:
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                key = _name_of(arg)
                if key:
                    pending[key] = call.lineno


def _contains(parent: ast.AST, node: ast.AST) -> bool:
    return any(sub is node for sub in ast.walk(parent))
