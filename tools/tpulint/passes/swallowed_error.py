"""swallowed-error: broad exception suppression in runtime paths.

A ``try: ... except Exception: pass`` (or bare ``except:``/
``except BaseException:`` with a body that only ``pass``/``continue``\\ s)
silently eats every failure class — including the transient faults the
resilience layer exists to retry and the programming errors that should
fail loudly. On this stack that pattern is how an io worker "finishes" an
epoch early, a checkpoint "commits" nothing, or a serving thread wedges
with no trace. The fix is one of: narrow the exception type to what the
site actually expects (``queue.Empty``, ``OSError``), route it through a
``resilience.RetryPolicy``, or at minimum log before suppressing.

Scope: ``mxnet_tpu/`` only (the runtime package); ``tools/`` scripts own
their CLI error handling. Handlers that *do something* — re-raise,
return, log, assign — are not flagged: the rule targets pure suppression.
Legitimate suppressions (destructors, interpreter teardown) carry a
``# tpulint: disable=swallowed-error`` with their justification or ride
the baseline.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Pass, dotted_name, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    """Bare except, Exception/BaseException, or a tuple containing one."""
    if type_node is None:
        return True
    name = dotted_name(type_node)
    if name in _BROAD:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    return False


def _only_suppresses(body) -> bool:
    """True when the handler body does nothing with the error: just
    ``pass``/``continue``/``...`` (a docstring-style constant counts as
    nothing too)."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@register
class SwallowedErrorPass(Pass):
    name = "swallowed-error"
    description = ("broad `except ...: pass`-style suppression in "
                   "mxnet_tpu/ runtime paths")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _only_suppresses(node.body):
                what = "bare `except:`" if node.type is None else \
                    "`except %s:`" % (dotted_name(node.type)
                                      or "<broad tuple>")
                yield ctx.finding(
                    node, self.name,
                    "%s with a body that only suppresses — narrow the "
                    "exception type, retry via resilience.RetryPolicy, or "
                    "log before dropping it" % what)
