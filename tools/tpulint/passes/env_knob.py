"""env-knob: raw ``os.environ`` reads inside the framework package.

The reference routed every knob through ``dmlc::GetEnv`` so
``docs/faq/env_var.md`` could document them all; this port keeps that
discipline in ``mxnet_tpu.base.get_env``. A raw ``os.environ.get(...)``
scattered in a module is an undocumented, unregistered knob — invisible to
``docs/env_var.md``, untypechecked, and (under jit) a silent trace-time
constant.

Scope: only files under ``mxnet_tpu/`` are policed (user-facing scripts in
``tools/`` legitimately read their own CLI environment), and ``base.py``
itself is exempt — it is the one place the raw read belongs.

Flagged (reads): ``os.environ.get`` / ``os.environ.setdefault`` /
``os.getenv`` / ``os.environ[...]`` loads. Mutations (``pop``, ``del``,
subscript stores) are not flagged — writing the environment for a
subprocess is host-side plumbing, not an unregistered knob.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Pass, dotted_name, parent, register

_READ_METHODS = {"get", "setdefault"}


@register
class EnvKnobPass(Pass):
    name = "env-knob"
    description = "raw os.environ reads in mxnet_tpu/ outside base.get_env"

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/") and relpath != "mxnet_tpu/base.py"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and dotted_name(node.func) == "os.getenv":
                yield ctx.finding(node, self.name,
                                  "raw `os.getenv()` — route knob reads through "
                                  "base.get_env so they are registered in one place")
            elif isinstance(node, ast.Attribute) and dotted_name(node) == "os.environ":
                p = parent(node)
                if isinstance(p, ast.Attribute) and p.attr in _READ_METHODS:
                    yield ctx.finding(node, self.name,
                                      "raw `os.environ.%s()` — route knob reads "
                                      "through base.get_env so they are registered "
                                      "in one place" % p.attr)
                elif isinstance(p, ast.Subscript) and isinstance(p.ctx, ast.Load):
                    yield ctx.finding(node, self.name,
                                      "raw `os.environ[...]` read — route knob reads "
                                      "through base.get_env so they are registered "
                                      "in one place")
