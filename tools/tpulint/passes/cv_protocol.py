"""cv-protocol: condition-variable usage that races its own predicate.

Three CV misuses that all present as rare hangs:

- **bare wait** — ``cv.wait()`` outside a ``while``-predicate loop.
  Spurious wakeups are allowed by every CV implementation and a notify
  can land between the predicate check and the wait; an unlooped wait
  returns with the predicate false and the caller proceeds on stale
  state.
- **unwakeable wait** — an *untimed* wait whose loop predicate observes
  no shutdown flag. ``close()`` has no way to wake the thread, so the
  owning ``join()`` blocks forever — the worker-leak shape the elastic
  plane's shutdown paths are designed against. A timeout bounds the
  hang; a ``_closed``-style flag in the predicate (re-checked on every
  wakeup) ends it.
- **unlocked notify** — ``cv.notify()`` / ``notify_all()`` without the
  CV's lock held. CPython raises for a genuinely unheld notify, but the
  static check also catches the subtler version: notify under the
  *wrong* lock, which races the waiter's predicate check and loses
  wakeups. Held-ness is judged on the lexical ``with`` stack plus the
  held-lock entry lattice, so a notify helper called under the CV is
  clean.

Receivers are matched by CV-ish name tokens (``cond``/``cv``), the same
naming-convention contract the v2 races pass uses for lock-ish names.
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import locks


@register
class CvProtocolPass(Pass):
    name = "cv-protocol"
    description = ("condition-variable protocol violations: bare wait "
                   "outside a while-loop, untimed wait no shutdown flag "
                   "can wake, notify without the CV's lock")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = locks.analyze(graph)
        for rec in ana.cv_findings.get(ctx.relpath, ()):
            yield ctx.finding(rec.node, self.name, rec.message())
