"""recompile-risk: jit sites statically reachable with ⊤-shaped operands.

The zero-steady-state-recompile invariant is the serving/training
planes' hottest property — and until now it was only *measured*: the
PR-3 jit-cache-growth gauge catches a recompile storm after a warm lap
on real hardware, a full bench round after the PR that caused it. This
pass makes it *provable* before execution: the abstract shape
interpreter (:mod:`tools.tpulint.shapes`) propagates a symbolic
dimension domain — constants, ``MXNET_*`` knob reads, bounded
bucket-ladder sets, ⊤ for data-dependent sizes — interprocedurally
through the PR-10 project graph into every jit/pallas dispatch site
(direct calls of ``jax.jit`` values, ``@jit``-decorated functions,
jit-valued ``self._step``-style attributes, and the
``telemetry.jit_call``/``resilience.call`` wrappers).

Flagged: a dispatch whose operand shape contains ⊤ — a dimension
positively derived from ``len()`` of host data, ``.shape`` of queue
payloads, or a python-loop accumulator. Every distinct runtime value of
that dimension compiles a new executable; in steady state that is a
recompile storm no warmup can cover.

Clean **by construction** (never flagged): const dims (one compile),
knob-derived dims (one compile per process), bounded bucket-ladder
rungs and ``select_bucket`` results (one compile per rung — exactly
what ``warmup()`` pre-compiles), and unknown dims (ignorance is not
evidence; the pass only reports positively-derived unboundedness).
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import shapes


@register
class RecompileRiskPass(Pass):
    name = "recompile-risk"
    description = ("jit/pallas dispatch sites reachable with ⊤-shaped "
                   "(data-dependent) operands — statically predicted "
                   "steady-state recompiles")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = shapes.analyze(graph)
        for risk in ana.jit_risks.get(ctx.relpath, ()):
            yield ctx.finding(risk.node, self.name, risk.message())
