"""perparam-jit: jitted-call dispatch inside a per-parameter loop.

The dispatch-bound regime BENCH_TPU_PARTIAL_r05 measured (0.6% MFU) came
from exactly one shape of code: a python ``for`` loop over parameters (or
kvstore keys) issuing one compiled-call dispatch per element —
``updater(i, g, w)`` per parameter, ``self._fused(...)(...)`` per weight,
``kv.push(i, ...)`` per key. Each iteration pays a full host→device
dispatch for micro-sized work while the accelerator idles between kernels.
The fastpath layer removes the pattern (one fused jit over the whole tree,
one batched pushpull over all keys); this pass keeps it from growing back.

Flagged inside a loop:

- invoking a jitted callable obtained *in the same expression*:
  ``jax.jit(f)(x)``, ``self._fused(...)(...)``, or a subscripted jit cache
  (``self._step_cache[k](...)``, ``_JITS[key](...)``);
- calling a name bound from ``jax.jit(...)`` in the same function;
- the per-parameter optimizer dispatch: ``optimizer.update(...)`` /
  ``.update_multi_precision(...)``, or calling an ``updater``/``upd``
  variable;
- the per-key kvstore exchange: ``.push(...)`` / ``.pull(...)`` on a
  kvstore-named receiver.

Legacy escape hatches (the ``MXNET_FASTPATH=0`` loops) stay baselined, not
fixed — the gate only stops NEW per-parameter dispatch loops.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import (FileContext, Finding, Pass, dotted_name, in_loop,
                    register)

_JIT_FACTORIES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_JIT_CACHE_SUFFIXES = ("_jit", "_jits", "_step_cache", "_fwd_cache")
_UPDATER_NAMES = {"updater", "upd", "self._updater"}
_OPT_METHODS = {"update", "update_multi_precision"}
_KV_METHODS = {"push", "pull"}


def _callee_text(node: ast.AST) -> str:
    name = dotted_name(node)
    if name:
        return name
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - display only
        return "<call>"


def _is_jit_cache_subscript(node: ast.AST) -> bool:
    if not isinstance(node, ast.Subscript):
        return False
    base = dotted_name(node.value) or ""
    tail = base.rsplit(".", 1)[-1]
    return tail.endswith(_JIT_CACHE_SUFFIXES) or tail.isupper() and "JIT" in tail


def _jit_bound_names(func_node: ast.AST) -> set:
    """Names assigned from ``jax.jit(...)`` within this function body."""
    names = set()
    for node in ast.walk(func_node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and dotted_name(node.value.func) in _JIT_FACTORIES:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


@register
class PerParamJitPass(Pass):
    name = "perparam-jit"
    description = ("jitted-call / optimizer / kvstore dispatch inside a "
                   "per-parameter loop (fuse over the tree instead)")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        from ..core import enclosing_function

        jit_names_cache = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not in_loop(node):
                continue
            f = node.func

            # jax.jit(...)(...) / self._fused(...)(...) in one expression
            if isinstance(f, ast.Call):
                inner = dotted_name(f.func) or ""
                if inner in _JIT_FACTORIES or inner.endswith("._fused"):
                    yield ctx.finding(
                        node, self.name,
                        "`%s(...)(...)` dispatches one compiled call per "
                        "loop iteration" % inner)
                    continue

            # jit-cache subscript invocation: self._step_cache[k](...)
            if _is_jit_cache_subscript(f):
                yield ctx.finding(
                    node, self.name,
                    "jit-cache dispatch `%s(...)` inside a loop"
                    % _callee_text(f))
                continue

            # name bound from jax.jit(...) in the same function
            if isinstance(f, ast.Name):
                fn = enclosing_function(node)
                if fn is not None:
                    if fn not in jit_names_cache:
                        jit_names_cache[fn] = _jit_bound_names(fn)
                    if f.id in jit_names_cache[fn]:
                        yield ctx.finding(
                            node, self.name,
                            "`%s(...)` (bound from jax.jit) dispatches one "
                            "compiled call per loop iteration" % f.id)
                        continue

            name = dotted_name(f) or ""
            recv, _, attr = name.rpartition(".")
            recv_tail = recv.rsplit(".", 1)[-1].lower()

            # per-parameter optimizer dispatch; receiver must literally be
            # optimizer-named — short names like `opt`/`o` collide with
            # ordinary dict.update() merges and would red-flag valid code
            if (attr in _OPT_METHODS and "optimizer" in recv_tail) \
                    or name in _UPDATER_NAMES:
                yield ctx.finding(
                    node, self.name,
                    "per-parameter optimizer dispatch `%s(...)` in a loop — "
                    "route through fastpath.fused_apply" % name)
                continue

            # per-key kvstore exchange
            if attr in _KV_METHODS and "kv" in recv_tail:
                yield ctx.finding(
                    node, self.name,
                    "per-key kvstore `%s(...)` in a loop — batch through "
                    "pushpull_multi" % name)
