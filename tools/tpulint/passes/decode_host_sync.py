"""decode-host-sync: device->host syncs in decode-plane code.

The decode engine (``mxnet_tpu/serving/decode.py``) runs one jitted step
per output token. At that cadence a device->host transfer is not an
occasional cost — it is a PER-TOKEN stall that serializes every tick of
every live sequence, the single easiest way to ruin decode throughput.
The generic ``host-sync`` pass only fires inside syntactic loops or jit
contexts; a decode engine hides its loop behind a worker thread, so its
per-token syncs sit in straight-line methods the loop pass cannot see.

This pass takes the cadence from the NAME SCOPE instead: any sync call
inside a function whose name says it runs per token — ``decode*`` /
``generate*`` (or ``_decode``/``_generate``-suffixed), or any method of a
class whose name contains ``Decode`` — is flagged, loop or no loop.

Flagged calls: ``fetch_host(...)`` / ``jax.device_get(...)`` and the
``.asnumpy()`` / ``.item()`` / ``.tolist()`` methods.

The decode plane keeps exactly one justified per-token sync — fetching
the tick's sampled token ids, which MUST reach the host for EOS/stop
checks and feedback — plus one per-sequence fetch at prefill. Those are
baselined with their justification in the source; the gate stops NEW
per-token syncs (logits peeks, per-slot scalar reads, debug fetches)
from creeping into the plane.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import (FileContext, Finding, Pass, ancestors, dotted_name,
                    register)

_SYNC_METHODS = {"asnumpy", "item", "tolist"}
_SYNC_CALLS = {"fetch_host", "device_get"}
# word-start match so `imdecode` (image decoding, host-side by nature)
# stays out of scope while `decode`, `_decode_step`, `generate_tokens`,
# `reference_generate` are in
_SCOPE_FN = re.compile(r"(^|_)(decode|generate)")


def _decode_scope(node: ast.AST):
    """The decode-plane scope name enclosing ``node``, or None."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _SCOPE_FN.search(anc.name):
                return anc.name
        if isinstance(anc, ast.ClassDef) and "Decode" in anc.name:
            return anc.name
    return None


@register
class DecodeHostSyncPass(Pass):
    name = "decode-host-sync"
    description = ("device->host sync (fetch_host/asnumpy/.item) in "
                   "decode-plane code — a per-token stall; batch it or "
                   "baseline the justified site")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            scope = _decode_scope(node)
            if scope is None:
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS:
                yield ctx.finding(
                    node, self.name,
                    "`.%s()` in decode-plane code runs per token — "
                    "a device->host stall every tick" % node.func.attr)
                continue
            fname = dotted_name(node.func) or ""
            if fname.rsplit(".", 1)[-1] in _SYNC_CALLS:
                yield ctx.finding(
                    node, self.name,
                    "`%s()` in decode-plane code runs per token — "
                    "a device->host stall every tick"
                    % fname.rsplit(".", 1)[-1])
