"""non-atomic-write: bare writes to checkpoint-ish paths outside the
atomic-commit helpers.

The elastic checkpoint protocol (``mxnet_tpu/elastic.py``) is
tmp + fsync + ``os.replace`` + directory-fsync, manifest committed last —
a crash or preemption at ANY moment leaves either the old state or the
new, never a readable-but-torn file. That guarantee only holds if every
write to checkpoint-shaped storage routes through the helpers
(``CheckpointManager._atomic_write``/``_commit``/``_commit_bytes``). A
bare ``open(path, "w")``/``np.save``/``pickle.dump`` straight onto a
checkpoint path re-introduces the torn-write window the PR-4/PR-9 chaos
gates exist to rule out: a kill between ``open`` and ``close`` leaves a
truncated file under the committed name.

Flagged in ``mxnet_tpu/``:

- ``open(path, "w"/"wb"/"a"/"ab")`` where the path expression or the
  enclosing function name reads checkpoint-ish (``ckpt``, ``checkpoint``,
  ``manifest``, ``shard``, ``save_states``, ``save_checkpoint``,
  ``optimizer_states``, ``save_parameters``, ``snapshot``, or a bare
  ``save``/``dump`` function);
- ``np.save``/``np.savez[_compressed]`` and ``pickle.dump`` under the
  same path/function test.

Exempt: code nested (lexically) inside a call to ``_atomic_write``/
``_commit``/``_commit_bytes`` (the writer lambdas), and the bodies of
functions by those names — the helpers ARE the implementation.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ..core import (FileContext, Finding, Pass, ancestors, dotted_name,
                    enclosing_function, register)

_WRITE_MODES = ("w", "wb", "a", "ab", "wt", "w+", "wb+", "w+b")

_CKPT_PATH_RE = re.compile(
    r"ckpt|checkpoint|manifest|shard|states|snapshot|\.params")
_CKPT_FN_RE = re.compile(
    r"ckpt|checkpoint|manifest|shard|snapshot|save_states|"
    r"save_checkpoint|optimizer_states|save_parameters|^save$|^_save")

_HELPERS = ("_atomic_write", "_commit", "_commit_bytes")

_NP_SAVERS = {"np.save", "np.savez", "np.savez_compressed",
              "numpy.save", "numpy.savez", "numpy.savez_compressed"}
_PICKLE_DUMPERS = {"pickle.dump", "cPickle.dump"}


def _expr_is_ckpt(node: ast.AST) -> bool:
    """Whether the path expression names checkpoint-ish storage: an
    identifier, attribute, call name or string constant matching the
    checkpoint vocabulary."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            name = sub.value
        if name and _CKPT_PATH_RE.search(name.lower()):
            return True
    return False


def _fn_name(node: ast.AST) -> Optional[str]:
    fn = enclosing_function(node)
    return getattr(fn, "name", None) if fn is not None else None


def _in_helper(node: ast.AST) -> bool:
    """Inside an atomic-commit helper: the helper function's own body, or
    a writer lambda/def passed (lexically) into a ``_commit``-family
    call."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and anc.name in _HELPERS:
            return True
        if isinstance(anc, ast.Call):
            tail = (dotted_name(anc.func) or "").rsplit(".", 1)[-1]
            if tail in _HELPERS:
                return True
    return False


def _is_ckpt_site(node: ast.Call, path_arg: Optional[ast.AST]) -> bool:
    if path_arg is not None and _expr_is_ckpt(path_arg):
        return True
    fn = _fn_name(node)
    return bool(fn and _CKPT_FN_RE.search(fn.lower()))


@register
class NonAtomicWritePass(Pass):
    name = "non-atomic-write"
    description = ("bare open(w)/np.save/pickle.dump onto checkpoint-ish "
                   "paths outside the _atomic_write/_commit helpers — "
                   "a crash mid-write leaves a torn file under a "
                   "committed name")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name == "open" and len(node.args) >= 2:
                mode = node.args[1]
                if not (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and mode.value in _WRITE_MODES):
                    continue
                if _in_helper(node) or not _is_ckpt_site(node, node.args[0]):
                    continue
                yield ctx.finding(
                    node, self.name,
                    "bare open(..., %r) onto a checkpoint-ish path — "
                    "commit through CheckpointManager._atomic_write/"
                    "_commit (tmp+fsync+rename, manifest last)"
                    % mode.value)
            elif name in _NP_SAVERS or name in _PICKLE_DUMPERS:
                path_arg = None
                if name in _NP_SAVERS and node.args:
                    path_arg = node.args[0]
                elif name in _PICKLE_DUMPERS and len(node.args) >= 2:
                    path_arg = node.args[1]
                if _in_helper(node) or not _is_ckpt_site(node, path_arg):
                    continue
                yield ctx.finding(
                    node, self.name,
                    "bare `%s(...)` onto a checkpoint-ish path — commit "
                    "through CheckpointManager._atomic_write/_commit "
                    "(tmp+fsync+rename, manifest last)" % name)
