"""unbounded-queue: consumer queues constructed without a bound.

The PR-2/PR-7 serving discipline — carried into the PR-13 multi-tenant
control plane — is that every producer/consumer queue inside
``mxnet_tpu/`` is *bounded*, with an explicit shed
(``QueueFullError``) when the bound is hit: under overload the system
answers fewer requests fast instead of buffering all requests until
memory or latency dies. An unbounded ``queue.Queue()`` or a
``collections.deque()`` used as a queue silently re-introduces the
failure mode (RAM-backed infinite backlog, tail latency unbounded).

Flagged in ``mxnet_tpu/``:

- any ``*Queue(...)`` construction (``queue.Queue``, ``ctx.Queue``,
  ``multiprocessing.Queue``, ``LifoQueue``, ...) with neither a
  positional size nor ``maxsize=`` — a Queue class IS a consumer queue,
  whatever the target name;
- ``deque()`` / ``collections.deque()`` without a ``maxlen`` (or with a
  literal ``maxlen=None``) assigned to a queue-named target (the name
  contains ``queue``, ends in ``_q``, or is ``q``) — deques are also
  general containers, so only queue-shaped uses are in scope.

A bound that is *enforced by a check before append* (the serving
batcher idiom) still wants ``maxlen=`` as the structural backstop — the
tenancy sub-queues do exactly that; sites where the bound genuinely
lives elsewhere ride the baseline with a justification.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Finding, Pass, dotted_name, register

_DEQUE_NAMES = {"deque", "collections.deque"}


def _target_name(node: ast.AST) -> Optional[str]:
    """The name a constructed value is bound to: plain name, attribute
    tail (``self._task_q`` -> ``_task_q``), or the container's name for
    a subscript (``self._queues[tid]`` -> ``_queues``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _target_name(node.value)
    return None


def _queue_ish(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return "queue" in low or low.endswith("_q") or low == "q"


def _kw(call: ast.Call, name: str) -> Optional[ast.keyword]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def _queue_unbounded(call: ast.Call) -> bool:
    """``Queue()`` with no positional size and no maxsize= (or a literal
    maxsize=None/0 — stdlib treats <= 0 as infinite)."""
    if call.args:
        return False
    kw = _kw(call, "maxsize")
    if kw is None:
        return True
    return isinstance(kw.value, ast.Constant) and kw.value.value in (None, 0)


def _deque_unbounded(call: ast.Call) -> bool:
    """``deque()`` with no second positional (maxlen) and no maxlen= (or
    a literal maxlen=None)."""
    if len(call.args) >= 2:
        return False
    kw = _kw(call, "maxlen")
    if kw is None:
        return True
    return isinstance(kw.value, ast.Constant) and kw.value.value is None


@register
class UnboundedQueuePass(Pass):
    name = "unbounded-queue"
    description = ("queue.Queue()/deque() consumer queues constructed "
                   "without a bound in mxnet_tpu/ — unbounded backlog "
                   "defers the overload failure from an explicit shed "
                   "to an OOM/latency collapse")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            name = dotted_name(value.func) or ""
            tail = name.rsplit(".", 1)[-1]
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            tnames = [_target_name(t) for t in targets]
            if tail.endswith("Queue"):
                if _queue_unbounded(value):
                    yield ctx.finding(
                        node, self.name,
                        "unbounded `%s()` consumer queue — give it a "
                        "bound (maxsize=) and shed explicitly when full "
                        "(the bounded-queue serving discipline)" % name)
            elif name in _DEQUE_NAMES:
                if any(_queue_ish(t) for t in tnames) \
                        and _deque_unbounded(value):
                    yield ctx.finding(
                        node, self.name,
                        "unbounded `%s()` bound to a queue-named target "
                        "(%s) — give it maxlen= (belt-and-braces even "
                        "when a depth check sheds first)"
                        % (name, "/".join(t for t in tnames if t)))
