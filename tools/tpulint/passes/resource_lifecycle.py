"""resource-lifecycle: paired acquire/release checking for the serving
stack's owned resources.

The PR-13/14 resources all follow the same discipline — KV pages
(``reserve``/``admit_prefix`` vs ``free``, CoW-refcounted), tenant page
budgets (``charge_pages``/``release_pages``), token buckets
(``take_tokens``/``refund_tokens``), breaker probe leases (``allow()``
vs ``on_success``/``on_failure``) — and the leak shape is always the
same: an exception edge or early return between the acquire and its
release. A leaked page is capacity gone until restart; a leaked probe
lease wedges a breaker in half-open; a leaked token charge starves the
tenant that paid it. The protocol table lives in
:data:`tools.tpulint.locks.PROTOCOLS`, so follow-on planes (fleet page
export, disaggregated prefill) register their hand-offs as first-class
transfers rather than teaching this pass new idioms.

The checker is path-sensitive where it matters: guard polarity
(``if not take_tokens(): return`` acquires only after the guard),
``try``/``finally``-or-handler protection (a cleanup that transitively
releases — ``_release_slot`` frees pages AND budget — protects the whole
window), and the ``donation_prep`` idiom that *a consuming call is the
sanctioned last touch*: declared transfer tails, a store into a ``self``
container (``self._slots[slot] = req`` moves ownership to the object),
and caller protection (every resolved call site sits under a catch-all
that evicts-then-frees). Protocol implementation files are exempt —
they are the audited internals, with ``MXNET_KVCACHE_AUDIT=1`` as the
runtime twin re-proving the refcount invariant per tick.

Deliberate hand-offs across function boundaries that the analysis
cannot prove (admission guards that charge on behalf of the engine) are
carried as justified baseline entries, not silenced — same policy as
shared-state-race.
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import locks


@register
class ResourceLifecyclePass(Pass):
    name = "resource-lifecycle"
    description = ("acquired resources (KV pages, budget charges, probe "
                   "leases) leaked on exception edges or early returns — "
                   "no finally, no owner transfer")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = locks.analyze(graph)
        for rec in ana.lifecycle_findings.get(ctx.relpath, ()):
            yield ctx.finding(rec.node, self.name, rec.message())
