"""shared-state-race: unlocked cross-thread access to instance state.

The planes that keep the TPU fed all run worker threads — the serving
batcher/decode workers, the telemetry Emitter, the io prefetchers, the
elastic host-engine commits — and the invariants protecting their shared
state are enforced today only by convention (``_atomic_write``,
per-metric locks, careful field discipline). The bug class PR 5-9 kept
fixing by hand is a field mutated on the worker and read by the caller
with no lock on one side: it works in CPython most of the time, then a
torn multi-field update or a stale read shows up as a poisoned prefetch
or an emitter race under load.

This pass combines the whole-program **thread-context lattice**
(:mod:`tools.tpulint.graph` — seeded at ``threading.Thread(target=...)``
sites, ``run`` methods of Thread subclasses, and engine-push callbacks,
closed over calls) with per-class lexical lock tracking:

- for every class, every ``self.X`` attribute **write** in a method that
  runs in thread context is paired against every ``self.X`` access
  (read or write) in a method that does not;
- each access carries the set of locks held lexically around it
  (``with self._lock:`` — any ``self`` attribute whose name reads
  lock-ish counts, including via ``self._lock:`` condition objects);
- the pair is a finding when the two sides hold **no common lock**;
  one finding per (class, attribute), reported at the thread-side write.

``__init__`` (and ``__new__``) accesses are exempt: construction happens
before the worker starts, by the ``Thread.start()`` happens-before edge.
Lock-named attributes themselves are exempt (assigning the lock is
setup, not shared state).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import (FileContext, Finding, Pass, ancestors, dotted_name,
                    register)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOCKISH = ("lock", "mutex", "cond", "_cv", "_mu", "sem")
_EXEMPT_METHODS = {"__init__", "__new__"}


def _lockish(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _LOCKISH)


def _locks_held(node: ast.AST, method: ast.AST) -> frozenset:
    """Lock names (dotted, e.g. ``self._lock``) held lexically at `node`
    within `method` via ``with`` blocks."""
    held: Set[str] = set()
    for anc in ancestors(node):
        if anc is method:
            break
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                d = dotted_name(item.context_expr)
                if d is None and isinstance(item.context_expr, ast.Call):
                    d = dotted_name(item.context_expr.func)
                if d and _lockish(d.rsplit(".", 1)[-1]):
                    held.add(d)
    return frozenset(held)


class _Access:
    __slots__ = ("method", "scope", "locks", "is_write", "node",
                 "threaded", "exempt")

    def __init__(self, method, scope, locks, is_write, node,
                 threaded, exempt):
        self.method = method      # the class-level method owning the code
        self.scope = scope        # nearest enclosing function (may be nested)
        self.locks = locks
        self.is_write = is_write
        self.node = node
        self.threaded = threaded
        self.exempt = exempt


@register
class SharedStateRacePass(Pass):
    name = "shared-state-race"
    description = ("instance attribute written from thread context and "
                   "accessed from non-thread context with no common lock "
                   "held on both sides")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._scan_class(ctx, graph, node)

    def _scan_class(self, ctx, graph, cls) -> Iterator[Finding]:
        accesses: Dict[str, List[_Access]] = {}
        for method in cls.body:
            if not isinstance(method, _FUNCS):
                continue
            for attr, acc in self._method_accesses(graph, method):
                accesses.setdefault(attr, []).append(acc)

        for attr in sorted(accesses):
            if _lockish(attr):
                continue
            group = accesses[attr]
            # construction writes are exempt on BOTH sides: an object
            # built ON the worker (e.g. a batch) publishes through a
            # queue/join edge before anyone else can see it
            thread_writes = [a for a in group
                             if a.is_write and a.threaded and not a.exempt]
            other = [a for a in group if not a.threaded and not a.exempt]
            hit = self._unlocked_pair(thread_writes, other)
            if hit is None:
                continue
            tw, oa = hit
            entry = graph.thread_entry(tw.scope) \
                or graph.thread_entry(tw.method) or "?"
            yield ctx.finding(
                tw.node, self.name,
                "`self.%s` is written on a worker thread (%s.%s, entered "
                "via `%s`) and %s without a common lock from %s.%s — "
                "guard both sides with one lock or confine the field to "
                "the worker" % (
                    attr, cls.name, tw.method.name, entry,
                    "written" if oa.is_write else "read",
                    cls.name, oa.method.name))

    @staticmethod
    def _unlocked_pair(thread_writes: List[_Access], other: List[_Access]
                       ) -> Optional[Tuple[_Access, _Access]]:
        for tw in thread_writes:
            for oa in other:
                if not (tw.locks & oa.locks):
                    return tw, oa
        return None

    def _method_accesses(self, graph, method) -> Iterator[Tuple[str, _Access]]:
        """(attr, access) for every ``self.X`` touch in `method`.

        Thread context is taken from the *nearest* enclosing function: a
        closure defined inside ``__init__`` and handed to
        ``threading.Thread(target=...)`` runs on the worker even though
        ``__init__`` itself does not — and only accesses that really run
        during construction get the pre-``start()`` exemption."""
        method_threaded = graph.is_threaded(method)
        for node in ast.walk(method):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            # `self.x += 1` parses the target as Store; reads in Load.
            # Attribute *method calls* (`self._q.put(x)`) are a Load of
            # the attr — mutation of its referent is out of scope (the
            # referent, e.g. a Queue, owns its own locking).
            scope = _nearest_func(node, method)
            threaded = method_threaded or graph.is_threaded(scope)
            # only code in __init__'s OWN body runs during construction —
            # a closure defined there executes whenever it is called
            # (possibly on the worker it was handed to)
            exempt = (method.name in _EXEMPT_METHODS and scope is method)
            locks = _locks_held(node, method)
            yield node.attr, _Access(method, scope, locks, is_write, node,
                                     threaded, exempt)


def _nearest_func(node: ast.AST, method: ast.AST) -> ast.AST:
    for anc in ancestors(node):
        if isinstance(anc, _FUNCS + (ast.Lambda,)):
            return anc
        if anc is method:
            break
    return method
