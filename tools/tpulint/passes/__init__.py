"""tpulint analysis passes. Importing this package populates
``tpulint.core.REGISTRY`` via the ``@register`` decorator in each module."""
from . import decode_host_sync  # noqa: F401
from . import dtype_drift  # noqa: F401
from . import eager_step  # noqa: F401
from . import env_knob  # noqa: F401
from . import host_sync  # noqa: F401
from . import native_guard  # noqa: F401
from . import non_atomic_write  # noqa: F401
from . import perparam_jit  # noqa: F401
from . import replicated_state  # noqa: F401
from . import shared_state_race  # noqa: F401
from . import swallowed_error  # noqa: F401
from . import traced_host_sync  # noqa: F401
from . import tracer_leak  # noqa: F401
from . import use_after_donate  # noqa: F401
