"""sharding-flow: mesh-axis and donation-layout consistency checks.

GSPMD sharding annotations are stringly-typed: a ``PartitionSpec`` axis
name is only checked against the enclosing mesh *at run time, on the
mesh that happens to be live* — the CPU tier-1 suite runs 1-2 device
meshes whose axis set ("dp", "dev") silently tolerates a typo that the
production slice rejects (or worse, replicates over). Donation has the
same failure shape: a donated operand whose declared layout matches no
declared output layout cannot have its buffer reused, so XLA inserts a
silent copy and the donation saves nothing — the 2x-HBM spike returns
with no error anywhere.

Whole-program checks (the axis-definition set is collected over the
entire lint scope — ``parallel.device_mesh`` defines "dp" for
``trainplane.py`` to use):

- **undefined mesh axis**: a string axis name used in ``PartitionSpec``
  / ``P(...)``, ``psum``/``pmean``/``all_gather``/``ppermute``/
  ``axis_index``/``all_to_all`` collectives, or ``axis_name=`` /
  ``dp_axis=`` keyword arguments, that no ``Mesh(...)``,
  ``axis_names=...`` argument or axis-parameter default anywhere in the
  lint scope defines;
- **donated layout mismatch**: a ``jax.jit`` call carrying
  ``donate_argnums`` *and* literal ``in_shardings``/``out_shardings``
  where a donated operand's declared sharding matches no declared
  output sharding — the silent-copy hazard above (the common
  state-threading jits that declare only ``out_shardings`` are skipped:
  no declared input layout, nothing to contradict).

``P`` is treated as ``PartitionSpec`` only in files that import it as
such, so a stray single-letter helper cannot alias into the check.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..core import (FileContext, Finding, Pass, dotted_name,
                    enclosing_function, register)
from ..shapes import resolve_name

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
                "axis_index", "all_to_all", "psum_scatter"}
_AXIS_KWARGS = {"axis_name", "dp_axis"}
_AXIS_DEF_PARAMS = {"axis_name", "axis_names", "dp_axis"}


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _axis_strings(node: ast.AST) -> List[str]:
    """All string constants in an axis-names expression (str, tuple or
    list of str)."""
    s = _str_const(node)
    if s is not None:
        return [s]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [v for e in node.elts for v in _axis_strings(e)]
    return []


def _p_is_partitionspec(tree: ast.AST) -> bool:
    """Whether this module binds the name ``P`` to PartitionSpec."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec" and alias.asname == "P":
                    return True
    return False


def collect_defined_axes(graph) -> Set[str]:
    """Mesh axis names defined anywhere in the lint scope: ``Mesh(devs,
    ("dp",))`` positional/keyword tuples, ``axis_names=``/``axis_name=``
    call arguments, and axis-parameter defaults (``def device_mesh(...,
    axis_names=("dp",))`` — the framework's own constructors). Memoized
    per project graph."""
    cached = getattr(graph, "_tpulint_defined_axes", None)
    if cached is not None:
        return cached
    axes: Set[str] = set()
    for minfo in graph.modules.values():
        for node in ast.walk(minfo.tree):
            if isinstance(node, ast.Call):
                tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                # only mesh CONSTRUCTORS define axes — an `axis_name=`
                # kwarg on a collective is a USE and must not legitimize
                # its own (possibly typo'd) axis
                if "mesh" not in tail.lower():
                    continue
                if tail in ("Mesh", "make_mesh") and len(node.args) >= 2:
                    axes.update(_axis_strings(node.args[1]))
                for kw in node.keywords:
                    if kw.arg in ("axis_names", "axis_name"):
                        axes.update(_axis_strings(kw.value))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                # defaults align to the TAIL of posonly+positional params
                pos = args.posonlyargs + args.args
                pos_defaults = [None] * (len(pos) - len(args.defaults)) \
                    + list(args.defaults)
                for a, d in zip(pos + args.kwonlyargs,
                                pos_defaults + list(args.kw_defaults)):
                    if d is not None and a.arg in _AXIS_DEF_PARAMS:
                        axes.update(_axis_strings(d))
    graph._tpulint_defined_axes = axes
    return axes


def _spec_repr(node: ast.AST) -> Optional[str]:
    """Canonical layout of a sharding expression for comparison:
    ``NamedSharding(mesh, spec)`` unwraps to its spec, and
    ``P(...)``/``PartitionSpec(...)`` normalize to their axis-argument
    tuple — so spelling variants of the same layout compare equal. An
    expression with no recognizable spec shape returns None: the caller
    bails rather than text-compare apples to oranges."""
    if isinstance(node, ast.Call):
        tail = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
        if tail == "NamedSharding" and len(node.args) >= 2:
            return _spec_repr(node.args[1])
        if tail in ("P", "PartitionSpec"):
            # PartitionSpec pads unmentioned trailing dims with None:
            # P("dp") == P("dp", None) — strip the padding first
            args = list(node.args)
            while args and isinstance(args[-1], ast.Constant) \
                    and args[-1].value is None:
                args.pop()
            return "spec(%s)" % ", ".join(ast.dump(a) for a in args)
    return None


@register
class ShardingFlowPass(Pass):
    name = "sharding-flow"
    description = ("mesh-axis names no enclosing mesh defines, and "
                   "donated operands whose declared in/out layouts "
                   "differ (silent-copy hazard)")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        defined = collect_defined_axes(graph)
        p_is_spec = _p_is_partitionspec(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            tail = fname.rsplit(".", 1)[-1]
            for axis, where in self._axis_uses(node, tail, p_is_spec):
                if axis not in defined:
                    yield ctx.finding(
                        node, self.name,
                        "mesh axis '%s' used in %s but no Mesh/axis_names "
                        "definition in the lint scope declares it — on the "
                        "real mesh this raises (or silently replicates) "
                        "instead of sharding" % (axis, where))
            if tail in ("jit", "pjit"):
                yield from self._check_donation(ctx, node)

    # ------------------------------------------------------------------
    def _axis_uses(self, node: ast.Call, tail: str,
                   p_is_spec: bool) -> Iterator[Tuple[str, str]]:
        if tail == "PartitionSpec" or (tail == "P" and p_is_spec):
            for a in node.args:
                s = _str_const(a)
                if s is not None:
                    yield s, "`PartitionSpec`"
                elif isinstance(a, (ast.Tuple, ast.List)):
                    for s in _axis_strings(a):
                        yield s, "`PartitionSpec`"
        elif tail in _COLLECTIVES:
            if len(node.args) >= 2:
                s = _str_const(node.args[1])
                if s is not None:
                    yield s, "`%s` collective" % tail
            elif len(node.args) == 1 and tail == "axis_index":
                s = _str_const(node.args[0])
                if s is not None:
                    yield s, "`%s` collective" % tail
        for kw in node.keywords:
            if kw.arg in _AXIS_KWARGS:
                s = _str_const(kw.value)
                if s is not None:
                    yield s, "`%s=` argument" % kw.arg

    def _check_donation(self, ctx: FileContext,
                        node: ast.Call) -> Iterator[Finding]:
        donate = in_sh = out_sh = None
        for kw in node.keywords:
            if kw.arg == "donate_argnums":
                donate = kw.value
            elif kw.arg == "in_shardings":
                in_sh = kw.value
            elif kw.arg == "out_shardings":
                out_sh = kw.value
            elif kw.arg in ("static_argnums", "static_argnames"):
                # static args shift donate_argnums relative to the
                # in_shardings (which cover dynamic args only): the
                # index mapping is unprovable here — bail
                return
        if donate is None or in_sh is None or out_sh is None:
            return
        donated: List[int] = []
        if isinstance(donate, (ast.Tuple, ast.List)):
            for e in donate.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    donated.append(e.value)
        elif isinstance(donate, ast.Constant) \
                and isinstance(donate.value, int):
            donated.append(donate.value)
        if not donated:
            return
        # resolve Name references to their local assignment before the
        # textual comparison (out_spec = P("dp") — or a Name-bound whole
        # TUPLE of specs — must compare like its literal); anything still
        # unresolved makes the check unprovable — bail rather than
        # manufacture a mismatch
        fn = enclosing_function(node)

        def layout_of(e):
            return _spec_repr(resolve_name(e, fn))

        in_sh = resolve_name(in_sh, fn)
        out_sh = resolve_name(out_sh, fn)
        if not isinstance(in_sh, (ast.Tuple, ast.List)):
            return
        outs = out_sh.elts if isinstance(out_sh, (ast.Tuple, ast.List)) \
            else [out_sh]
        out_reprs = {layout_of(o) for o in outs}
        if None in out_reprs:  # an out layout we can't normalize: bail
            return
        for i in donated:
            if not (0 <= i < len(in_sh.elts)):
                continue
            spec_i = layout_of(in_sh.elts[i])
            if spec_i is None:
                continue
            if spec_i not in out_reprs:
                yield ctx.finding(
                    in_sh.elts[i], self.name,
                    "donated operand %d's declared in_sharding matches no "
                    "declared out_sharding — XLA cannot reuse the donated "
                    "buffer and inserts a silent copy (the donation saves "
                    "nothing; align the layouts or drop the donation)" % i)
