"""dtype-drift: bare float64 literals outside the dtype registry.

TPUs have no native float64: with ``jax_enable_x64`` off (the default) a
``jnp.float64`` request silently *downcasts* to float32, and with it on the
compiler emulates doubles at a large throughput cost. Either way a stray
``np.float64`` that worked on the CPU tier-1 suite misbehaves on the chip.
All dtype choices are supposed to flow through the registry in
``mxnet_tpu/base.py`` (``DTYPE_NP``), where the policy lives in one place.

Flagged: attribute literals ``np.float64`` / ``numpy.float64`` /
``jnp.float64`` / ``jax.numpy.float64`` anywhere except inside the
``DTYPE_NP`` registry assignment itself. Intentional uses (host-side
accumulators, wire-format tables) carry an inline suppression or a baseline
entry with the justification next to them.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import (FileContext, Finding, Pass, ancestors, dotted_name,
                    register)

_F64_NAMES = {"np.float64", "numpy.float64", "jnp.float64", "jax.numpy.float64"}
_REGISTRY_TARGETS = {"DTYPE_NP"}


def _in_registry_assign(node: ast.AST) -> bool:
    for anc in ancestors(node):
        if isinstance(anc, ast.Assign):
            for target in anc.targets:
                if isinstance(target, ast.Name) and target.id in _REGISTRY_TARGETS:
                    return True
    return False


@register
class DtypeDriftPass(Pass):
    name = "dtype-drift"
    description = "bare np/jnp.float64 literals outside the DTYPE_NP registry"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                full = dotted_name(node)
                if full in _F64_NAMES and not _in_registry_assign(node):
                    yield ctx.finding(node, self.name,
                                      "bare `%s` outside the DTYPE_NP registry — "
                                      "float64 is emulated or silently downcast on "
                                      "TPU" % full)
