"""traced-host-sync: host syncs/impurities *reachable* from traced code.

The file-local ``host-sync`` pass sees a ``.asnumpy()`` inside a jitted
function only when the jit wrap and the sync share a file — PR 5-9 kept
finding the other shape by hand: a sync buried two frames below a traced
``_leaf_step``, or inside a helper a whole-step jit inlines from another
module. This pass walks the whole-program **traced-context lattice**
(:mod:`tools.tpulint.graph`): a function is traced when it is seeded at a
``jax.jit``/``pl.pallas_call`` site or a known kernel entry point
(``_leaf_step``/``tree_kernel``) or called — to a bounded depth — from
one that is.

Flagged inside traced context, anywhere in ``mxnet_tpu/``:

- ``.asnumpy()``/``.item()``/``.tolist()``/``.wait_to_read()``/
  ``.block_until_ready()`` and ``fetch_host(...)``/``jax.device_get(...)``
  — concretize the tracer at trace time (error, or a stale constant baked
  into the compiled program);
- ``float(...)``/``int(...)`` on a computed value, and
  ``np.asarray``/``np.array`` — same trace-time materialization;
- ``get_env(..., cache=False)`` — the knob is *designed* to be re-read
  per call, but under tracing it is read once and frozen: the program
  silently stops honoring the knob;
- lock acquisition (``with self._lock:`` / ``.acquire()``) — the lock is
  taken at trace time and never inside the compiled step: the guard the
  author wrote does not exist at runtime.

Sites already covered by the file-local pass (lexically inside a
same-file jit closure) are skipped — this pass reports only what the
whole-program lattice adds, so existing baselines don't double.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import (FileContext, Finding, Pass, dotted_name,
                    enclosing_function, in_jit, register)

_SYNC_METHODS = {"asnumpy", "item", "tolist", "wait_to_read",
                 "block_until_ready"}
_FETCH_TAILS = {"fetch_host", "device_get"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_SCALAR_SAFE_CALLEES = {"len", "str", "ord", "round", "hash", "id"}
_LOCKISH = ("lock", "mutex", "cond", "_cv", "_mu")


def _lockish(name: Optional[str]) -> bool:
    low = (name or "").lower()
    return any(t in low for t in _LOCKISH)


def _classify_call(node: ast.Call) -> Optional[str]:
    """A short description of why this call is a trace-time hazard, or
    None."""
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
        return "`.%s()`" % node.func.attr
    fname = dotted_name(node.func) or ""
    tail = fname.rsplit(".", 1)[-1]
    if tail in _FETCH_TAILS:
        return "`%s()`" % tail
    if fname in _NP_CONVERTERS:
        return "`%s()`" % fname
    if fname in ("float", "int") and len(node.args) == 1 \
            and isinstance(node.args[0], ast.Call) \
            and dotted_name(node.args[0].func) not in _SCALAR_SAFE_CALLEES:
        return "`%s()` on a computed value" % fname
    if tail == "get_env":
        for kw in node.keywords:
            if kw.arg == "cache" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return "`get_env(cache=False)` (per-call re-read, frozen "\
                       "to one trace-time value)"
    if tail == "acquire" and isinstance(node.func, ast.Attribute) \
            and _lockish(dotted_name(node.func.value)):
        return "lock `.acquire()`"
    return None


@register
class TracedHostSyncPass(Pass):
    name = "traced-host-sync"
    description = ("host syncs, get_env(cache=False) re-reads and lock "
                   "acquisition reachable (interprocedurally) from "
                   "jit/pallas-traced context")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        jitted_local = ctx.jit_functions()
        for node in ast.walk(ctx.tree):
            what = None
            if isinstance(node, ast.Call):
                what = _classify_call(node)
            elif isinstance(node, ast.With):
                for item in node.items:
                    d = dotted_name(item.context_expr)
                    if d and _lockish(d.rsplit(".", 1)[-1]):
                        what = "`with %s:` lock acquisition" % d
                        break
            if what is None:
                continue
            fn = enclosing_function(node)
            if fn is None:
                continue
            chain = graph.traced_chain(fn)
            if chain is None:
                continue
            # lexically inside a same-file jit closure: the file-local
            # host-sync/tracer-leak passes own that report
            if in_jit(node, jitted_local) or fn in jitted_local:
                continue
            # name only the seed and the enclosing function (not the whole
            # chain): baseline keys embed the message, and intermediate
            # frames churn on refactors the finding shouldn't care about
            yield ctx.finding(
                node, self.name,
                "%s in `%s` runs under jax tracing (reachable from traced "
                "`%s`) — a device sync or impure effect at trace time, "
                "frozen or erroring in the compiled step"
                % (what, chain[-1], chain[0]))
