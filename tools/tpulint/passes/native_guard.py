"""native-guard: every ``_native.get_lib()`` call site must handle ``None``.

The native C++ runtime (``src/*.cc`` -> ``libmxtpu.so``) is an optional
accelerator for host-side work; the documented invariant in
``mxnet_tpu/_native.py`` is that the whole framework degrades to pure
Python when no toolchain is available — *every caller must handle
``get_lib() is None``*. A call site that dereferences the result
unconditionally turns "no g++ on this machine" into an AttributeError deep
inside IO or engine code.

A call site counts as guarded when, within the same function (or module)
scope, the result is:

- compared against ``None`` (``if lib is None: ...``, ternaries included);
- truth-tested (``if lib:``, ``if not lib:``, ``while lib``, ``assert lib``,
  or as a direct operand of ``and`` / ``or``);
- read only through ``getattr(lib, name, default)`` with a default.

Anything else — including a bare ``return get_lib()`` that forwards the
``Optional`` to callers the analysis cannot see — is flagged; forwarding
helpers whose callers all guard carry an inline suppression saying so.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import (FileContext, Finding, Pass, dotted_name, enclosing_scope,
                    parent, register)

_GET_LIB = {"get_lib", "_native.get_lib"}


def _is_name(node: ast.AST, name: str) -> bool:
    return isinstance(node, ast.Name) and node.id == name


def _guards_name(scope: ast.AST, name: str) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, right = node.left, node.comparators[0]
            none_cmp = isinstance(node.ops[0], (ast.Is, ast.IsNot))
            if none_cmp and ((_is_name(left, name) and _is_const_none(right)) or
                             (_is_const_none(left) and _is_name(right, name))):
                return True
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
            if _is_name(test, name):
                return True
            if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                    and _is_name(test.operand, name):
                return True
        elif isinstance(node, ast.Assert) and _is_name(node.test, name):
            return True
        elif isinstance(node, ast.BoolOp) and any(_is_name(v, name)
                                                  for v in node.values):
            return True
        elif isinstance(node, ast.Call) and dotted_name(node.func) == "getattr" \
                and len(node.args) == 3 and _is_name(node.args[0], name):
            return True
    return False


def _is_const_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _assigned_name(call: ast.Call) -> Optional[str]:
    p = parent(call)
    if isinstance(p, ast.Assign) and len(p.targets) == 1 \
            and isinstance(p.targets[0], ast.Name):
        return p.targets[0].id
    if isinstance(p, ast.AnnAssign) and isinstance(p.target, ast.Name):
        return p.target.id
    return None


@register
class NativeGuardPass(Pass):
    name = "native-guard"
    description = "_native.get_lib() call sites that never handle the None fallback"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func) in _GET_LIB):
                continue
            p = parent(node)
            # `get_lib() is None` / `get_lib() is not None` guards inline.
            if isinstance(p, ast.Compare) and len(p.ops) == 1 \
                    and isinstance(p.ops[0], (ast.Is, ast.IsNot)):
                continue
            name = _assigned_name(node)
            if name is not None:
                if _guards_name(enclosing_scope(node), name):
                    continue
                yield ctx.finding(node, self.name,
                                  "`%s = get_lib()` is never checked against the "
                                  "None (pure-Python) fallback in this scope" % name)
                continue
            if isinstance(p, ast.Return):
                yield ctx.finding(node, self.name,
                                  "`return get_lib()` forwards an unguarded Optional "
                                  "to callers")
                continue
            yield ctx.finding(node, self.name,
                              "get_lib() result used directly without handling the "
                              "None (pure-Python) fallback")
