"""eager-step: an eager forward/backward training step inside a loop.

The training plane behind ``MXNET_TRAINSTEP`` (``mxnet_tpu.trainplane``)
compiles the whole step — forward + loss + backward + allreduce + update —
into ONE XLA module; an eager loop body that records a forward, runs
``.backward()`` and applies an optimizer step dispatches dozens of
compiled calls per iteration instead (the regime BENCH_TPU_PARTIAL_r05
measured at 0.6% MFU even after the update plane fused). This pass flags
the shape of code that bypasses the step plane inside ``mxnet_tpu/`` so
framework-owned training loops route through ``trainplane``/``TrainStep``
(or get explicitly baselined as the eager fallback they are).

Flagged — a ``for``/``while`` loop whose body contains a full eager
training step, i.e. either:

- a ``.forward_backward(...)`` call together with an ``.update(...)``
  dispatch (the Module idiom), or
- an ``autograd.record()`` with-block AND a ``.backward(...)`` call AND a
  trainer/optimizer step (``.step(...)`` / ``.update(...)``) — the gluon
  idiom.

One finding per loop. The legit eager sites — the documented fallback
loops the graph plane demotes to — stay baselined, not fixed; the gate
only stops NEW eager training loops from growing into the framework.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Pass, dotted_name, register

_LOOPS = (ast.For, ast.AsyncFor, ast.While)


def _is_record_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func) or ""
            if name.endswith("record") or name.endswith("train_mode"):
                return True
    return False


def _scan_loop(loop: ast.AST):
    """(has_record, has_backward, has_step, has_fwd_bwd, has_update) over
    the loop body, not descending into nested function definitions."""
    has = {"record": False, "backward": False, "step": False,
           "fwd_bwd": False, "update": False}

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.With) and _is_record_with(child):
                has["record"] = True
            if isinstance(child, ast.Call):
                name = dotted_name(child.func) or ""
                attr = name.rsplit(".", 1)[-1]
                if attr == "forward_backward":
                    has["fwd_bwd"] = True
                elif attr == "backward":
                    has["backward"] = True
                elif attr == "step":
                    has["step"] = True
                elif attr == "update":
                    # metric.update(label, pred) is bookkeeping, not an
                    # optimizer step — `eval_metric.update` next to
                    # record/backward must not read as a training loop
                    recv = name.rsplit(".", 1)[0] if "." in name else ""
                    if "metric" not in recv.lower():
                        has["update"] = True
            walk(child)

    walk(loop)
    return has


@register
class EagerStepPass(Pass):
    name = "eager-step"
    description = ("eager forward/backward training step inside a loop — "
                   "route through trainplane/TrainStep (one whole-step jit)")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, _LOOPS):
                continue
            has = _scan_loop(node)
            if has["fwd_bwd"] and has["update"]:
                yield ctx.finding(
                    node, self.name,
                    "eager forward_backward()+update() training loop — "
                    "route through the MXNET_TRAINSTEP graph plane")
            elif has["record"] and has["backward"] and (
                    has["step"] or has["update"]):
                yield ctx.finding(
                    node, self.name,
                    "eager record/backward/step training loop — route "
                    "through trainplane.TrainPlane (one whole-step jit)")
