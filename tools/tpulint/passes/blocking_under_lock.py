"""blocking-under-lock: device round trips and unbounded waits inside
critical sections.

The decode engine's tick discipline is that ``_cv`` guards *bookkeeping
only* — batch swaps, slot maps, queue state — and every device->host
fetch, jit dispatch, sleep, and thread join happens outside it. One
violation serializes the whole plane: a ``fetch_host()`` under the CV
stalls submit(), close(), the SLO sampler, and every other waiter for a
full device round trip, and under load that reads as a tail-latency
cliff with no obvious owner.

This pass checks the discipline statically. The concurrency interpreter
(:mod:`tools.tpulint.locks`) knows which locks are lexically held at
every call site, and propagates a may-block summary bottom-up through
the call graph, so the flagged site is the lock-holding frame even when
the blocking call is buried two helpers deep (the finding names the
witness chain). Blocking operations: ``fetch_host`` / ``device_get`` /
``.asnumpy()`` / ``.item()`` / ``.tolist()`` / ``.block_until_ready()``
/ ``.wait_to_read()``, dispatch of a jit-wrapped project function,
``time.sleep``, ``queue.get()`` with no timeout, and ``.join()`` on a
thread-ish receiver (``str.join`` is not blocking and is never flagged).
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import locks


@register
class BlockingUnderLockPass(Pass):
    name = "blocking-under-lock"
    description = ("device->host syncs, jit dispatch, sleeps and unbounded "
                   "waits reachable with a lock held — serializes every "
                   "waiter on the critical section")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = locks.analyze(graph)
        for rec in ana.blocking_findings.get(ctx.relpath, ()):
            yield ctx.finding(rec.node, self.name, rec.message())
