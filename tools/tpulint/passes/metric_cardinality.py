"""metric-cardinality: unbounded runtime data fed into metric labels.

The telemetry naming contract (docs/observability.md) is that labels
identify *which instance of a thing*, never unbounded user data: a label
set is a SERIES, each distinct value a new one held forever by the
registry and shipped on every scrape. A label fed from a request id, a
raw prompt-derived string or exception text turns a bounded gauge into
an unbounded memory leak + scrape bomb — the classic Prometheus
cardinality explosion, invisible until production traffic arrives.

Flagged in ``mxnet_tpu/``: update calls (``inc``/``dec``/``set``/
``observe``/``observe_many``) on metric handles — module-level
``NAME = telemetry.counter/gauge/histogram(...)`` assignments, handles
reached as ``telemetry.SOME_METRIC``, or a chained
``telemetry.counter(...).inc(...)`` — whose label keyword values are:

- f-strings / ``%``-formatted / ``str.format`` strings (runtime
  interpolation into a label value),
- ``str(...)`` / ``repr(...)`` coercions (the exception-text idiom),
- names bound by an ``except ... as e`` handler,
- identifier names that *are* per-request data: ``*request_id``,
  ``*trace_id``, ``uuid``, ``prompt``-ish.

Per-tenant labels stay legal by construction: ``TenantRegistry`` bounds
the tenant-id set (spec + auto-registration under operator control), so
a keyword literally named ``tenant`` is exempt. Survivors that are
genuinely bounded some other way ride the baseline WITH a justification.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set

from ..core import FileContext, Finding, Pass, dotted_name, register

_UPDATE_METHODS = {"inc", "dec", "set", "observe", "observe_many"}
_CONSTRUCTORS = {"counter", "gauge", "histogram"}

#: keywords that are the sample value, not a label
_VALUE_KWARGS = {"value"}

#: label names bounded by construction elsewhere (TenantRegistry)
_BOUNDED_LABELS = {"tenant"}

_IDISH_RE = re.compile(
    r"(?:^|_)(?:request|trace|req|session|uuid)_?id$"
    r"|^uuid\d*$|^prompt(?:s|_text)?$|(?:^|_)prompt$",
    re.IGNORECASE)


def _constructor_call(node: ast.AST) -> bool:
    """``telemetry.counter(...)`` / ``registry.gauge(...)`` /
    ``REGISTRY.histogram(...)`` / bare ``counter(...)`` (the
    from-import spelling inside the telemetry package)."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    return name.rsplit(".", 1)[-1] in _CONSTRUCTORS


def _metric_handles(tree: ast.Module) -> Set[str]:
    """Names bound (at module or class level) to a metric constructor."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _constructor_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, ast.Attribute):
                    out.add(t.attr)
    return out


def _receiver_is_metric(func: ast.Attribute, handles: Set[str]) -> bool:
    recv = func.value
    if _constructor_call(recv):  # telemetry.counter(...).inc(...)
        return True
    name = dotted_name(recv)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail in handles:
        return True
    # cross-module handles: telemetry.RECOMPILES.inc(...) — the
    # ALL-CAPS module-constant convention every telemetry handle uses
    parts = name.split(".")
    return len(parts) >= 2 and parts[-1].isupper() and len(parts[-1]) > 1


def _except_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


def _unbounded(value: ast.AST, exc_names: Set[str]) -> Optional[str]:
    """Why this label value is unbounded runtime data (None = looks
    bounded)."""
    if isinstance(value, ast.JoinedStr):
        if any(isinstance(v, ast.FormattedValue) for v in value.values):
            return "f-string interpolation"
        return None
    if isinstance(value, ast.BinOp) and isinstance(value.op, ast.Mod):
        left = value.left
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            return "%-formatted string"
        return None
    if isinstance(value, ast.Call):
        fname = dotted_name(value.func) or ""
        tail = fname.rsplit(".", 1)[-1]
        if tail in ("str", "repr") and fname in ("str", "repr"):
            return "str()/repr() coercion (exception-text idiom)"
        if isinstance(value.func, ast.Attribute) \
                and value.func.attr == "format" and value.args:
            return "str.format interpolation"
        return None
    name = None
    if isinstance(value, ast.Name):
        name = value.id
    elif isinstance(value, ast.Attribute):
        name = value.attr
    if name is None:
        return None
    if name in exc_names:
        return "except-handler binding (exception text)"
    if _IDISH_RE.search(name):
        return "per-request identifier %r" % name
    return None


@register
class MetricCardinalityPass(Pass):
    name = "metric-cardinality"
    description = ("Counter/Gauge/Histogram label values fed from "
                   "unbounded runtime data (request ids, prompt-derived "
                   "strings, exception text) — every distinct value is a "
                   "new series the registry holds forever; labels must "
                   "come from registry-bounded sets")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        handles = _metric_handles(ctx.tree)
        exc_names = _except_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _UPDATE_METHODS:
                continue
            if not _receiver_is_metric(node.func, handles):
                continue
            for kw in node.keywords:
                if kw.arg is None or kw.arg in _VALUE_KWARGS \
                        or kw.arg in _BOUNDED_LABELS:
                    continue
                why = _unbounded(kw.value, exc_names)
                if why:
                    yield ctx.finding(
                        node, self.name,
                        "label %r of %s.%s() fed from unbounded runtime "
                        "data (%s): unbounded label values explode "
                        "series cardinality — key the label from a "
                        "registry-bounded set and put the detail in a "
                        "log/trace/flight-recorder event instead"
                        % (kw.arg,
                           dotted_name(node.func.value) or "<metric>",
                           node.func.attr, why))
