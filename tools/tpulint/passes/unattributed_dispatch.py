"""unattributed-dispatch: jit dispatch sites invisible to the perf plane.

The telemetry stack attributes everything that flows through
``telemetry.jit_call``: recompiles + compile seconds per site (PR 3),
chaos injection (PR 4) and — since the devprof plane — sampled
``block_until_ready`` device time, the decode/train host-gap
breakdowns, and the chrome-trace device lane. A jit/pallas dispatch
that bypasses the wrapper gets NONE of that: its recompiles surface
only as unexplained latency, and its device milliseconds are missing
from exactly the per-site cost model the autotuner roadmap item needs.

This pass reuses the recompile-risk interpreter's dispatch-site finder
(:class:`tools.tpulint.shapes.DispatchSite` — the same resolution that
sees direct calls of ``jax.jit`` values, jit-valued ``self._step``-style
attributes and ``@jit``-decorated functions called by name) and flags
every site in ``mxnet_tpu/`` not routed through ``telemetry.jit_call``.
A bare ``resilience.call`` around a jitted fn counts as UNattributed:
it retries the dispatch but accounts nothing.

Legitimate bypasses exist — one-shot AOT warmup dispatches, the
optimizer's fused-update plumbing where the wrapper would sit inside a
scan, engine warmup laps whose recompiles are the *point* — and live in
the baseline with justifications, same as every other pass.
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import shapes


@register
class UnattributedDispatchPass(Pass):
    name = "unattributed-dispatch"
    description = ("jit/pallas dispatch sites not routed through "
                   "telemetry.jit_call — invisible to recompile "
                   "accounting and devprof device-time attribution")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = shapes.analyze(graph)
        for site in ana.dispatch_sites.get(ctx.relpath, ()):
            if site.wrapped:
                continue
            how = {"resilience.call": "dispatches through a bare "
                                      "resilience.call, which retries but "
                                      "does not attribute",
                   "decorated": "calls a @jit-decorated function directly",
                   }.get(site.via, "dispatches a jit-compiled callable "
                                   "directly")
            yield ctx.finding(
                site.node, self.name,
                "jit dispatch `%s` %s — its recompiles and (sampled) "
                "device time are invisible to the perf attribution plane; "
                "route it as telemetry.jit_call(\"<site>\", fn, ...) or "
                "baseline it with the reason it must stay bare"
                % (site.fn_label, how))
