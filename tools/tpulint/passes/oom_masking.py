"""oom-masking: device-OOM swallowed without classification.

An HBM out-of-memory surfaces out of XLA as ``XlaRuntimeError``
(``RESOURCE_EXHAUSTED``) at a jit dispatch or a device<->host transfer.
A handler that catches those sites broadly and "handles" the error
locally — logs it, returns a default, retries — *masks* the OOM: the
pressure governor never latches red, admission keeps running at the
size that just blew up, and the next dispatch OOMs again, forever.
The survival plane only works if every catch around a dispatch/transfer
site routes the exception through :func:`mxnet_tpu.resilience.hbm.classify`
(or :func:`~mxnet_tpu.resilience.hbm.oom_survival` / the engine's
``_on_oom`` wrapper) or re-raises so an outer guarded layer can.

Flagged: an ``except`` clause in ``mxnet_tpu/`` that

* catches broadly (bare, ``Exception``/``BaseException``, or anything
  named ``*XlaRuntimeError``), AND
* guards a ``try`` body that calls a dispatch/transfer site
  (``jit_call``, ``fetch_host``, ``asnumpy``, ``device_put``,
  ``device_get``, ``block_until_ready``), AND
* whose handler neither re-raises (any ``raise``) nor calls
  ``classify`` / ``oom_survival`` / ``_on_oom`` / ``oom_sentinel``.

Handlers that re-raise conditionally still pass — routing the *decision*
is the point, not unconditionality. Sites with a justified local catch
(e.g. a debug endpoint that must answer) carry a
``# tpulint: disable=oom-masking`` or ride the baseline.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Finding, Pass, dotted_name, register

#: calls that can surface a device RESOURCE_EXHAUSTED
_DISPATCH = {"jit_call", "fetch_host", "asnumpy", "device_put",
             "device_get", "block_until_ready"}

#: handler calls that count as routing the error through the OOM plane
_ROUTES = {"classify", "oom_survival", "_on_oom", "oom_sentinel"}

_BROAD = {"Exception", "BaseException"}


def _last_part(name) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _catches_oom(type_node) -> bool:
    """Bare except, broad Exception, or an XlaRuntimeError spelling."""
    if type_node is None:
        return True
    name = dotted_name(type_node)
    if name is not None:
        last = _last_part(name)
        return last in _BROAD or last.endswith("XlaRuntimeError")
    if isinstance(type_node, ast.Tuple):
        return any(_catches_oom(elt) for elt in type_node.elts)
    return False


def _calls_in(nodes):
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node


def _guards_dispatch(try_body) -> bool:
    for call in _calls_in(try_body):
        if _last_part(dotted_name(call.func)) in _DISPATCH:
            return True
    return False


def _handler_routes(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) \
                    and _last_part(dotted_name(node.func)) in _ROUTES:
                return True
    return False


@register
class OOMMaskingPass(Pass):
    name = "oom-masking"
    description = ("broad catch around a jit dispatch/transfer site whose "
                   "handler neither classifies the OOM nor re-raises")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _guards_dispatch(node.body):
                continue
            for handler in node.handlers:
                if not _catches_oom(handler.type):
                    continue
                if _handler_routes(handler):
                    continue
                what = "bare `except:`" if handler.type is None else \
                    "`except %s:`" % (dotted_name(handler.type)
                                      or "<broad tuple>")
                yield ctx.finding(
                    handler, self.name,
                    "%s guards a jit dispatch/transfer site but the "
                    "handler neither routes through hbm.classify()/"
                    "oom_survival() nor re-raises — a device OOM is "
                    "masked here and the pressure governor never "
                    "learns" % what)
