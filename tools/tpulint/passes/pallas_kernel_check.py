"""pallas-kernel-check: static verification of ``pl.pallas_call`` sites.

A Pallas kernel that violates the TPU layout contract fails *only on
real hardware* — interpret mode (the CPU tier-1 path) checks the math,
not the tiling, so a misaligned block or an oversized VMEM footprint
ships green and dies (or silently degrades) in the next chip window.
This pass checks, at every ``pl.pallas_call`` whose parameters resolve
statically (module consts like ``LANES = 128`` and local const algebra
are folded; symbolic dims are skipped, never guessed):

- **block tile alignment**: a BlockSpec/scratch block's last dim must be
  a multiple of the 128-lane tile and its second-to-last a multiple of
  the dtype's sublane count ((8, 128) f32, (16, 128) bf16, (32, 128)
  int8 — /opt/skills/guides/pallas_guide.md), unless the dim is 1 (an
  untiled leading axis);
- **grid ↔ index_map arity**: each ``index_map`` lambda must take
  exactly ``len(grid)`` arguments plus one per scalar-prefetch operand
  (``PrefetchScalarGridSpec(num_scalar_prefetch=N)`` appends the N
  scalar refs) — an arity mismatch is a TypeError at first trace on
  device, after the CPU suite passed;
- **scalar-prefetch consistency**: ``num_scalar_prefetch`` must be a
  non-negative constant and the grid must be present when it is used;
- **VMEM budget**: the summed footprint of all const-shaped blocks
  (×2 for the in/out pipeline's double buffering) plus scratch must fit
  the ~16 MB/core VMEM ceiling; an overflow is an OOM (or a silent
  spill) the first time the kernel runs on silicon.

File-local and deliberately under-approximate: a shape the const folder
cannot resolve contributes nothing — the pass proves violations, it
does not prove kernels correct.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import (FileContext, Finding, Pass, dotted_name,
                    enclosing_function, register)
from ..shapes import (AbsValue, Dim, const_int, module_const_env,
                      resolve_name as _resolve_name)

LANES = 128
VMEM_BYTES = 16 * 1024 * 1024

#: dtype-name tail -> (sublane tile, bytes per element)
_DTYPES = {
    "float32": (8, 4), "f32": (8, 4), "int32": (8, 4), "uint32": (8, 4),
    "bfloat16": (16, 2), "float16": (16, 2), "int16": (16, 2),
    "int8": (32, 1), "uint8": (32, 1), "float8_e4m3fn": (32, 1),
    "float8_e5m2": (32, 1), "bool_": (32, 1),
    "float64": (8, 8), "int64": (8, 8),
}


def _dtype_of(expr: Optional[ast.AST]) -> Optional[str]:
    """``jnp.float32`` / ``"bfloat16"`` -> dtype-name tail."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    d = dotted_name(expr)
    if d:
        tail = d.rsplit(".", 1)[-1]
        if tail in _DTYPES:
            return tail
    return None


def _local_const_env(fn: Optional[ast.AST],
                     mod_env: Dict[str, AbsValue]) -> Dict[str, AbsValue]:
    """Module consts plus simple ``name = <const expr>`` bindings in the
    enclosing function (resolved recursively through const_int). A name
    the function assigns MORE than once is dropped entirely — folding
    either value could name a constant the code no longer holds at the
    call site (a wrong-value finding is worse than a skipped check), and
    a single local assignment shadows any same-named module const."""
    env = dict(mod_env)
    if fn is None:
        return env
    def bound_names(target):
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                yield n.id

    assigns: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns.setdefault(node.targets[0].id, []).append(node.value)
        elif isinstance(node, ast.Assign):
            for name in bound_names(node.targets[0] if len(node.targets)
                                    == 1 else ast.Tuple(elts=node.targets)):
                assigns.setdefault(name, []).append(None)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(node.target, ast.Name):
            assigns.setdefault(node.target.id, []).append(None)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            # any other binding form (loop targets, with-as, tuple
            # unpack, comprehension targets) shadows without a foldable
            # value — the name must drop out of the env, not leak the
            # stale module const
            for name in bound_names(node.target):
                assigns.setdefault(name, []).append(None)
        elif isinstance(node, ast.comprehension):
            for name in bound_names(node.target):
                assigns.setdefault(name, []).append(None)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for name in bound_names(item.optional_vars):
                        assigns.setdefault(name, []).append(None)
    once = {name: values[0] for name, values in assigns.items()
            if len(values) == 1 and values[0] is not None}
    for name in assigns:
        if name not in once:
            env.pop(name, None)  # reassigned: no trustworthy value
    folded: set = set()
    for _ in range(3):  # chase simple chains (a = 8; b = a * 2)
        changed = False
        for name, value in once.items():
            v = const_int(value, env)
            if v is not None and (name not in env
                                  or env[name].dim is None
                                  or env[name].dim.value != v):
                env[name] = AbsValue(dim=Dim.const(v))
                changed = True
            if v is not None:
                folded.add(name)
        if not changed:
            break
    for name in once:
        if name not in folded:
            # `TILE = pick_tile(x)` shadows a module-level TILE even
            # when unfoldable — the stale module value must not leak
            # into the checks
            env.pop(name, None)
    return env


class _SpecInfo:
    __slots__ = ("node", "block", "index_map", "role")

    def __init__(self, node, block, index_map, role):
        self.node = node          # the BlockSpec call
        self.block = block        # Optional[List[Optional[int]]] const dims
        self.index_map = index_map  # Optional[ast.Lambda]
        self.role = role          # "in" | "out"


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _block_dims(shape_expr: ast.AST,
                env: Dict[str, AbsValue]) -> Optional[List[Optional[int]]]:
    if not isinstance(shape_expr, (ast.Tuple, ast.List)):
        return None
    return [const_int(e, env) for e in shape_expr.elts]


def _collect_specs(expr: Optional[ast.AST], role: str, fn,
                   env: Dict[str, AbsValue]) -> List[_SpecInfo]:
    """BlockSpec calls out of an in_specs/out_specs expression (a single
    spec, or a list/tuple of them)."""
    if expr is None:
        return []
    expr = _resolve_name(expr, fn)
    items = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    out: List[_SpecInfo] = []
    for item in items:
        if not (isinstance(item, ast.Call)
                and (dotted_name(item.func) or "").rsplit(".", 1)[-1]
                == "BlockSpec"):
            continue
        block = None
        index_map = None
        if item.args:
            block = _block_dims(_resolve_name(item.args[0], fn), env)
        if len(item.args) >= 2 and isinstance(item.args[1], ast.Lambda):
            index_map = item.args[1]
        km = _kwarg(item, "index_map")
        if isinstance(km, ast.Lambda):
            index_map = km
        kb = _kwarg(item, "block_shape")
        if kb is not None:
            block = _block_dims(_resolve_name(kb, fn), env)
        out.append(_SpecInfo(item, block, index_map, role))
    return out


def _scratch_shapes(expr: Optional[ast.AST], fn,
                    env: Dict[str, AbsValue]
                    ) -> List[Tuple[ast.AST, Optional[List[Optional[int]]],
                                    Optional[str]]]:
    """``scratch_shapes=[pltpu.VMEM((a, b), jnp.float32), ...]`` ->
    (node, const dims, dtype). SMEM scratch (scalar memory, not subject
    to (sublane, lane) tiling and not drawn from the VMEM pool) is
    deliberately excluded."""
    if expr is None:
        return []
    expr = _resolve_name(expr, fn)
    if not isinstance(expr, (ast.Tuple, ast.List)):
        return []
    out = []
    for item in expr.elts:
        if not (isinstance(item, ast.Call)
                and (dotted_name(item.func) or "").rsplit(".", 1)[-1]
                == "VMEM"):
            continue
        dims = _block_dims(item.args[0], env) if item.args else None
        dtype = _dtype_of(item.args[1]) if len(item.args) >= 2 \
            else _dtype_of(_kwarg(item, "dtype"))
        out.append((item, dims, dtype))
    return out


@register
class PallasKernelCheckPass(Pass):
    name = "pallas-kernel-check"
    description = ("pl.pallas_call static verification: (8,128)/dtype "
                   "sublane block tiles, grid<->index_map arity, "
                   "scalar-prefetch consistency, ~16MB VMEM budget")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        mod_env = module_const_env(ctx.tree)
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            if (dotted_name(call.func) or "").rsplit(".", 1)[-1] \
                    != "pallas_call":
                continue
            fn = enclosing_function(call)
            env = _local_const_env(fn, mod_env)
            yield from self._check_call(ctx, call, fn, env)

    # ------------------------------------------------------------------
    def _check_call(self, ctx: FileContext, call: ast.Call, fn,
                    env: Dict[str, AbsValue]) -> Iterator[Finding]:
        grid_expr = _kwarg(call, "grid")
        num_prefetch = 0
        gs_call: Optional[ast.Call] = None
        gs_expr = _kwarg(call, "grid_spec")
        if gs_expr is not None:
            resolved = _resolve_name(gs_expr, fn)
            if isinstance(resolved, ast.Call) and (
                    dotted_name(resolved.func) or "").rsplit(".", 1)[-1] \
                    in ("PrefetchScalarGridSpec", "GridSpec"):
                gs_call = resolved
        src = gs_call if gs_call is not None else call
        if gs_call is not None:
            # PrefetchScalarGridSpec(num_scalar_prefetch, grid=...) and
            # GridSpec(grid, ...) both allow the positional spelling
            gs_tail = (dotted_name(gs_call.func) or "").rsplit(".", 1)[-1]
            pos = list(gs_call.args)
            if gs_tail == "GridSpec":
                pos.insert(0, None)  # GridSpec has no prefetch slot
            grid_expr = _kwarg(gs_call, "grid") \
                or (pos[1] if len(pos) >= 2 else None) or grid_expr
            np_expr = _kwarg(gs_call, "num_scalar_prefetch") \
                or (pos[0] if pos else None)
            if np_expr is not None:
                npv = const_int(np_expr, env)
                if npv is None or npv < 0:
                    yield ctx.finding(
                        np_expr if npv is not None else gs_call, self.name,
                        "num_scalar_prefetch must be a non-negative "
                        "constant — a traced/negative value breaks the "
                        "scalar-prefetch ref layout at device trace time")
                else:
                    num_prefetch = npv

        grid_len: Optional[int] = None
        if grid_expr is not None:
            g = _resolve_name(grid_expr, fn)
            if isinstance(g, (ast.Tuple, ast.List)):
                grid_len = len(g.elts)
            else:
                gv = const_int(g, env)
                if gv is not None:
                    grid_len = 1

        specs = _collect_specs(_kwarg(src, "in_specs"), "in", fn, env) \
            + _collect_specs(_kwarg(src, "out_specs"), "out", fn, env)
        out_dtype = None
        # out_shape is pallas_call's SECOND positional parameter — both
        # spellings must feed the dtype tables or the f32 fallback
        # silently blesses off-tile bf16 blocks
        out_shape = _kwarg(call, "out_shape")
        if out_shape is None and len(call.args) >= 2:
            out_shape = call.args[1]
        # single ShapeDtypeStruct, or a list/tuple of them (multi-output
        # kernels): one unambiguous dtype feeds the tile/budget checks
        out_items = out_shape.elts if isinstance(
            out_shape, (ast.Tuple, ast.List)) else [out_shape]
        dtypes = {_dtype_of(o.args[1]) if len(o.args) >= 2
                  else _dtype_of(_kwarg(o, "dtype"))
                  for o in out_items if isinstance(o, ast.Call)}
        dtypes.discard(None)
        if len(dtypes) == 1:
            out_dtype = dtypes.pop()

        # 1. grid <-> index_map arity (+ scalar-prefetch refs)
        if grid_len is not None:
            expected = grid_len + num_prefetch
            for spec in specs:
                lam = spec.index_map
                if lam is None:
                    continue
                n_params = len(getattr(lam.args, "posonlyargs", [])) \
                    + len(lam.args.args)
                # defaulted trailing params are legally omittable: the
                # lambda accepts anything in [required, total]
                required = n_params - len(lam.args.defaults)
                if lam.args.vararg is not None \
                        or required <= expected <= n_params:
                    continue
                yield ctx.finding(
                    lam, self.name,
                    "index_map takes %d argument(s) but the grid has %d "
                    "dim(s)%s — arity mismatch, a trace-time TypeError on "
                    "device" % (
                        n_params, grid_len,
                        " plus %d scalar-prefetch ref(s)" % num_prefetch
                        if num_prefetch else ""))

        # 2. block tile alignment — the out_shape dtype speaks for every
        # block (a bf16 kernel's inputs are bf16 too, and its (16, 128)
        # min tile catches what the f32 (8, 128) fallback would bless)
        for spec in specs:
            yield from self._check_tiles(ctx, spec.node, spec.block,
                                         out_dtype, "BlockSpec")
        scratch = _scratch_shapes(_kwarg(src, "scratch_shapes")
                                  or _kwarg(call, "scratch_shapes"), fn, env)
        for node, dims, dtype in scratch:
            yield from self._check_tiles(ctx, node, dims, dtype,
                                         "VMEM scratch")

        # 3. VMEM budget: const-resolvable blocks only (under-approximate)
        total = 0
        for spec in specs:
            if spec.block and all(d is not None for d in spec.block):
                size = 1
                for d in spec.block:
                    size *= d
                # the kernel's element size: the out_shape dtype is the
                # best single estimate for EVERY block (a bf16 kernel's
                # inputs are bf16 too — counting them as f32 would
                # manufacture over-ceiling findings); f32 only when the
                # call declares no dtype at all
                _, esize = _DTYPES.get(out_dtype or "float32", (8, 4))
                total += size * esize * 2  # pipeline double buffering
        for _node, dims, dtype in scratch:
            if dims and all(d is not None for d in dims):
                size = 1
                for d in dims:
                    size *= d
                total += size * _DTYPES.get(dtype or "float32", (8, 4))[1]
        if total > VMEM_BYTES:
            yield ctx.finding(
                call, self.name,
                "summed BlockSpec+scratch VMEM estimate %.1f MB exceeds "
                "the ~16 MB/core ceiling (block buffers are double-"
                "buffered by the pipeline) — shrink the block shapes or "
                "split the kernel" % (total / (1024.0 * 1024.0)))

    def _check_tiles(self, ctx: FileContext, node: ast.AST,
                     dims: Optional[List[Optional[int]]],
                     dtype: Optional[str], what: str) -> Iterator[Finding]:
        if not dims or len(dims) < 2:
            return
        sublane, _ = _DTYPES.get(dtype or "float32", (8, 4))
        last, second = dims[-1], dims[-2]
        if last is not None and last % LANES != 0:
            yield ctx.finding(
                node, self.name,
                "%s last dim %d is not a multiple of the %d-lane tile "
                "(dtype %s wants (%d, %d) tiles) — Mosaic pads or rejects "
                "the layout on device" % (what, last, LANES,
                                          dtype or "float32/unknown",
                                          sublane, LANES))
        if second is not None and second != 1 and second % sublane != 0:
            yield ctx.finding(
                node, self.name,
                "%s second-to-last dim %d is not a multiple of the "
                "%d-sublane tile for dtype %s ((%d, %d) min tile) — "
                "misaligned sublanes force a relayout on every DMA"
                % (what, second, sublane, dtype or "float32/unknown",
                   sublane, LANES))
