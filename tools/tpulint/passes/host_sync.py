"""host-sync: implicit device->host synchronization on the hot path.

The reference engine makes a sync explicit (``WaitForVar`` /
``NDArray.wait_to_read``); under JAX the same sync hides inside innocuous
host conversions. ``x.asnumpy()`` / ``x.item()`` / ``float(x)`` block the
Python thread until the device stream drains — once per loop iteration that
serializes dispatch and idles the TPU; inside a ``jit``-traced function it
is worse: the tracer is concretized at *trace time*, either erroring or
baking a stale constant into the compiled program.

Flagged:

- ``.asnumpy()`` / ``.item()`` / ``.tolist()`` / ``.wait_to_read()`` /
  ``.block_until_ready()`` calls inside a loop or inside jit-traced code;
- ``np.asarray(...)`` / ``np.array(...)`` inside jit-traced code (on host
  data in a plain loop it is legitimate, so only the jit context is
  flagged there);
- ``float(...)`` / ``int(...)`` applied to a call result (e.g.
  ``float(x.sum())``, ``float(np.sum(f(x)))``) inside a loop or jit-traced
  code — scalar conversion of a device value is a full sync.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import (FileContext, Finding, Pass, dotted_name, in_jit, in_loop,
                    register)

_SYNC_METHODS = {"asnumpy", "item", "tolist", "wait_to_read", "block_until_ready"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
# float(len(x)) etc. never touches the device
_SCALAR_SAFE_CALLEES = {"len", "str", "ord", "round", "hash", "id"}


@register
class HostSyncPass(Pass):
    name = "host-sync"
    description = ("device->host syncs (.asnumpy()/.item()/float()/np.asarray) "
                   "inside loops or jit-traced code")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        jitted = ctx.jit_functions()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            jit_ctx = in_jit(node, jitted)
            loop_ctx = in_loop(node)

            if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
                if jit_ctx:
                    yield ctx.finding(node, self.name,
                                      "`.%s()` inside jit-traced code concretizes the "
                                      "tracer at trace time" % node.func.attr)
                elif loop_ctx:
                    yield ctx.finding(node, self.name,
                                      "`.%s()` inside a loop forces a device->host "
                                      "sync per iteration" % node.func.attr)
                continue

            fname = dotted_name(node.func)
            if fname in _NP_CONVERTERS and jit_ctx:
                yield ctx.finding(node, self.name,
                                  "`%s()` inside jit-traced code materializes the "
                                  "tracer on the host at trace time" % fname)
                continue

            if fname in ("float", "int") and len(node.args) == 1:
                arg = node.args[0]
                if isinstance(arg, ast.Call) \
                        and dotted_name(arg.func) not in _SCALAR_SAFE_CALLEES:
                    if jit_ctx:
                        yield ctx.finding(node, self.name,
                                          "`%s()` on a computed value inside jit-traced "
                                          "code concretizes the tracer" % fname)
                    elif loop_ctx:
                        yield ctx.finding(node, self.name,
                                          "`%s()` on a computed value inside a loop is a "
                                          "device->host sync per iteration" % fname)
