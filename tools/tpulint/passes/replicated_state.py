"""replicated-state: eager full-tree copy/placement of optimizer state
outside the blessed placement helpers.

The ZeRO plane (``mxnet_tpu/fastpath/zero.py``) keeps optimizer state
partitioned over the dp axis between steps; HBM headroom — the whole
point of ``MXNET_ZERO`` — survives only as long as nothing quietly pulls
that state back to a replicated (or single-device) layout. The hazard
shape is an eager ``jnp.copy(...)`` / ``jax.device_put(...)`` over a
state tree: it allocates a full per-device copy of every shard NOW (the
2x-HBM init-spike class of bug ``parallel.fresh_replicate`` was built to
kill) and, applied to a sharded tree, silently re-replicates it. All
state placement must route through the blessed helpers —
``parallel.fresh_replicate`` / ``parallel.put_sharded`` (layout-aware,
alias-guarded) or the ``fastpath.zero`` plane itself.

Flagged in ``mxnet_tpu/`` (the helpers' own homes ``parallel.py`` and
``fastpath/zero.py`` are exempt):

- ``jnp.copy`` / ``jax.device_put`` whose argument expression names
  optimizer state (an identifier containing ``state``, e.g. ``states``,
  ``opt_states``, ``updater.states[i]``);
- a ``tree_map`` mapping a copy/device_put-containing function over a
  state tree (the full-tree variant of the same eager placement).

``states_synced`` (a bool bookkeeping dict) never matches.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import (FileContext, Finding, Pass, ancestors, dotted_name,
                    register)

_COPY_CALLS = {"jnp.copy", "jax.numpy.copy", "np.copy", "numpy.copy"}
_PUT_TAILS = ("device_put",)
_TREE_MAP_TAILS = ("tree_map", "tree_multimap")
_STATE_RE = re.compile(r"(?<![A-Za-z0-9_])_?(?:[a-z0-9_]*_)?states?(?:_|\b)")
_EXCLUDED = ("states_synced", "state_dict", "recordingstatescope")

_BLESSED = ("mxnet_tpu/parallel.py", "mxnet_tpu/fastpath/zero.py")


def _mentions_state(node: ast.AST) -> bool:
    """Whether any identifier under ``node`` reads like optimizer state."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is None:
            continue
        low = name.lower()
        if any(x in low for x in _EXCLUDED):
            continue
        if _STATE_RE.search(low):
            return True
    return False


def _is_copy_call(node: ast.Call) -> bool:
    name = dotted_name(node.func) or ""
    return name in _COPY_CALLS or name.rsplit(".", 1)[-1] in _PUT_TAILS


def _arg_is_state(call: ast.Call, arg: ast.AST) -> bool:
    """The copied value names state directly, OR is a loop/comprehension
    variable fed from a state iterable (``for s in opt_states: copy(s)``
    — the common full-tree spread shape)."""
    if _mentions_state(arg):
        return True
    names = {sub.id for sub in ast.walk(arg) if isinstance(sub, ast.Name)}
    if not names:
        return False
    for anc in ancestors(call):
        gens = list(getattr(anc, "generators", ()))
        if isinstance(anc, (ast.For, ast.AsyncFor)):
            gens.append(anc)
        for g in gens:
            tgt = {s.id for s in ast.walk(g.target)
                   if isinstance(s, ast.Name)}
            if tgt & names and _mentions_state(g.iter):
                return True
    return False


@register
class ReplicatedStatePass(Pass):
    name = "replicated-state"
    description = ("eager jnp.copy/device_put of optimizer state outside "
                   "the blessed placement helpers (fresh_replicate/"
                   "put_sharded/fastpath.zero) — re-replicates sharded "
                   "state and spikes HBM")

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/") and relpath not in _BLESSED

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if _is_copy_call(node):
                if any(_arg_is_state(node, a) for a in node.args[:1]):
                    yield ctx.finding(
                        node, self.name,
                        "eager `%s(...)` over optimizer state — route "
                        "placement through parallel.fresh_replicate/"
                        "put_sharded (layout-aware) or fastpath.zero"
                        % name)
                continue
            # tree_map(fn-with-copy/device_put, states): the full-tree
            # eager placement in one expression
            if name.rsplit(".", 1)[-1] in _TREE_MAP_TAILS and node.args:
                fn_arg, tree_args = node.args[0], node.args[1:]
                if not any(_mentions_state(a) for a in tree_args):
                    continue
                has_copy = any(
                    isinstance(sub, ast.Call) and _is_copy_call(sub)
                    for sub in ast.walk(fn_arg))
                has_copy = has_copy or (dotted_name(fn_arg) or "") \
                    in _COPY_CALLS or (dotted_name(fn_arg) or "") \
                    .rsplit(".", 1)[-1] in _PUT_TAILS
                if has_copy:
                    yield ctx.finding(
                        node, self.name,
                        "eager `%s(copy/device_put, states)` replicates a "
                        "whole state tree — route placement through "
                        "parallel.fresh_replicate/put_sharded or "
                        "fastpath.zero" % name)
