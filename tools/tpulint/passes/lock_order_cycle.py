"""lock-order-cycle: static deadlock detection over the acquisition graph.

The serving stack's locks form a documented one-way hierarchy — the
decode engine's CV may reach into tenant and breaker locks (the
weighted-fair admission callback runs under it), tenant/breaker locks
may reach into telemetry, and nothing points back. This pass proves
that hierarchy instead of trusting it: the concurrency interpreter
(:mod:`tools.tpulint.locks`) resolves every ``with <lock>:`` /
``.acquire()`` site to a per-class lock identity and adds an edge
``A -> B`` whenever B is taken while A is held — directly, through a
bounded-depth call chain, or through a callback reference passed as an
argument. A cycle between any two lock classes is a *static deadlock*:
two threads acquiring in opposite orders need only interleave once, and
the resulting hang is the exact shape the flight recorder can only
autopsy after the fact.

The finding carries both witness directions (function + how each
forward edge is realized). Same-class self-edges are never reported:
two *instances* of one lock class (``t1._lock`` then ``t2._lock``)
are ordered by the caller, not by class identity.
"""
from __future__ import annotations

from typing import Iterator

from ..core import FileContext, Finding, Pass, register
from .. import locks


@register
class LockOrderCyclePass(Pass):
    name = "lock-order-cycle"
    description = ("cycles in the whole-program lock-acquisition graph — "
                   "two threads acquiring in opposite orders deadlock")
    project = True

    def applies(self, relpath: str) -> bool:
        return relpath.startswith("mxnet_tpu/")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        graph = ctx.project
        if graph is None:
            return
        ana = locks.analyze(graph)
        for rec in ana.cycle_findings.get(ctx.relpath, ()):
            yield ctx.finding(rec.node, self.name, rec.message())
