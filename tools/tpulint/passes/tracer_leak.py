"""tracer-leak: side effects captured (or dropped) by ``jax.jit`` tracing.

``jit`` runs the Python body ONCE per input signature; anything that is not
a pure function of the traced arguments is frozen into the compiled program
or silently skipped on cache hits. The reference engine had no tracing —
every Python line executed every call — so ported code is full of these.

Flagged inside jit-traced functions (decorated, wrapped, or transitively
called by name in the same file — see ``core.jit_functions``):

- ``print(...)`` — executes at trace time only; use ``jax.debug.print``;
- clock reads (``time.time()`` et al.) — trace-time constants;
- ``os.environ`` / ``os.getenv`` access — trace-time constant config;
- ``global`` / ``nonlocal`` declarations — mutation of outer state runs
  once per *compile*, not once per call;
- ``np.random.*`` draws — one sample frozen for every call.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import (FileContext, Finding, Pass, dotted_name, in_jit,
                    register)

_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}


@register
class TracerLeakPass(Pass):
    name = "tracer-leak"
    description = ("side effects (print, clocks, os.environ, global/nonlocal, "
                   "np.random) inside jit-traced functions")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        jitted = ctx.jit_functions()
        if not jitted:
            return
        for node in ast.walk(ctx.tree):
            if not in_jit(node, jitted):
                continue
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname == "print":
                    yield ctx.finding(node, self.name,
                                      "`print()` under jit runs at trace time only; "
                                      "use jax.debug.print")
                elif fname in _CLOCK_CALLS:
                    yield ctx.finding(node, self.name,
                                      "`%s()` under jit is frozen to a trace-time "
                                      "constant" % fname)
                elif fname == "os.getenv":
                    yield ctx.finding(node, self.name,
                                      "`os.getenv()` under jit is frozen to a "
                                      "trace-time constant")
                elif fname is not None and fname.startswith(("np.random.",
                                                             "numpy.random.")):
                    yield ctx.finding(node, self.name,
                                      "`%s()` under jit draws once at trace time; "
                                      "thread a jax PRNG key instead" % fname)
            elif isinstance(node, ast.Attribute) and dotted_name(node) == "os.environ":
                yield ctx.finding(node, self.name,
                                  "`os.environ` under jit is frozen to a trace-time "
                                  "constant")
            elif isinstance(node, ast.Global):
                yield ctx.finding(node, self.name,
                                  "`global %s` under jit mutates module state at "
                                  "trace time, not per call" % ", ".join(node.names))
            elif isinstance(node, ast.Nonlocal):
                yield ctx.finding(node, self.name,
                                  "`nonlocal %s` under jit mutates closure state at "
                                  "trace time, not per call" % ", ".join(node.names))
