"""tpulint — AST-based TPU-correctness linter for mxnet_tpu.

Programmatic entry points::

    from tools.tpulint import lint_paths, main
    new_findings, all_findings = lint_paths(["mxnet_tpu"])

CLI::

    python -m tools.tpulint [paths...] [--format json] [--write-baseline]
                            [--changed-only] [--no-baseline] [--list-rules]

Pure stdlib ``ast`` — no JAX import, no device work; safe in tier-1 CI.
"""
from .core import (DEFAULT_BASELINE, DEFAULT_ROOTS, FileContext, Finding,
                   Pass, REGISTRY, all_passes, apply_baseline, collect_files,
                   lint_files, lint_source, load_baseline, write_baseline)
from .cli import lint_paths, main

__all__ = [
    "DEFAULT_BASELINE", "DEFAULT_ROOTS", "FileContext", "Finding", "Pass",
    "REGISTRY", "all_passes", "apply_baseline", "collect_files", "lint_files",
    "lint_source", "load_baseline", "write_baseline", "lint_paths", "main",
]
