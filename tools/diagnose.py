#!/usr/bin/env python
"""Dump environment/platform diagnostics for bug reports.

Counterpart of the reference's ``tools/diagnose.py`` (python/env dump used
when filing issues), extended with the TPU-stack facts that matter here:
jax/jaxlib versions, visible devices, the distributed-runtime state, the
native mxtpu library, and every ``MXNET_*`` env knob.
"""
from __future__ import annotations

import os
import platform
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())
    print("----------Platform Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())
    print("----------mxnet_tpu Info----------")
    try:
        import mxnet_tpu as mx
        print("version      :", mx.__version__)
        print("package      :", os.path.dirname(mx.__file__))
        from mxnet_tpu import _native
        lib = _native.get_lib()
        print("native lib   :", getattr(lib, "_name", None) or "unavailable (pure Python)")
        from mxnet_tpu import engine
        print("engine mode  :", "NaiveEngine" if engine.is_naive_mode() else "ThreadedEngine")
        print("host workers :", engine.num_workers())
    except Exception as exc:  # noqa: BLE001
        print("import failed:", exc)
    print("----------JAX Info----------")
    try:
        import jax
        import jaxlib
        print("jax          :", jax.__version__)
        print("jaxlib       :", jaxlib.__version__)
        print("backend      :", jax.default_backend())
        print("devices      :", jax.devices())
        print("local devices:", jax.local_devices())
        print("process      : %d / %d" % (jax.process_index(), jax.process_count()))
    except Exception as exc:  # noqa: BLE001
        print("jax unavailable:", exc)
    print("----------Environment----------")
    for k in sorted(os.environ):
        if k.startswith(("MXNET_", "JAX_", "XLA_", "TPU_", "LIBTPU_")):
            print("%s=%s" % (k, os.environ[k]))


if __name__ == "__main__":
    main()
