#!/usr/bin/env python
"""Measure gradient-aggregation bandwidth across devices.

Counterpart of the reference's ``tools/bandwidth/measure.py`` (which timed
kvstore push/pull over PCIe/IB to find the communication bottleneck,
``docs/faq/perf.md:224-228``). Here the transport is ICI (or host loopback
on CPU meshes): the measurement allreduces ResNet-sized gradient sets over
all available devices through ``parallel.all_reduce`` and through
``kvstore`` push/pull, reporting GB/s of algorithmic bandwidth
(2*(n-1)/n * bytes / time, the standard allreduce cost model).

Example:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python tools/bandwidth/measure.py --size-mb 64 --iters 10
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--size-mb", type=float, default=64.0,
                        help="total gradient bytes per round")
    parser.add_argument("--num-keys", type=int, default=20,
                        help="split the payload over this many tensors")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--kvstore", default="device",
                        help="also time this kvstore type's push/pull")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import parallel

    devices = jax.local_devices()
    n = len(devices)
    if n < 2:
        print("need >=2 devices (got %d); set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8" % n)
        return 1
    total = int(args.size_mb * 1e6 / 4)
    per_key = max(1, total // args.num_keys)
    print("devices: %d x %s | payload %.1f MB in %d keys"
          % (n, devices[0].platform, args.size_mb, args.num_keys))

    copies = [[jax.device_put(jnp.full((per_key,), float(d_i + 1), jnp.float32), d)
               for d_i, d in enumerate(devices)] for _ in range(args.num_keys)]

    def round_allreduce():
        outs = [parallel.all_reduce(c) for c in copies]
        outs[-1].block_until_ready()

    for _ in range(args.warmup):
        round_allreduce()
    tic = time.perf_counter()
    for _ in range(args.iters):
        round_allreduce()
    dt = (time.perf_counter() - tic) / args.iters
    nbytes = per_key * 4 * args.num_keys
    algo_bw = 2 * (n - 1) / n * nbytes / dt / 1e9
    print("all_reduce : %7.2f ms/round  algorithmic %6.2f GB/s" % (dt * 1e3, algo_bw))

    kv = mx.kvstore.create(args.kvstore)
    vals = [[mx.nd.NDArray(c, mx.Context("cpu" if d.platform == "cpu" else "tpu", i))
             for i, (c, d) in enumerate(zip(cs, devices))] for cs in copies]
    for k in range(args.num_keys):
        kv.init(str(k), vals[k][0])

    def round_kv():
        for k in range(args.num_keys):
            kv.push(str(k), vals[k])
            kv.pull(str(k), out=vals[k])
        vals[-1][0]._data.block_until_ready()

    for _ in range(args.warmup):
        round_kv()
    tic = time.perf_counter()
    for _ in range(args.iters):
        round_kv()
    dt = (time.perf_counter() - tic) / args.iters
    algo_bw = 2 * (n - 1) / n * nbytes / dt / 1e9
    print("kv=%s push+pull : %7.2f ms/round  algorithmic %6.2f GB/s"
          % (args.kvstore, dt * 1e3, algo_bw))
    return 0


if __name__ == "__main__":
    sys.exit(main())
