#!/bin/bash
# Round-5 live-TPU evidence sequence (runs in tmux; relay is finally up).
cd /root/repo
set -x
date -u
# 1. headline bench (unprofiled, generous deadline for fresh remote compiles)
MXNET_BENCH_DEADLINE_S=3300 timeout 3600 python bench.py > /tmp/bench_live_raw.txt 2>/tmp/bench_live.err
grep '^{' /tmp/bench_live_raw.txt | tail -1 > BENCH_TPU_LIVE.json
date -u
# 2. profiled short rerun (server compile cache now warm)
rm -rf tpu_trace; MXNET_BENCH_PROFILE=/root/repo/tpu_trace MXNET_BENCH_DEADLINE_S=1500 timeout 1700 python bench.py > /tmp/bench_prof_raw.txt 2>/tmp/bench_prof.err
grep '^{' /tmp/bench_prof_raw.txt | tail -1 > BENCH_TPU_PROFILED.json
date -u
# 3. entry() compile check on the real chip
timeout 900 python -c "import __graft_entry__ as g, jax; fn, args = g.entry(); out = jax.jit(fn)(*args); jax.block_until_ready(out); print('ENTRY_OK', getattr(out, 'shape', None))" > /tmp/entry_check.txt 2>&1
date -u
# 4. on-chip operator suite rerun
MXNET_TEST_DEVICE=tpu timeout 3600 python -m pytest tests/test_operator_tpu.py -q --no-header > /tmp/tpu_tests.txt 2>&1
tail -5 /tmp/tpu_tests.txt
date -u
echo SEQUENCE_DONE
