#!/usr/bin/env python
"""Launch a distributed mxnet_tpu job.

TPU-native re-design of the reference's ``tools/launch.py:57-111`` (dmlc
tracker over ssh/mpi/sge/yarn/local spawning scheduler + parameter servers +
workers with ``DMLC_ROLE``/``DMLC_PS_ROOT_URI`` env). On TPU there is no
parameter-server role — weights live in HBM and gradients ride ICI/DCN
collectives — so the launcher's job collapses to: pick a coordinator
address, spawn N worker processes with rendezvous env vars
(``MXNET_COORDINATOR_ADDR``/``MXNET_NUM_WORKERS``/``MXNET_WORKER_RANK``,
consumed by ``mxnet_tpu.kvstore.init_distributed``), stream their output,
and propagate the first failure.

Launchers:
  local  — N processes on this host (the reference's ``--launcher local``,
           used by tests/nightly/dist_sync_kvstore.py). With
           ``JAX_PLATFORMS=cpu`` each process contributes its host CPU
           device(s) to one global mesh, so distributed semantics run
           without TPU hardware.
  ssh    — one process per host listed in --hostfile (reference ssh mode).

Example:
  python tools/launch.py -n 2 -- python examples/train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int) -> None:
    for line in iter(proc.stdout.readline, b""):
        sys.stdout.write("[worker %d] %s" % (rank, line.decode(errors="replace")))
        sys.stdout.flush()


def launch_local(args, command) -> int:
    port = args.port or find_free_port()
    coord = "127.0.0.1:%d" % port
    procs = []
    threads = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env["MXNET_COORDINATOR_ADDR"] = coord
        env["MXNET_NUM_WORKERS"] = str(args.num_workers)
        env["MXNET_WORKER_RANK"] = str(rank)
        p = subprocess.Popen(command, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, rank), daemon=True)
        t.start()
        threads.append(t)
    rc = 0
    try:
        for p in procs:
            p.wait()
        for t in threads:
            t.join(timeout=5)
        rc = max(p.returncode for p in procs)
    except KeyboardInterrupt:
        rc = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
    return rc


def launch_ssh(args, command) -> int:
    if not args.hostfile or not os.path.isfile(args.hostfile):
        print("ssh launcher needs --hostfile", file=sys.stderr)
        return 2
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        print("hostfile has %d hosts; need %d" % (len(hosts), args.num_workers),
              file=sys.stderr)
        return 2
    port = args.port or find_free_port()
    coord = "%s:%d" % (hosts[0], port)
    cmd_str = " ".join(shlex.quote(c) for c in command)
    procs = []
    threads = []
    for rank in range(args.num_workers):
        envs = "MXNET_COORDINATOR_ADDR=%s MXNET_NUM_WORKERS=%d MXNET_WORKER_RANK=%d" % (
            coord, args.num_workers, rank)
        remote = "cd %s && %s %s" % (shlex.quote(os.getcwd()), envs, cmd_str)
        p = subprocess.Popen(["ssh", "-o", "StrictHostKeyChecking=no",
                              hosts[rank], remote],
                             stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_stream, args=(p, rank), daemon=True)
        t.start()
        threads.append(t)
    for p in procs:
        p.wait()
    for t in threads:
        t.join(timeout=5)
    return max(p.returncode for p in procs)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference CLI parity; the TPU "
                             "runtime has no server role (weights stay in "
                             "HBM, reduction rides collectives)")
    parser.add_argument("--launcher", choices=["local", "ssh"], default="local")
    parser.add_argument("--hostfile", "-H", help="hostfile for ssh launcher")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command to launch")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given")
    if args.num_servers:
        print("note: -s/--num-servers ignored — no parameter-server role on "
              "the TPU runtime", file=sys.stderr)
    if args.launcher == "local":
        return launch_local(args, command)
    return launch_ssh(args, command)


if __name__ == "__main__":
    sys.exit(main())
