"""Input-pipeline throughput benchmark (VERDICT r4 item 2).

Measures images/sec out of the data pipeline against the train step's
consumption rate — the role of the reference's comm/perf measurements
(docs/faq/perf.md:224-228). Three numbers:

  1. single-process ImageRecordIter (decode under the GIL) — the old path
  2. MPImageRecordIter with N worker processes — the throughput path
  3. the fused train step's img/s on this host (optional, --train)

Verdict: the MP pipeline must sustain more img/s than the train step
consumes, i.e. the input side is not the bottleneck.

Run:  python tools/pipeline_bench.py [--images 512] [--side 256]
         [--crop 224] [--batch-size 32] [--workers N] [--train resnet50]
Prints ONE JSON line.
"""
import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def build_dataset(n, side, tmpdir):
    from mxnet_tpu import recordio

    rec = os.path.join(tmpdir, "bench.rec")
    idx = os.path.join(tmpdir, "bench.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rs = np.random.RandomState(0)
    # structured patterns compress like natural images (pure noise JPEGs
    # decode unrealistically slowly)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32)
    for i in range(n):
        f1, f2 = rs.uniform(0.01, 0.1, 2)
        img = np.stack([
            127 + 120 * np.sin(f1 * xx + i),
            127 + 120 * np.cos(f2 * yy + 2 * i),
            127 + 120 * np.sin(f1 * xx + f2 * yy),
        ], axis=2).clip(0, 255).astype(np.uint8)
        hdr = recordio.IRHeader(0, float(i % 1000), i, 0)
        w.write_idx(i, recordio.pack_img(hdr, img, quality=90))
    w.close()
    return rec


def drain(it, seconds):
    """Pull batches for ~seconds; returns images/sec."""
    n_img = 0
    start = time.perf_counter()
    while time.perf_counter() - start < seconds:
        try:
            batch = it.next()
        except StopIteration:
            it.reset()
            continue
        batch.data[0].asnumpy()  # force materialization
        n_img += batch.data[0].shape[0] - batch.pad
    return n_img / (time.perf_counter() - start)


def train_rate(batch_size, crop, model, seconds):
    from mxnet_tpu import gluon, nd, parallel
    from mxnet_tpu.gluon.model_zoo import vision
    import jax

    net = getattr(vision, model)(classes=1000)
    net.initialize()
    mesh = parallel.device_mesh(1, devices=[jax.devices()[0]])
    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd", mesh,
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    rs = np.random.RandomState(0)
    x = nd.array(rs.rand(batch_size, 3, crop, crop).astype(np.float32))
    y = nd.array(rs.randint(0, 1000, (batch_size,)))
    step(x, y)._data.block_until_ready()  # compile
    n = 0
    start = time.perf_counter()
    out = None
    while time.perf_counter() - start < seconds:
        out = step(x, y)
        n += batch_size
    out._data.block_until_ready()
    return n / (time.perf_counter() - start)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--side", type=int, default=256)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--workers", type=int, default=max(2, (os.cpu_count() or 4) // 2))
    ap.add_argument("--seconds", type=float, default=8.0)
    ap.add_argument("--train", default=None,
                    help="also measure this model's train-step img/s "
                         "(e.g. resnet50_v1)")
    args = ap.parse_args()

    from mxnet_tpu import io as mxio
    from mxnet_tpu.image_pipeline import MPImageRecordIter

    with tempfile.TemporaryDirectory() as tmpdir:
        rec = build_dataset(args.images, args.side, tmpdir)
        shape = (3, args.crop, args.crop)

        single = mxio.ImageRecordIter(
            path_imgrec=rec, data_shape=shape, batch_size=args.batch_size,
            preprocess_threads=0, prefetch_buffer=0)
        single_rate = drain(single, args.seconds)

        mp_it = MPImageRecordIter(
            rec, data_shape=shape, batch_size=args.batch_size,
            shuffle=True, rand_crop=True, rand_mirror=True,
            preprocess_threads=args.workers, prefetch_buffer=4)
        # let workers warm up (first batches include process start)
        drain(mp_it, 2.0)
        mp_rate = drain(mp_it, args.seconds)
        mp_it.close()

    result = {
        "metric": "input pipeline img/s (mp, %d workers, %dpx->%d crop)"
                  % (args.workers, args.side, args.crop),
        "value": round(mp_rate, 1),
        "unit": "img/s",
        "vs_baseline": round(mp_rate / single_rate, 2),
        "extra": {
            "single_process_img_s": round(single_rate, 1),
            "speedup_vs_single": round(mp_rate / single_rate, 2),
            "batch": args.batch_size,
        },
    }
    if args.train:
        t = train_rate(args.batch_size, args.crop, args.train, args.seconds)
        result["extra"]["train_step_img_s"] = round(t, 1)
        result["extra"]["pipeline_keeps_up"] = bool(mp_rate > t)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
