#!/usr/bin/env bash
# tools/lint_gate.sh — the pre-commit/CI tpulint gate.
#
# Runs the whole-program linter twice (a cold-or-warm pass that fills the
# incremental cache, then a fully-warm pass), enforces the tier-1 time
# contract on each (cold < LINT_GATE_COLD_S, warm < LINT_GATE_WARM_S),
# and checks the JSON output for non-baselined findings. The scope runs
# every registered pass, including the v4 concurrency/lifecycle set
# (lock-order-cycle, blocking-under-lock, cv-protocol,
# resource-lifecycle) — their shared LockAnalysis dominates the cold
# run (~19s measured vs the 30s gate); warm runs stay cache-only
# (~0.2s). Exit codes:
#   0  clean and inside the time gates
#   1  new (non-baselined) findings — fix, suppress, or --write-baseline
#   2  usage/environment error (python or repo missing)
#   3  time gate exceeded (the cache or a pass regressed)
#
# Wire into pre-commit with:
#   ln -s ../../tools/lint_gate.sh .git/hooks/pre-commit
# bench.py stamps the same verdict on every JSON line as
# lint_clean/lint_findings (see docs/performance.md).
set -u -o pipefail

# resolve symlinks (the documented `ln -s .../lint_gate.sh
# .git/hooks/pre-commit` wiring) before deriving the repo root, or the
# hook would root itself inside .git/ and fail every commit
SELF="$(readlink -f "${BASH_SOURCE[0]}" 2>/dev/null || echo "${BASH_SOURCE[0]}")"
REPO_ROOT="$(cd "$(dirname "$SELF")/.." && pwd -P)"
PY="${PYTHON:-python3}"
COLD_GATE="${LINT_GATE_COLD_S:-30}"
WARM_GATE="${LINT_GATE_WARM_S:-5}"
SCOPE=("mxnet_tpu" "tools")
OUT="$(mktemp)"
trap 'rm -f "$OUT" "$OUT.stats"' EXIT

command -v "$PY" >/dev/null 2>&1 || { echo "lint_gate: no $PY" >&2; exit 2; }
cd "$REPO_ROOT" || exit 2

ELAPSED=""
run_lint() { # $1 = phase label; sets $ELAPSED (seconds). NOT called in a
             # subshell — a broken run must exit the GATE with rc 2, and
             # `exit` inside $(...) would only kill the substitution.
    local t0 t1 rc
    t0=$(date +%s.%N)
    "$PY" -m tools.tpulint "${SCOPE[@]}" --format json --stats >"$OUT" 2>"$OUT.stats"
    rc=$?
    t1=$(date +%s.%N)
    # rc 1 = findings (checked from the JSON below); rc >= 2 = broken run
    if [ "$rc" -ge 2 ]; then
        echo "lint_gate: $1 run failed (rc=$rc)" >&2
        cat "$OUT" "$OUT.stats" >&2
        exit 2
    fi
    ELAPSED=$(echo "$t0 $t1" | awk '{printf "%.1f", $2 - $1}')
}

check_findings() { # $1 = phase label; rc 0 clean, 1 findings, 2 bad output
    "$PY" - "$OUT" "$1" <<'PYEOF'
import json, sys
try:
    payload = json.load(open(sys.argv[1]))
except (OSError, ValueError) as exc:
    # polluted/unparseable linter stdout is a BROKEN TOOL, not findings
    print("lint_gate: unparseable linter output (%s run): %s"
          % (sys.argv[2], exc), file=sys.stderr)
    sys.exit(2)
new = payload.get("new", [])
if new:
    print("lint_gate: %d new finding(s) [%s run]:" % (len(new), sys.argv[2]),
          file=sys.stderr)
    for f in new:
        print("  %s:%s: [%s] %s" % (f["path"], f["line"], f["rule"],
                                    f["message"]), file=sys.stderr)
    sys.exit(1)
PYEOF
}

check_time() { # $1 = elapsed, $2 = gate, $3 = label
    awk -v t="$1" -v g="$2" 'BEGIN { exit !(t < g) }' || {
        echo "lint_gate: $3 run took ${1}s (gate: <${2}s) — the incremental" \
             "cache or a pass regressed" >&2
        exit 3
    }
}

gate_phase() { # $1 = label, $2 = time gate
    run_lint "$1"
    local elapsed="$ELAPSED" rc=0
    check_findings "$1" || rc=$?
    [ "$rc" -eq 1 ] && exit 1
    [ "$rc" -ge 2 ] && exit 2
    check_time "$elapsed" "$2" "$1"
    LAST_ELAPSED="$elapsed"
}

gate_phase cold "$COLD_GATE"
cold_s="$LAST_ELAPSED"
gate_phase warm "$WARM_GATE"
warm_s="$LAST_ELAPSED"

echo "lint_gate: clean (cold ${cold_s}s < ${COLD_GATE}s, warm ${warm_s}s < ${WARM_GATE}s)"
exit 0
