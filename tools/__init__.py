"""Repo utility scripts. A package so ``python -m tools.tpulint`` resolves;
the standalone scripts (im2rec.py, launch.py, ...) are still run directly
and keep importing each other via sys.path, not via this package.
"""
