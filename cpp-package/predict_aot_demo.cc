// Standalone AOT inference runner — NO Python dependency.
//
// The TPU-native counterpart of the reference's amalgamation build
// (amalgamation/README.md:1-13: a single predict-only library with zero
// Python). Loads the SavedModel produced by mxnet_tpu.aot.export_model
// (jax2tf-wrapped StableHLO, weights baked in) through the TensorFlow C
// API and runs one forward pass.
//
// Usage: predict_aot_demo <export_dir> <in_tensor> <out_tensor>
//                         <n_elements_in>
//   reads float32 input from stdin (binary), writes float32 output to
//   stdout (binary); diagnostics go to stderr.
//
// Build (see tests/test_aot_predict.py):
//   g++ -std=c++17 predict_aot_demo.cc -I<tf>/include \
//       <tf>/libtensorflow_cc.so.2 <tf>/libtensorflow_framework.so.2 \
//       -Wl,-rpath,<tf> -o predict_aot_demo
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tensorflow/c/c_api.h"

namespace {

void CheckOk(TF_Status* status, const char* what) {
  if (TF_GetCode(status) != TF_OK) {
    std::fprintf(stderr, "%s: %s\n", what, TF_Message(status));
    std::exit(2);
  }
}

// "serving_default_data:0" -> (op name, output index)
std::pair<std::string, int> SplitTensorName(const std::string& name) {
  auto colon = name.rfind(':');
  if (colon == std::string::npos) return {name, 0};
  return {name.substr(0, colon), std::atoi(name.c_str() + colon + 1)};
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 5) {
    std::fprintf(stderr,
                 "usage: %s <export_dir> <in_tensor> <out_tensor> <n_in>\n",
                 argv[0]);
    return 1;
  }
  const char* export_dir = argv[1];
  const auto in_name = SplitTensorName(argv[2]);
  const auto out_name = SplitTensorName(argv[3]);
  const long n_in = std::atol(argv[4]);

  TF_Status* status = TF_NewStatus();
  TF_Graph* graph = TF_NewGraph();
  TF_SessionOptions* opts = TF_NewSessionOptions();
  const char* tags[] = {"serve"};
  std::string sm_dir = std::string(export_dir) + "/saved_model";
  TF_Session* session = TF_LoadSessionFromSavedModel(
      opts, nullptr, sm_dir.c_str(), tags, 1, graph, nullptr, status);
  CheckOk(status, "LoadSessionFromSavedModel");
  std::fprintf(stderr, "loaded %s\n", sm_dir.c_str());

  TF_Operation* in_op = TF_GraphOperationByName(graph, in_name.first.c_str());
  TF_Operation* out_op = TF_GraphOperationByName(graph, out_name.first.c_str());
  if (!in_op || !out_op) {
    std::fprintf(stderr, "tensor op not found (in=%s out=%s)\n",
                 in_name.first.c_str(), out_name.first.c_str());
    return 2;
  }
  TF_Output in_port{in_op, in_name.second};
  TF_Output out_port{out_op, out_name.second};

  // input element count + shape from the graph itself — argv's count is
  // only cross-checked, never trusted (a short buffer under a larger
  // declared shape would make SessionRun read out of bounds)
  int ndims = TF_GraphGetTensorNumDims(graph, in_port, status);
  CheckOk(status, "GetTensorNumDims");
  if (ndims < 0) {
    std::fprintf(stderr, "input tensor has unknown rank; re-export with a "
                         "fully static input_signature\n");
    return 1;
  }
  std::vector<int64_t> dims(ndims);
  TF_GraphGetTensorShape(graph, in_port, dims.data(), ndims, status);
  CheckOk(status, "GetTensorShape");
  long graph_n = 1;
  for (int64_t d : dims) {
    if (d <= 0) {
      std::fprintf(stderr, "input tensor has a dynamic dim; re-export with "
                           "a fully static input_signature\n");
      return 1;
    }
    graph_n *= d;
  }
  if (graph_n != n_in) {
    std::fprintf(stderr,
                 "input element count mismatch: graph wants %ld, got %ld\n",
                 graph_n, n_in);
    return 1;
  }

  std::vector<float> input(n_in);
  if (std::fread(input.data(), sizeof(float), n_in, stdin) !=
      static_cast<size_t>(n_in)) {
    std::fprintf(stderr, "short read on stdin (want %ld floats)\n", n_in);
    return 1;
  }
  TF_Tensor* in_tensor = TF_AllocateTensor(TF_FLOAT, dims.data(), ndims,
                                           n_in * sizeof(float));
  std::memcpy(TF_TensorData(in_tensor), input.data(), n_in * sizeof(float));

  TF_Tensor* out_tensor = nullptr;
  TF_SessionRun(session, nullptr, &in_port, &in_tensor, 1, &out_port,
                &out_tensor, 1, nullptr, 0, nullptr, status);
  CheckOk(status, "SessionRun");

  const size_t out_bytes = TF_TensorByteSize(out_tensor);
  std::fwrite(TF_TensorData(out_tensor), 1, out_bytes, stdout);
  std::fflush(stdout);
  std::fprintf(stderr, "wrote %zu output bytes\n", out_bytes);

  TF_DeleteTensor(in_tensor);
  TF_DeleteTensor(out_tensor);
  TF_CloseSession(session, status);
  TF_DeleteSession(session, status);
  TF_DeleteSessionOptions(opts);
  TF_DeleteGraph(graph);
  TF_DeleteStatus(status);
  return 0;
}
