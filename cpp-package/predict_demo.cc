/*
 * C++ inference frontend over the C predict API (src/predict/predict.cc) —
 * the analogue of the reference's example/image-classification/predict-cpp
 * and the matlab/amalgamation consumers of include/mxnet/c_predict_api.h.
 *
 * Usage: predict_demo <prefix> <batch> <dim>
 *   loads <prefix>-symbol.json + <prefix>-0000.params, feeds a (batch, dim)
 *   input of 0.01*i values, prints each output value on one line.
 *
 * Build: make -C cpp-package predict_demo
 */
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

extern "C" {
const char *MXPredGetLastError(void);
int MXPredCreate(const char *symbol_json, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, void **out);
int MXPredSetInput(void *handle, const char *key, const float *data,
                   uint32_t size);
int MXPredForward(void *handle);
int MXPredGetOutputShape(void *handle, uint32_t index, uint32_t **shape_data,
                         uint32_t *shape_ndim);
int MXPredGetOutput(void *handle, uint32_t index, float *data, uint32_t size);
int MXPredFree(void *handle);
}

#define CHECK_OK(call)                                            \
  do {                                                            \
    if ((call) != 0) {                                            \
      std::fprintf(stderr, "error: %s\n", MXPredGetLastError());  \
      return 1;                                                   \
    }                                                             \
  } while (0)

static std::string ReadFile(const std::string &path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <prefix> <batch> <dim>\n", argv[0]);
    return 2;
  }
  std::string prefix = argv[1];
  uint32_t batch = (uint32_t)std::atoi(argv[2]);
  uint32_t dim = (uint32_t)std::atoi(argv[3]);

  std::string symbol_json = ReadFile(prefix + "-symbol.json");
  std::string params = ReadFile(prefix + "-0000.params");
  if (symbol_json.empty()) {
    std::fprintf(stderr, "cannot read %s-symbol.json\n", prefix.c_str());
    return 1;
  }

  const char *input_keys[] = {"data"};
  uint32_t indptr[] = {0, 2};
  uint32_t shape_data[] = {batch, dim};
  void *pred = nullptr;
  CHECK_OK(MXPredCreate(symbol_json.c_str(), params.data(),
                        (int)params.size(), /*dev_type=cpu*/ 1, 0, 1,
                        input_keys, indptr, shape_data, &pred));

  std::vector<float> input(batch * dim);
  for (size_t i = 0; i < input.size(); ++i) input[i] = 0.01f * (float)i;
  CHECK_OK(MXPredSetInput(pred, "data", input.data(), (uint32_t)input.size()));
  CHECK_OK(MXPredForward(pred));

  uint32_t *oshape = nullptr, ondim = 0;
  CHECK_OK(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
  uint32_t osize = 1;
  std::printf("output shape:");
  for (uint32_t i = 0; i < ondim; ++i) {
    std::printf(" %u", oshape[i]);
    osize *= oshape[i];
  }
  std::printf("\n");
  std::vector<float> out(osize);
  CHECK_OK(MXPredGetOutput(pred, 0, out.data(), osize));
  for (uint32_t i = 0; i < osize; ++i) std::printf("%.6f\n", out[i]);
  CHECK_OK(MXPredFree(pred));
  return 0;
}
