/*
 * mxtpu.hpp — header-only C++ frontend over the native C ABIs.
 *
 * The TPU-native counterpart of the reference's cpp-package
 * (cpp-package/include/mxnet-cpp *.hpp, which wraps c_api.h /
 * c_predict_api.h in RAII classes): everything here is a thin,
 * exception-safe wrapper over src/mxtpu.h (storage pool, dependency
 * engine, recordio) and src/predict/predict.cc (the 6-function predict
 * ABI). Compute itself is XLA-compiled — a C++ caller drives inference
 * through Predictor (embedded-interpreter path) or through the AOT
 * StableHLO artifact (docs/deploy_aot.md); there is deliberately no
 * per-op C++ math API, that role belongs to the compiler.
 *
 * Link: -lmxtpu (engine/storage/recordio) and/or -lmxtpu_predict.
 */
#ifndef MXTPU_HPP_
#define MXTPU_HPP_

#include <cstdint>
#include <cstring>
#include <functional>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

extern "C" {
/* src/mxtpu.h — redeclared so the header is self-contained for users
 * installing only cpp-package/include. */
const char *MXTPUGetLastError(void);
int MXTPUGetVersion(int *out);
int MXTPUStorageAlloc(size_t size, void **out);
int MXTPUStorageFree(void *ptr);
int MXTPUStorageDirectFree(void *ptr);
int MXTPUStorageReleaseAll(void);
int MXTPUStorageStats(uint64_t *bytes_in_use, uint64_t *bytes_pooled,
                      uint64_t *peak_bytes, uint64_t *num_allocs,
                      uint64_t *num_pool_hits);
typedef uint64_t MXTPUVarHandle;
typedef int (*MXTPUEngineFn)(void *arg);
int MXTPUEngineNewVar(MXTPUVarHandle *out);
int MXTPUEngineDeleteVar(MXTPUVarHandle var);
int MXTPUEnginePushAsync(MXTPUEngineFn fn, void *arg,
                         const MXTPUVarHandle *const_vars, int num_const,
                         const MXTPUVarHandle *mutable_vars, int num_mutable,
                         int priority, uint64_t *out_opr_id);
int MXTPUEngineWaitForVar(MXTPUVarHandle var);
int MXTPUEngineWaitForAll(void);
int MXTPUEngineNumWorkers(int *out);
int MXTPUEngineIsNaive(int *out);
int MXTPURecordIOWriterCreate(const char *path, void **out);
int MXTPURecordIOWriterWrite(void *handle, const char *buf, size_t size,
                             uint64_t *out_pos);
int MXTPURecordIOWriterTell(void *handle, uint64_t *out_pos);
int MXTPURecordIOWriterClose(void *handle);
int MXTPURecordIOReaderCreate(const char *path, void **out);
int MXTPURecordIOReaderSeek(void *handle, uint64_t pos);
int MXTPURecordIOReaderNext(void *handle, const char **out, size_t *out_size);
int MXTPURecordIOReaderTell(void *handle, uint64_t *out_pos);
int MXTPURecordIOReaderClose(void *handle);
}

namespace mxtpu {

/* Every failing ABI call raises this with MXTPUGetLastError's text —
 * the C++ analogue of python's base.check_call -> MXNetError. */
class Error : public std::runtime_error {
 public:
  explicit Error(const std::string &what) : std::runtime_error(what) {}
};

inline void check(int rc, const char *ctx) {
  if (rc != 0) {
    const char *msg = MXTPUGetLastError();
    throw Error(std::string(ctx) + ": " + (msg && *msg ? msg : "unknown"));
  }
}

inline int version() {
  int v = 0;
  check(MXTPUGetVersion(&v), "MXTPUGetVersion");
  return v;
}

/* ---------------- storage: RAII buffer from the size-bucketed pool ---- */

class Buffer {
 public:
  Buffer() = default;
  explicit Buffer(size_t size) : size_(size) {
    check(MXTPUStorageAlloc(size, &ptr_), "MXTPUStorageAlloc");
  }
  ~Buffer() { reset(); }
  Buffer(Buffer &&o) noexcept : ptr_(o.ptr_), size_(o.size_) {
    o.ptr_ = nullptr;
    o.size_ = 0;
  }
  Buffer &operator=(Buffer &&o) noexcept {
    if (this != &o) {
      reset();
      std::swap(ptr_, o.ptr_);
      std::swap(size_, o.size_);
    }
    return *this;
  }
  Buffer(const Buffer &) = delete;
  Buffer &operator=(const Buffer &) = delete;

  void *data() const { return ptr_; }
  size_t size() const { return size_; }
  void reset() {
    if (ptr_) MXTPUStorageFree(ptr_);  /* back to the pool */
    ptr_ = nullptr;
    size_ = 0;
  }

 private:
  void *ptr_ = nullptr;
  size_t size_ = 0;
};

struct StorageStats {
  uint64_t bytes_in_use, bytes_pooled, peak_bytes, num_allocs, num_pool_hits;
};

inline StorageStats storage_stats() {
  StorageStats s{};
  check(MXTPUStorageStats(&s.bytes_in_use, &s.bytes_pooled, &s.peak_bytes,
                          &s.num_allocs, &s.num_pool_hits),
        "MXTPUStorageStats");
  return s;
}

inline void storage_release_all() {
  check(MXTPUStorageReleaseAll(), "MXTPUStorageReleaseAll");
}

/* ---------------- dependency engine ----------------------------------- */

class Var {
 public:
  Var() { check(MXTPUEngineNewVar(&h_), "MXTPUEngineNewVar"); }
  ~Var() {
    if (h_) MXTPUEngineDeleteVar(h_);
  }
  Var(Var &&o) noexcept : h_(o.h_) { o.h_ = 0; }
  Var &operator=(Var &&o) noexcept {
    if (this != &o) std::swap(h_, o.h_);
    return *this;
  }
  Var(const Var &) = delete;
  Var &operator=(const Var &) = delete;

  MXTPUVarHandle handle() const { return h_; }
  void wait() const { check(MXTPUEngineWaitForVar(h_), "WaitForVar"); }

 private:
  MXTPUVarHandle h_ = 0;
};

namespace detail {
inline int trampoline(void *arg) {
  auto *fn = static_cast<std::function<void()> *>(arg);
  int rc = 0;
  try {
    (*fn)();
  } catch (...) {
    rc = -1;  /* engine records the failure against the opr id */
  }
  delete fn;
  return rc;
}
}  // namespace detail

class Engine {
 public:
  /* Push an arbitrary C++ callable with read (const) / write (mutable)
   * dependencies — the reference's Engine::PushAsync contract. */
  static uint64_t push(std::function<void()> fn,
                       const std::vector<const Var *> &const_vars = {},
                       const std::vector<const Var *> &mutable_vars = {},
                       int priority = 0) {
    std::vector<MXTPUVarHandle> cv, mv;
    for (const Var *v : const_vars) cv.push_back(v->handle());
    for (const Var *v : mutable_vars) mv.push_back(v->handle());
    auto *heap_fn = new std::function<void()>(std::move(fn));
    uint64_t id = 0;
    int rc = MXTPUEnginePushAsync(
        detail::trampoline, heap_fn, cv.empty() ? nullptr : cv.data(),
        static_cast<int>(cv.size()), mv.empty() ? nullptr : mv.data(),
        static_cast<int>(mv.size()), priority, &id);
    if (rc != 0) {
      delete heap_fn;
      check(rc, "MXTPUEnginePushAsync");
    }
    return id;
  }
  static void wait_all() { check(MXTPUEngineWaitForAll(), "WaitForAll"); }
  static int num_workers() {
    int n = 0;
    check(MXTPUEngineNumWorkers(&n), "NumWorkers");
    return n;
  }
  static bool is_naive() {
    int b = 0;
    check(MXTPUEngineIsNaive(&b), "IsNaive");
    return b != 0;
  }
};

/* ---------------- recordio -------------------------------------------- */

class RecordIOWriter {
 public:
  explicit RecordIOWriter(const std::string &path) {
    check(MXTPURecordIOWriterCreate(path.c_str(), &h_), "RecordIOWriterCreate");
  }
  ~RecordIOWriter() { close(); }
  RecordIOWriter(const RecordIOWriter &) = delete;
  RecordIOWriter &operator=(const RecordIOWriter &) = delete;

  /* Returns the record's seek position (for building .idx files). */
  uint64_t write(const void *buf, size_t size) {
    uint64_t pos = 0;
    check(MXTPURecordIOWriterWrite(h_, static_cast<const char *>(buf), size,
                                   &pos),
          "RecordIOWriterWrite");
    return pos;
  }
  uint64_t write(const std::string &s) { return write(s.data(), s.size()); }
  uint64_t tell() const {
    uint64_t pos = 0;
    check(MXTPURecordIOWriterTell(h_, &pos), "RecordIOWriterTell");
    return pos;
  }
  void close() {
    if (h_) {
      MXTPURecordIOWriterClose(h_);
      h_ = nullptr;
    }
  }

 private:
  void *h_ = nullptr;
};

class RecordIOReader {
 public:
  explicit RecordIOReader(const std::string &path) {
    check(MXTPURecordIOReaderCreate(path.c_str(), &h_), "RecordIOReaderCreate");
  }
  ~RecordIOReader() { close(); }
  RecordIOReader(const RecordIOReader &) = delete;
  RecordIOReader &operator=(const RecordIOReader &) = delete;

  /* False at EOF; the string_view-ish pair stays valid until next(). */
  bool next(std::string *out) {
    const char *buf = nullptr;
    size_t size = 0;
    check(MXTPURecordIOReaderNext(h_, &buf, &size), "RecordIOReaderNext");
    if (buf == nullptr) return false;
    out->assign(buf, size);
    return true;
  }
  void seek(uint64_t pos) {
    check(MXTPURecordIOReaderSeek(h_, pos), "RecordIOReaderSeek");
  }
  uint64_t tell() const {
    uint64_t pos = 0;
    check(MXTPURecordIOReaderTell(h_, &pos), "RecordIOReaderTell");
    return pos;
  }
  void close() {
    if (h_) {
      MXTPURecordIOReaderClose(h_);
      h_ = nullptr;
    }
  }

 private:
  void *h_ = nullptr;
};

}  // namespace mxtpu

/* ---------------- predict (separate library: -lmxtpu_predict) ---------- */

extern "C" {
const char *MXPredGetLastError(void);
int MXPredCreate(const char *symbol_json, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, void **out);
int MXPredSetInput(void *handle, const char *key, const float *data,
                   uint32_t size);
int MXPredForward(void *handle);
int MXPredGetOutputShape(void *handle, uint32_t index, uint32_t **shape_data,
                         uint32_t *shape_ndim);
int MXPredGetOutput(void *handle, uint32_t index, float *data, uint32_t size);
int MXPredFree(void *handle);
}

namespace mxtpu {

/* RAII over the reference-compatible 6-function predict ABI
 * (reference include/mxnet/c_predict_api.h consumers). */
class Predictor {
 public:
  struct Input {
    std::string name;
    std::vector<uint32_t> shape;
  };

  Predictor(const std::string &symbol_json, const std::string &param_bytes,
            const std::vector<Input> &inputs, int dev_type = 1,
            int dev_id = 0) {
    std::vector<const char *> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> shapes;
    for (const Input &in : inputs) {
      keys.push_back(in.name.c_str());
      for (uint32_t d : in.shape) shapes.push_back(d);
      indptr.push_back(static_cast<uint32_t>(shapes.size()));
    }
    int rc = MXPredCreate(symbol_json.c_str(), param_bytes.data(),
                          static_cast<int>(param_bytes.size()), dev_type,
                          dev_id, static_cast<uint32_t>(keys.size()),
                          keys.data(), indptr.data(), shapes.data(), &h_);
    if (rc != 0) throw Error(std::string("MXPredCreate: ") +
                             MXPredGetLastError());
  }
  ~Predictor() {
    if (h_) MXPredFree(h_);
  }
  Predictor(const Predictor &) = delete;
  Predictor &operator=(const Predictor &) = delete;

  void set_input(const std::string &key, const float *data, size_t size) {
    if (MXPredSetInput(h_, key.c_str(), data,
                       static_cast<uint32_t>(size)) != 0)
      throw Error(std::string("MXPredSetInput: ") + MXPredGetLastError());
  }
  void set_input(const std::string &key, const std::vector<float> &v) {
    set_input(key, v.data(), v.size());
  }
  void forward() {
    if (MXPredForward(h_) != 0)
      throw Error(std::string("MXPredForward: ") + MXPredGetLastError());
  }
  std::vector<uint32_t> output_shape(uint32_t index = 0) const {
    uint32_t *dims = nullptr, ndim = 0;
    if (MXPredGetOutputShape(h_, index, &dims, &ndim) != 0)
      throw Error(std::string("MXPredGetOutputShape: ") +
                  MXPredGetLastError());
    return std::vector<uint32_t>(dims, dims + ndim);
  }
  std::vector<float> output(uint32_t index = 0) const {
    size_t n = 1;
    for (uint32_t d : output_shape(index)) n *= d;
    std::vector<float> out(n);
    if (MXPredGetOutput(h_, index, out.data(),
                        static_cast<uint32_t>(n)) != 0)
      throw Error(std::string("MXPredGetOutput: ") + MXPredGetLastError());
    return out;
  }

 private:
  void *h_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_HPP_
