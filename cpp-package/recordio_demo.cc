/*
 * Minimal C++ frontend over the mxnet_tpu native ABI (src/mxtpu.h) —
 * the analogue of the reference's header-only cpp-package
 * (cpp-package/include/mxnet-cpp) built on the flat C API.
 *
 * Demonstrates the host-side runtime from pure C++ with no Python:
 * writes a .rec dataset, reads it back through the dependency engine
 * (reader op ordered behind the writer via an engine variable), and prints
 * storage-pool stats.
 *
 * Build + run:
 *   g++ -std=c++17 -O2 cpp-package/recordio_demo.cc -Isrc -Lsrc/build \
 *       -lmxtpu -Wl,-rpath,$PWD/src/build -o /tmp/recordio_demo && /tmp/recordio_demo
 */
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "mxtpu.h"

#define CHECK_OK(call)                                              \
  do {                                                              \
    if ((call) != 0) {                                              \
      std::fprintf(stderr, "error: %s\n", MXTPUGetLastError());     \
      return 1;                                                     \
    }                                                               \
  } while (0)

struct WriteJob {
  const char *path;
  int n;
};

static int WriteRecords(void *arg) {
  auto *job = static_cast<WriteJob *>(arg);
  void *w = nullptr;
  if (MXTPURecordIOWriterCreate(job->path, &w) != 0) return 1;
  for (int i = 0; i < job->n; ++i) {
    std::string rec = "record-" + std::to_string(i);
    uint64_t pos;
    if (MXTPURecordIOWriterWrite(w, rec.data(), rec.size(), &pos) != 0) return 1;
  }
  return MXTPURecordIOWriterClose(w);
}

int main() {
  int version = 0;
  CHECK_OK(MXTPUGetVersion(&version));
  std::printf("mxtpu native runtime, capability version %d\n", version);

  // storage pool round trip
  void *buf = nullptr;
  CHECK_OK(MXTPUStorageAlloc(1 << 20, &buf));
  std::memset(buf, 0, 1 << 20);
  CHECK_OK(MXTPUStorageFree(buf));
  uint64_t in_use, pooled, peak, nalloc, nhit;
  CHECK_OK(MXTPUStorageStats(&in_use, &pooled, &peak, &nalloc, &nhit));
  std::printf("storage: in_use=%llu pooled=%llu peak=%llu allocs=%llu hits=%llu\n",
              (unsigned long long)in_use, (unsigned long long)pooled,
              (unsigned long long)peak, (unsigned long long)nalloc,
              (unsigned long long)nhit);

  // write a dataset through the dependency engine, then read it back after
  // waiting on the var that orders the write.
  const char *path = "/tmp/mxtpu_demo.rec";
  WriteJob job{path, 5};
  MXTPUVarHandle file_var;
  CHECK_OK(MXTPUEngineNewVar(&file_var));
  uint64_t opr_id;
  CHECK_OK(MXTPUEnginePushAsync(WriteRecords, &job, nullptr, 0, &file_var, 1, 0, &opr_id));
  CHECK_OK(MXTPUEngineWaitForVar(file_var));

  void *r = nullptr;
  CHECK_OK(MXTPURecordIOReaderCreate(path, &r));
  int count = 0;
  while (true) {
    const char *rec;
    size_t size;
    CHECK_OK(MXTPURecordIOReaderNext(r, &rec, &size));
    if (rec == nullptr) break;
    std::printf("  read [%d]: %.*s\n", count, (int)size, rec);
    ++count;
  }
  CHECK_OK(MXTPURecordIOReaderClose(r));
  CHECK_OK(MXTPUEngineDeleteVar(file_var));
  std::printf("read %d records OK\n", count);
  return count == 5 ? 0 : 1;
}
