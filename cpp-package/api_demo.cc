/*
 * Exercises the C++ frontend classes (include/mxtpu.hpp) end-to-end:
 * pooled buffers, dependency-engine push with RW deps, recordio
 * round-trip — the non-predict half of the frontend. Prints API_DEMO_OK
 * on success (tests/test_cpp_frontend.py asserts on it).
 *
 * Build: make -C cpp-package api_demo
 */
#include <atomic>
#include <cstdio>
#include <string>
#include <vector>

#include "include/mxtpu.hpp"

int main(int argc, char **argv) {
  const std::string rec_path =
      argc > 1 ? argv[1] : "/tmp/mxtpu_api_demo.rec";

  /* storage pool: alloc/free hits the bucket pool on the second pass */
  {
    mxtpu::Buffer a(1 << 16);
    std::memset(a.data(), 0xab, a.size());
  }
  mxtpu::Buffer b(1 << 16);  /* same bucket -> pool hit */
  mxtpu::StorageStats st = mxtpu::storage_stats();
  std::printf("storage: in_use=%llu pooled=%llu allocs=%llu hits=%llu\n",
              (unsigned long long)st.bytes_in_use,
              (unsigned long long)st.bytes_pooled,
              (unsigned long long)st.num_allocs,
              (unsigned long long)st.num_pool_hits);
  if (st.num_pool_hits < 1) {
    std::fprintf(stderr, "expected a pool hit\n");
    return 1;
  }

  /* engine: writer -> two readers -> writer, ordered by var deps */
  mxtpu::Var var;
  std::atomic<int> value{0};
  std::atomic<bool> readers_ok{true};
  mxtpu::Engine::push([&] { value = 42; }, {}, {&var});
  for (int i = 0; i < 2; ++i)
    mxtpu::Engine::push([&] { if (value != 42) readers_ok = false; },
                        {&var}, {});
  mxtpu::Engine::push([&] { value = 7; }, {}, {&var});
  var.wait();
  mxtpu::Engine::wait_all();
  std::printf("engine: workers=%d naive=%d final=%d readers_ok=%d\n",
              mxtpu::Engine::num_workers(), (int)mxtpu::Engine::is_naive(),
              value.load(), (int)readers_ok.load());
  if (value != 7 || !readers_ok) {
    std::fprintf(stderr, "engine ordering violated\n");
    return 1;
  }

  /* recordio: write 100 records, read them back, seek to the 50th */
  std::vector<uint64_t> positions;
  {
    mxtpu::RecordIOWriter w(rec_path);
    for (int i = 0; i < 100; ++i)
      positions.push_back(w.write("record-" + std::to_string(i)));
  }
  mxtpu::RecordIOReader r(rec_path);
  std::string rec;
  int n = 0;
  while (r.next(&rec)) {
    if (rec != "record-" + std::to_string(n)) {
      std::fprintf(stderr, "record %d corrupt: %s\n", n, rec.c_str());
      return 1;
    }
    ++n;
  }
  r.seek(positions[50]);
  r.next(&rec);
  std::printf("recordio: %d records, seek(50) -> %s\n", n, rec.c_str());
  if (n != 100 || rec != "record-50") return 1;

  std::printf("mxtpu version %d\nAPI_DEMO_OK\n", mxtpu::version());
  return 0;
}
