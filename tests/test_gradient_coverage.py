"""Registry-wide gradient/oracle coverage (VERDICT r4 item 5).

The reference's backbone is finite-difference checking of essentially every
differentiable op (``python/mxnet/test_utils.py check_numeric_gradient``).
This module closes the gap left by the family suites: every distinct
registered op must be (a) gradient-checked here or in a named suite,
(b) forward-checked here, or (c) explicitly exempted with a reason —
``test_registry_fully_accounted`` enforces that and writes the coverage
report to ``docs/grad_coverage.md``.
"""
import os
import re
from pathlib import Path

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops.registry import OP_REGISTRY
from mxnet_tpu.test_utils import check_numeric_gradient

RS = np.random.RandomState(21)
REPO = Path(__file__).resolve().parent.parent


def A(*shape):
    return RS.randn(*shape).astype(np.float32)


def POS(*shape):
    return (RS.rand(*shape).astype(np.float32) + 0.5)


def SPD(n):
    b = RS.randn(n, n).astype(np.float32)
    return b @ b.T + n * np.eye(n, dtype=np.float32)


def TRI(n):
    return np.tril(RS.randn(n, n).astype(np.float32)) + 2 * np.eye(n, dtype=np.float32)


# ---------------------------------------------------------------------------
# gradient specs: name -> (diff inputs, const inputs-after, attrs, tols)
# Each spec: dict(d=[arrays w/ grads checked], c=[(pos, array)], attrs={},
#                 rtol=, atol=, eps=)
# ---------------------------------------------------------------------------

def spec(d, c=(), attrs=None, **tol):
    return {"d": d, "c": list(c), "attrs": attrs or {}, "tol": tol}


def _interleave(diff_args, const, n_total):
    """Reassemble the op's positional inputs from diff args + (pos, value)."""
    out = [None] * n_total
    for pos, val in const:
        out[pos] = val
    it = iter(diff_args)
    for i in range(n_total):
        if out[i] is None:
            out[i] = next(it)
    return out


GRAD = {
    # ---- layers ----------------------------------------------------------
    "Activation": spec([A(3, 4)], attrs={"act_type": "tanh"}),
    "SoftmaxActivation": spec([A(3, 5)]),
    "LeakyReLU": spec([(lambda x: np.where(np.abs(x) < .1, .6, x))(A(3, 4))],
                      attrs={"slope": 0.3}),
    "FullyConnected": spec([A(4, 6), A(3, 6), A(3)],
                           attrs={"num_hidden": 3}),
    "Convolution": spec([A(1, 2, 6, 6), A(3, 2, 3, 3), A(3)],
                        attrs={"kernel": (3, 3), "num_filter": 3},
                        rtol=2e-2, atol=5e-3),
    "Deconvolution": spec([A(1, 3, 4, 4), A(3, 2, 3, 3), A(2)],
                          attrs={"kernel": (3, 3), "num_filter": 2},
                          rtol=2e-2, atol=5e-3),
    "Pooling": spec([A(1, 2, 6, 6)],
                    attrs={"kernel": (2, 2), "stride": (2, 2),
                           "pool_type": "avg"}),
    # use_global_stats pins train/eval to the same statistics: the fd
    # probe runs outside autograd.record, which would otherwise flip the
    # op into eval mode and compare two different functions
    "BatchNorm": spec([A(4, 3), POS(3), A(3)],
                      c=[(3, np.zeros(3, np.float32)),
                         (4, np.ones(3, np.float32))],
                      attrs={"use_global_stats": True, "fix_gamma": False},
                      rtol=3e-2, atol=5e-3),
    "InstanceNorm": spec([A(2, 3, 5), POS(3), A(3)], rtol=3e-2, atol=5e-3),
    "LayerNorm": spec([A(4, 6), POS(6), A(6)], rtol=3e-2, atol=5e-3),
    "L2Normalization": spec([A(3, 4) + 2.0], rtol=2e-2),
    "LRN": spec([POS(1, 4, 5, 5)], attrs={"nsize": 3}, rtol=2e-2),
    "Embedding": spec([A(7, 4)], c=[(0, np.array([[1, 3], [5, 1]]))],
                      attrs={"input_dim": 7, "output_dim": 4}),
    "_contrib_SparseEmbedding": spec(
        [A(7, 4)], c=[(0, np.array([[1, 3], [5, 1]]))],
        attrs={"input_dim": 7, "output_dim": 4}),
    "Concat": spec([A(2, 3), A(2, 4)], attrs={"num_args": 2, "dim": 1}),
    "SliceChannel": spec([A(2, 6)], attrs={"num_outputs": 2, "axis": 1}),
    "Reshape": spec([A(2, 6)], attrs={"shape": (3, 4)}),
    "SwapAxis": spec([A(2, 3, 4)], attrs={"dim1": 0, "dim2": 2}),
    "Pad": spec([A(1, 2, 3, 3)],
                attrs={"mode": "constant",
                       "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    "UpSampling": spec([A(1, 2, 3, 3)],
                       attrs={"scale": 2, "sample_type": "nearest",
                              "num_args": 1}),
    "Crop": spec([A(1, 2, 6, 6)],
                 attrs={"num_args": 1, "offset": (1, 1), "h_w": (3, 3)}),
    "Dropout": spec([A(3, 4)], attrs={"p": 0.0}),  # p=0: deterministic
    "Cast": spec([A(3, 4)], attrs={"dtype": "float32"}),
    "BlockGrad": spec([A(3, 4)], expect_zero_grad=True),
    "_copy": spec([A(3, 4)]),
    "_grad_add": spec([A(3, 4), A(3, 4)]),
    "_identity_with_attr_like_rhs": spec([A(3, 4)], c=[(1, A(3, 4))]),
    "IdentityAttachKLSparseReg": spec([POS(3, 4) * 0.1]),
    "make_loss": spec([A(3, 4)]),
    # ---- losses / outputs ------------------------------------------------
    "CTCLoss": spec([A(2, 6, 5)], c=[(1, np.array([[1., 2.], [2., 3.]]))],
                    rtol=5e-2, atol=1e-2),
    "softmax_cross_entropy": spec([A(4, 5)],
                                  c=[(1, np.array([1., 0., 3., 2.]))],
                                  rtol=3e-2, atol=1e-3),
    "smooth_l1": spec([A(3, 4) * 0.3 + 2.0], attrs={"scalar": 1.0}),
    "softmax": spec([A(3, 5)]),
    "softmin": spec([A(3, 5)]),
    "log_softmax": spec([A(3, 5)], rtol=3e-2, atol=1e-3),
    "hard_sigmoid": spec([A(3, 4) * 0.3]),
    "erfinv": spec([A(3, 4) * 0.2]),
    # ---- sequence --------------------------------------------------------
    "SequenceLast": spec([A(5, 3, 2)]),
    "SequenceMask": spec([A(5, 3, 2)]),
    "SequenceReverse": spec([A(5, 3, 2)]),
    # ---- tensor / contraction -------------------------------------------
    "dot": spec([A(3, 4), A(4, 2)]),
    "batch_dot": spec([A(2, 3, 4), A(2, 4, 2)], atol=1e-3),
    "khatri_rao": spec([A(3, 4), A(2, 4)], attrs={"num_args": 2}),
    "add_n": spec([A(3, 4), A(3, 4), A(3, 4)], attrs={"num_args": 3}),
    "stack": spec([A(3, 4), A(3, 4)], attrs={"num_args": 2, "axis": 1}),
    "where": spec([A(3, 4), A(3, 4)],
                  c=[(0, (RS.rand(3, 4) > 0.5).astype(np.float32))]),
    "norm": spec([A(3, 4) + 2.0], attrs={"ord": 2}),
    "_square_sum": spec([A(4, 3)], attrs={"axis": (1,)}),
    "_maximum": spec([A(3, 4), A(3, 4) + 2.0]),
    "_minimum": spec([A(3, 4), A(3, 4) + 2.0]),
    "_mod": spec([POS(3, 4) * 7, POS(3, 4) + 2.0], rtol=2e-2),
    "broadcast_mod": spec([POS(3, 4) * 7, POS(1, 4) + 2.0], rtol=2e-2),
    "_power": spec([POS(3, 4), POS(3, 4)], rtol=2e-2),
    "_hypot": spec([A(3, 4) + 3, A(3, 4) - 3]),
    "_hypot_scalar": spec([A(3, 4)], attrs={"scalar": 2.0}),
    "_rpower_scalar": spec([A(3, 4) * 0.3], attrs={"scalar": 2.0}),
    "_rmod_scalar": spec([POS(3, 4) + 1.5], attrs={"scalar": 7.0}, rtol=2e-2),
    "broadcast_axis": spec([A(3, 1, 4)], attrs={"axis": 1, "size": 2}),
    "broadcast_to": spec([A(3, 1, 4)], attrs={"shape": (3, 2, 4)}),
    "broadcast_like": spec([A(3, 1)], c=[(1, A(3, 5))]),
    "reshape_like": spec([A(2, 6)], c=[(1, A(3, 4))]),
    "slice_like": spec([A(4, 5)], c=[(1, A(2, 3))]),
    "diag": spec([A(4, 4)]),
    "depth_to_space": spec([A(1, 4, 2, 2)], attrs={"block_size": 2}),
    "space_to_depth": spec([A(1, 1, 4, 4)], attrs={"block_size": 2}),
    "batch_take": spec([A(4, 5)], c=[(1, np.array([1, 0, 3, 2]))]),
    "scatter_nd": spec([A(4)], c=[(1, np.array([[0, 2, 1, 3]]))],
                       attrs={"shape": (5,)}),
    "argmax_channel": spec([A(3, 4)], expect_zero_grad=True),
    # ---- vision tail -----------------------------------------------------
    "_contrib_AdaptiveAvgPooling2D": spec([A(1, 2, 6, 6)],
                                          attrs={"output_size": (3, 3)}),
    "_contrib_BilinearResize2D": spec([A(1, 2, 4, 4)],
                                      attrs={"height": 8, "width": 8},
                                      rtol=2e-2),
    "BilinearSampler": spec(
        [A(1, 2, 5, 5)],
        c=[(1, (RS.rand(1, 2, 4, 4).astype(np.float32) - 0.5) * 1.2)],
        rtol=3e-2, atol=5e-3),
    "GridGenerator": spec([A(1, 6) * 0.1 + np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
                          attrs={"transform_type": "affine",
                                 "target_shape": (4, 4)},
                          rtol=2e-2, atol=1e-3),
    "SpatialTransformer": spec(
        [A(1, 2, 5, 5), A(1, 6) * 0.05 + np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        attrs={"transform_type": "affine", "sampler_type": "bilinear",
               "target_shape": (4, 4)}, rtol=3e-2, atol=5e-3),
    "Correlation": spec([A(1, 2, 5, 5), A(1, 2, 5, 5)],
                        attrs={"kernel_size": 1, "max_displacement": 1,
                               "stride1": 1, "stride2": 1},
                        rtol=3e-2, atol=5e-3),
    "ROIPooling": spec(
        [A(1, 2, 6, 6)], c=[(1, np.array([[0, 0, 0, 4, 4]], np.float32))],
        attrs={"pooled_size": (2, 2), "spatial_scale": 1.0}),
    "_contrib_ROIAlign": spec(
        [A(1, 2, 6, 6)], c=[(1, np.array([[0, 0.5, 0.5, 4, 4]], np.float32))],
        attrs={"pooled_size": (2, 2), "spatial_scale": 1.0},
        rtol=3e-2, atol=5e-3),
    "_contrib_DeformablePSROIPooling": spec(
        [A(1, 4, 4, 4), A(1, 2, 2, 2) * 0.1],
        c=[(1, np.array([[0, 1, 1, 3, 3]], np.float32))],
        attrs={"spatial_scale": 1.0, "output_dim": 1, "group_size": 2,
               "pooled_size": 2, "sample_per_part": 1, "trans_std": 0.1},
        rtol=3e-2, atol=5e-3),
    "_contrib_PSROIPooling": spec(
        [A(1, 8, 6, 6)], c=[(1, np.array([[0, 0, 0, 4, 4]], np.float32))],
        attrs={"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2},
        rtol=3e-2, atol=5e-3),
    "_contrib_count_sketch": spec(
        [A(3, 8)],
        c=[(1, RS.randint(0, 5, (1, 8)).astype(np.float32)),
           (2, RS.choice([-1.0, 1.0], (1, 8)).astype(np.float32))],
        attrs={"out_dim": 5}),
    "_contrib_fft": spec([A(2, 8)], rtol=5e-2, atol=1e-3),
    "_contrib_ifft": spec([A(2, 16)], rtol=5e-2, atol=1e-3),
    # ---- linalg ----------------------------------------------------------
    "_linalg_gemm": spec([A(3, 4), A(4, 2), A(3, 2)]),
    "_linalg_gemm2": spec([A(3, 4), A(4, 2)]),
    "_linalg_syrk": spec([A(3, 4)]),
    "_linalg_trmm": spec([TRI(3)], c=[(1, A(3, 4))]),
    "_linalg_trsm": spec([TRI(3)], c=[(1, A(3, 4))], rtol=3e-2, atol=5e-3),
    "_linalg_potrf": spec([SPD(3)], rtol=3e-2, atol=5e-3),
    "_linalg_sumlogdiag": spec([SPD(3)]),
    "_linalg_extractdiag": spec([A(4, 4)]),
    "_linalg_makediag": spec([A(4)]),
    "_linalg_det": spec([SPD(3)], rtol=3e-2, atol=5e-3),
    "_linalg_inverse": spec([SPD(3)], rtol=3e-2, atol=5e-3),
}


@pytest.mark.parametrize("name", sorted(GRAD))
def test_gradient(name):
    s = GRAD[name]
    n_total = len(s["d"]) + len(s["c"])
    attrs = s["attrs"]

    def fn(*xs):
        args = _interleave(xs, [(p, mx.nd.array(v)) for p, v in s["c"]],
                           n_total)
        out = invoke(name, *args, **attrs)
        return out

    tol = dict(s["tol"])
    if tol.pop("expect_zero_grad", False):
        x = mx.nd.array(s["d"][0])
        x.attach_grad()
        from mxnet_tpu import autograd
        with autograd.record():
            out = fn(x)
            loss = out.sum() if not isinstance(out, (list, tuple)) \
                else sum(o.sum() for o in out)
        loss.backward()
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   np.zeros_like(s["d"][0]), atol=1e-7)
        return
    check_numeric_gradient(fn, [d.copy() for d in s["d"]], **tol)


# ---------------------------------------------------------------------------
# forward-only specs (non-differentiable / random / data-dependent output)
# ---------------------------------------------------------------------------

def fwd(inputs, attrs=None, oracle=None, shape=None):
    return {"in": inputs, "attrs": attrs or {}, "oracle": oracle,
            "shape": shape}


_cmp = lambda f: (lambda a, b: f(a, b).astype(np.float32))

FWD = {
    "_equal": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.equal)),
    "_not_equal": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.not_equal)),
    "_greater": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.greater)),
    "_greater_equal": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.greater_equal)),
    "_lesser": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.less)),
    "_lesser_equal": fwd([A(3, 4), A(3, 4)], oracle=_cmp(np.less_equal)),
    "_logical_and": fwd([A(3, 4), A(3, 4)],
                        oracle=lambda a, b: np.logical_and(a, b).astype(np.float32)),
    "_logical_or": fwd([A(3, 4), A(3, 4)],
                       oracle=lambda a, b: np.logical_or(a, b).astype(np.float32)),
    "_logical_xor": fwd([A(3, 4), A(3, 4)],
                        oracle=lambda a, b: np.logical_xor(a != 0, b != 0).astype(np.float32)),
    "_equal_scalar": fwd([A(3, 4)], attrs={"scalar": 0.5},
                         oracle=lambda a: (a == 0.5).astype(np.float32)),
    "_not_equal_scalar": fwd([A(3, 4)], attrs={"scalar": 0.5},
                             oracle=lambda a: (a != 0.5).astype(np.float32)),
    "_greater_scalar": fwd([A(3, 4)], attrs={"scalar": 0.0},
                           oracle=lambda a: (a > 0).astype(np.float32)),
    "_greater_equal_scalar": fwd([A(3, 4)], attrs={"scalar": 0.0},
                                 oracle=lambda a: (a >= 0).astype(np.float32)),
    "_lesser_scalar": fwd([A(3, 4)], attrs={"scalar": 0.0},
                          oracle=lambda a: (a < 0).astype(np.float32)),
    "_lesser_equal_scalar": fwd([A(3, 4)], attrs={"scalar": 0.0},
                                oracle=lambda a: (a <= 0).astype(np.float32)),
    "_logical_and_scalar": fwd([A(3, 4)], attrs={"scalar": 1.0},
                               oracle=lambda a: np.logical_and(a, 1).astype(np.float32)),
    "_logical_or_scalar": fwd([A(3, 4)], attrs={"scalar": 0.0},
                              oracle=lambda a: np.logical_or(a, 0).astype(np.float32)),
    "_logical_xor_scalar": fwd([A(3, 4)], attrs={"scalar": 1.0},
                               oracle=lambda a: np.logical_xor(a != 0, True).astype(np.float32)),
    "fix": fwd([A(3, 4) * 3], oracle=np.fix),
    "_histogram": fwd([A(100)], attrs={"bin_cnt": 10, "range": (-3, 3)},
                      oracle=lambda a: np.histogram(a, bins=10, range=(-3, 3))[0].astype(np.float32)),
    "_arange": fwd([], attrs={"start": 0, "stop": 8},
                   oracle=lambda: np.arange(0, 8, dtype=np.float32)),
    "_eye": fwd([], attrs={"N": 4}, oracle=lambda: np.eye(4, dtype=np.float32)),
    "_full": fwd([], attrs={"shape": (2, 3), "value": 2.5},
                 oracle=lambda: np.full((2, 3), 2.5, np.float32)),
    "_ones": fwd([], attrs={"shape": (2, 3)},
                 oracle=lambda: np.ones((2, 3), np.float32)),
    "_zeros": fwd([], attrs={"shape": (2, 3)},
                  oracle=lambda: np.zeros((2, 3), np.float32)),
    "ones_like": fwd([A(2, 3)], oracle=np.ones_like),
    "zeros_like": fwd([A(2, 3)], oracle=np.zeros_like),
    "shape_array": fwd([A(2, 3)],
                       oracle=lambda a: np.array([2, 3], np.int64)),
    "size_array": fwd([A(2, 3)], oracle=lambda a: np.array([6], np.int64)),
    "_ravel_multi_index": fwd([np.array([[1., 0.], [2., 3.]])],
                              attrs={"shape": (4, 5)},
                              oracle=lambda a: np.ravel_multi_index(
                                  a.astype(np.int64), (4, 5)).astype(np.float32)),
    "_unravel_index": fwd([np.array([7., 13.])], attrs={"shape": (4, 5)},
                          oracle=lambda a: np.stack(np.unravel_index(
                              a.astype(np.int64), (4, 5))).astype(np.float32)),
    "_scatter_set_nd": fwd([np.zeros((5,), np.float32), A(4),
                            np.array([[0, 2, 1, 3]])], attrs={"shape": (5,)}),
    "_rnn_param_concat": fwd([A(4), A(6)], attrs={"num_args": 2, "dim": 0},
                             oracle=lambda a, b: np.concatenate([a, b])),
    # random: shape/dtype/finite checks only
    "_random_uniform": fwd([], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_normal": fwd([], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_exponential": fwd([], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_gamma": fwd([], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_poisson": fwd([], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_negative_binomial": fwd([], attrs={"shape": (3, 4)},
                                     shape=(3, 4)),
    "_random_generalized_negative_binomial": fwd(
        [], attrs={"shape": (3, 4)}, shape=(3, 4)),
    "_random_randint": fwd([], attrs={"shape": (3, 4), "low": 0, "high": 9},
                           shape=(3, 4)),
    "_sample_uniform": fwd([np.zeros(2, np.float32), np.ones(2, np.float32)],
                           attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_normal": fwd([np.zeros(2, np.float32), np.ones(2, np.float32)],
                          attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_exponential": fwd([np.ones(2, np.float32)],
                               attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_gamma": fwd([np.ones(2, np.float32), np.ones(2, np.float32)],
                         attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_poisson": fwd([np.ones(2, np.float32)],
                           attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_negative_binomial": fwd(
        [np.ones(2, np.float32) * 3, np.ones(2, np.float32) * 0.5],
        attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_generalized_negative_binomial": fwd(
        [np.ones(2, np.float32) * 3, np.ones(2, np.float32) * 0.3],
        attrs={"shape": (5,)}, shape=(2, 5)),
    "_sample_multinomial": fwd([np.full((2, 4), 0.25, np.float32)],
                               attrs={"shape": 6}, shape=(2, 6)),
    "_sample_unique_zipfian": fwd([], attrs={"range_max": 100,
                                             "shape": (1, 8)}),
    "_shuffle": fwd([A(8, 2)], shape=(8, 2)),
    "_NoGradient": fwd([], oracle=lambda: np.zeros(())),
    # detection tail: executable forward, structural checks
    "_contrib_MultiBoxPrior": fwd([A(1, 3, 4, 4)],
                                  attrs={"sizes": (0.5,), "ratios": (1.0,)}),
    "_contrib_MultiBoxTarget": fwd(
        [np.array([[[0.1, 0.1, 0.4, 0.4]]], np.float32),
         np.array([[[0., 0.1, 0.1, 0.4, 0.4]]], np.float32),
         np.full((1, 2, 1), 0.5, np.float32)]),
    "_contrib_MultiBoxDetection": fwd(
        [np.array([[[0.3, 0.7]]], np.float32).transpose(0, 2, 1),
         np.array([[0.0, 0.0, 0.0, 0.0]], np.float32),
         np.array([[[0.1, 0.1, 0.4, 0.4]]], np.float32)]),
    "_contrib_box_iou": fwd([np.array([[0., 0., 1., 1.]], np.float32),
                             np.array([[0., 0., 1., 1.]], np.float32)]),
    "_contrib_box_nms": fwd([np.array([[1, 0.9, 0, 0, 1, 1],
                                       [1, 0.8, 0, 0, 1, 1]], np.float32)]),
    "_contrib_bipartite_matching": fwd(
        [np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)],
        attrs={"threshold": 0.05}),
    "_contrib_quantized_flatten": fwd(
        [RS.randint(-100, 100, (2, 3, 4)).astype(np.int8),
         np.array([-1.0], np.float32), np.array([1.0], np.float32)]),
    "_contrib_quantized_pooling": fwd(
        [RS.randint(-100, 100, (1, 2, 4, 4)).astype(np.int8),
         np.array([-1.0], np.float32), np.array([1.0], np.float32)],
        attrs={"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
    "Proposal": fwd([np.abs(A(1, 2, 4, 4)), A(1, 4, 4, 4),
                     np.array([[32., 32., 1.]], np.float32)],
                    attrs={"feature_stride": 8, "rpn_pre_nms_top_n": 6,
                           "rpn_post_nms_top_n": 4, "scales": (8.0,),
                           "ratios": (1.0,), "rpn_min_size": 1}),
}


@pytest.mark.parametrize("name", sorted(FWD))
def test_forward(name):
    s = FWD[name]
    out = invoke(name, *[mx.nd.array(x) for x in s["in"]], **s["attrs"])
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        v = o.asnumpy()
        assert np.isfinite(v.astype(np.float64)).all() or name == "_contrib_box_nms"
    if s["shape"] is not None:
        assert outs[0].shape == tuple(s["shape"]), outs[0].shape
    if s["oracle"] is not None:
        expect = s["oracle"](*s["in"])
        np.testing.assert_allclose(outs[0].asnumpy().astype(np.float64),
                                   np.asarray(expect, np.float64),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# exemptions: op -> (test file that owns it, reason)
# ---------------------------------------------------------------------------

EXEMPT = {
    "Custom": ("tests/test_contrib.py", "custom-op bridge has its own suite"),
    "RNN": ("tests/test_gluon_rnn.py", "fused RNN forward/backward suite"),
    "_foreach": ("tests/test_control_flow.py", "control-flow suite"),
    "_while_loop": ("tests/test_control_flow.py", "control-flow suite"),
    "_cond": ("tests/test_control_flow.py", "control-flow suite"),
    "_subgraph_op": ("tests/test_subgraph.py", "subgraph partitioner suite"),
    "sgd_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "sgd_mom_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "mp_sgd_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "mp_sgd_mom_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "adam_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "ftml_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "ftrl_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "rmsprop_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "rmspropalex_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "signsgd_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "signum_update": ("tests/test_optimizer_ops.py", "optimizer-update suite"),
    "_sparse_adagrad_update": ("tests/test_optimizer_ops.py",
                               "optimizer-update suite"),
    "cast_storage": ("tests/test_sparse_ops.py", "sparse-op suite"),
    "_sparse_retain": ("tests/test_sparse_ops.py", "sparse-op suite"),
    "_contrib_quantize": ("tests/test_quantization.py", "quantization suite"),
    "_contrib_dequantize": ("tests/test_quantization.py", "quantization suite"),
    "_contrib_requantize": ("tests/test_quantization.py", "quantization suite"),
    "_contrib_quantized_conv": ("tests/test_quantization.py",
                                "quantization suite"),
    "_contrib_quantized_fully_connected": ("tests/test_quantization.py",
                                           "quantization suite"),
    "_contrib_DeformableConvolution": ("tests/test_vision_tail.py",
                                       "deformable conv suite"),
}


# ops verified by dedicated closed-form/oracle tests in THIS module
CUSTOM_TESTED = {
    "_contrib_flash_attention":
        "Pallas kernel: oracle + gradient tests in test_sequence_parallel.py",
    "SoftmaxOutput": "closed-form custom-backward test",
    "LinearRegressionOutput": "closed-form custom-backward test",
    "LogisticRegressionOutput": "closed-form custom-backward test",
    "MAERegressionOutput": "closed-form custom-backward test",
    "SVMOutput": "closed-form custom-backward test",
    "_linalg_gelqf": "reconstruction/orthonormality oracle",
    "_linalg_syevd": "eigendecomposition reconstruction oracle",
    "_linalg_slogdet": "numpy slogdet oracle",
    "_linalg_potri": "cholesky-inverse oracle",
}


def test_registry_fully_accounted():
    """Every distinct registered op must be gradient-checked, forward-
    checked, or exempted to a named suite (verified to mention it). Writes
    docs/grad_coverage.md."""
    distinct = {}
    for alias, od in OP_REGISTRY.items():
        distinct[od.name] = od
    ops = sorted(distinct)

    here = set(GRAD) | set(FWD)
    sweep_text = (REPO / "tests" / "test_operator_sweep.py").read_text()
    operator_text = (REPO / "tests" / "test_operator.py").read_text()

    rows = []
    missing = []
    for op in ops:
        if op in GRAD:
            rows.append((op, "grad-checked", "tests/test_gradient_coverage.py"))
        elif op in CUSTOM_TESTED:
            rows.append((op, CUSTOM_TESTED[op],
                         "tests/test_gradient_coverage.py"))
        elif op in FWD:
            rows.append((op, "forward-oracle", "tests/test_gradient_coverage.py"))
        elif op in EXEMPT:
            f, reason = EXEMPT[op]
            text = (REPO / f).read_text()
            forms = (op, op.lstrip("_"),
                     op.replace("_contrib_", ""), op.replace("_linalg_", ""))
            assert any(v in text for v in forms), \
                "%s exempted to %s but not mentioned there" % (op, f)
            rows.append((op, "suite: %s" % reason, f))
        elif ('"%s"' % op) in sweep_text:
            rows.append((op, "swept", "tests/test_operator_sweep.py"))
        elif re.search(r"\b%s\b" % re.escape(op), operator_text):
            rows.append((op, "family tests", "tests/test_operator.py"))
        else:
            missing.append(op)

    covered = len(rows)
    total = len(ops)
    lines = ["# Operator gradient/oracle coverage",
             "",
             "Auto-generated by tests/test_gradient_coverage.py.",
             "",
             "Coverage: **%d/%d distinct ops (%.0f%%)** — %d gradient-checked"
             " here, %d forward-oracle here, remainder owned by named suites."
             % (covered, total, 100 * covered / total, len(GRAD), len(FWD)),
             "", "| op | status | where |", "|---|---|---|"]
    for op, status, where in rows:
        lines.append("| %s | %s | %s |" % (op, status, where))
    if missing:
        lines.append("")
        lines.append("## UNCOVERED")
        for op in missing:
            lines.append("- %s" % op)
    (REPO / "docs" / "grad_coverage.md").write_text("\n".join(lines) + "\n")

    assert covered / total >= 0.9, \
        "coverage %.0f%% < 90%%; uncovered: %s" % (100 * covered / total,
                                                   missing)
    assert not missing, "unaccounted ops: %s" % missing


# ---------------------------------------------------------------------------
# loss-output ops: custom reference backwards (finite differences of the
# FORWARD cannot match by design — the reference backward ignores the
# incoming gradient), so each is checked against its documented closed form.
# ---------------------------------------------------------------------------

def _loss_grad(name, arrays, attrs=None):
    from mxnet_tpu import autograd
    nds = [mx.nd.array(a) for a in arrays]
    nds[0].attach_grad()
    with autograd.record():
        out = invoke(name, *nds, **(attrs or {}))
        loss = out.sum()
    loss.backward()
    return nds[0].grad.asnumpy(), out.asnumpy()


def _onehot(idx, k):
    return np.eye(k, dtype=np.float32)[idx.astype(np.int64)]


def test_softmax_output_reference_gradient():
    """grad = softmax(data) - onehot(label) (src/operator/softmax_output-inl.h)."""
    data, label = A(4, 5), np.array([1., 0., 3., 2.])
    g, out = _loss_grad("SoftmaxOutput", [data, label])
    prob = np.exp(data - data.max(1, keepdims=True))
    prob /= prob.sum(1, keepdims=True)
    np.testing.assert_allclose(out, prob, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(g, prob - _onehot(label, 5), rtol=1e-5,
                               atol=1e-6)
    # normalization="batch" divides by batch size
    g2, _ = _loss_grad("SoftmaxOutput", [data, label],
                       {"normalization": "batch"})
    np.testing.assert_allclose(g2, (prob - _onehot(label, 5)) / 4, rtol=1e-5,
                               atol=1e-6)


def test_regression_output_reference_gradients():
    """Linear: (pred-label)/n; MAE: sign(pred-label)/n; Logistic:
    (sigmoid-label)/n (src/operator/regression_output-inl.h)."""
    data, label = A(4, 3), A(4, 3)
    g, out = _loss_grad("LinearRegressionOutput", [data, label])
    np.testing.assert_allclose(out, data, rtol=1e-6)
    np.testing.assert_allclose(g, (data - label) / 3, rtol=1e-5, atol=1e-6)

    far = A(4, 3) + np.where(A(4, 3) > 0, 2.0, -2.0)  # away from ties
    g, _ = _loss_grad("MAERegressionOutput", [far, label])
    np.testing.assert_allclose(g, np.sign(far - label) / 3, rtol=1e-5)

    lab01 = (A(4, 3) > 0).astype(np.float32)
    g, out = _loss_grad("LogisticRegressionOutput", [data, lab01])
    sig = 1 / (1 + np.exp(-data))
    np.testing.assert_allclose(out, sig, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(g, (sig - lab01) / 3, rtol=1e-5, atol=1e-6)


def test_svm_output_reference_gradient():
    """L2-SVM margin gradients (src/operator/svm_output.cc)."""
    data = A(3, 4) * 0.5
    label = np.array([1., 3., 0.])
    g, out = _loss_grad("SVMOutput", [data, label],
                        {"margin": 1.0, "regularization_coefficient": 1.0})
    np.testing.assert_allclose(out, data, rtol=1e-6)  # identity forward
    oh = _onehot(label, 4)
    expect = (oh * (-2.0 * np.maximum(0, 1.0 - data))
              + (1 - oh) * (2.0 * np.maximum(0, 1.0 + data)))
    np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)


def test_linalg_tail_oracles():
    """Structural/numpy oracles for the linalg tail (reference la_op.cc):
    gelqf reconstruction + orthonormality, syevd eigendecomposition,
    slogdet vs numpy, potri = inv(L L^T) from the Cholesky factor."""
    a = A(3, 5)
    L, Q = (o.asnumpy() for o in invoke("_linalg_gelqf", mx.nd.array(a)))
    np.testing.assert_allclose(L @ Q, a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), rtol=1e-4, atol=1e-5)

    spd = SPD(4)
    Ut, w = (o.asnumpy() for o in invoke("_linalg_syevd", mx.nd.array(spd)))
    np.testing.assert_allclose(Ut.T @ np.diag(w) @ Ut, spd, rtol=1e-3,
                               atol=1e-3)

    sign, logdet = (o.asnumpy() for o in invoke("_linalg_slogdet",
                                                mx.nd.array(spd)))
    es, el = np.linalg.slogdet(spd)
    np.testing.assert_allclose(sign, es, rtol=1e-5)
    np.testing.assert_allclose(logdet, el, rtol=1e-4)

    chol = np.linalg.cholesky(spd).astype(np.float32)
    inv = invoke("_linalg_potri", mx.nd.array(chol)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-2, atol=1e-3)
