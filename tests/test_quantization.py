"""INT8 quantization tests (reference
tests/python/quantization/test_quantization.py): op-level semantics +
the quantize_model graph pass with naive calibration and dynamic ranges."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_quantize_dequantize_int8_roundtrip():
    x = nd.array(np.array([[-2.0, 0.5, 1.0, 3.0]], np.float32))
    q, mn, mx_ = mx.nd.contrib.quantize(
        x, nd.array([-2.0]), nd.array([3.0]), out_type="int8")
    assert q.asnumpy().dtype == np.int8
    # symmetric: range is max(|min|, |max|)
    np.testing.assert_allclose(mn.asnumpy(), -3.0)
    np.testing.assert_allclose(mx_.asnumpy(), 3.0)
    back = mx.nd.contrib.dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(),
                               atol=3 / 127 + 1e-6)


def test_quantize_uint8():
    x = nd.array(np.array([0.0, 0.5, 1.0], np.float32))
    q, mn, mx_ = mx.nd.contrib.quantize(
        x, nd.array([0.0]), nd.array([1.0]), out_type="uint8")
    assert q.asnumpy().dtype == np.uint8
    np.testing.assert_allclose(q.asnumpy(), [0, 128, 255])
    back = mx.nd.contrib.dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=1 / 255)


def test_quantized_fc_matches_float():
    rng = np.random.RandomState(0)
    d = rng.randn(4, 8).astype(np.float32)
    w = (rng.randn(16, 8) * 0.2).astype(np.float32)
    qd, dmin, dmax = mx.nd.contrib.quantize(
        nd.array(d), nd.array([d.min()]), nd.array([d.max()]),
        out_type="int8")
    qw, wmin, wmax = mx.nd.contrib.quantize(
        nd.array(w), nd.array([w.min()]), nd.array([w.max()]),
        out_type="int8")
    acc, amin, amax = mx.nd.contrib.quantized_fully_connected(
        qd, qw, dmin, dmax, wmin, wmax, num_hidden=16, no_bias=True)
    assert acc.asnumpy().dtype == np.int32
    out = mx.nd.contrib.dequantize(acc, amin, amax)
    np.testing.assert_allclose(out.asnumpy(), d @ w.T, rtol=0.1, atol=0.05)


def test_quantized_conv_matches_float():
    rng = np.random.RandomState(1)
    d = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = (rng.randn(4, 3, 3, 3) * 0.2).astype(np.float32)
    qd, dmin, dmax = mx.nd.contrib.quantize(
        nd.array(d), nd.array([d.min()]), nd.array([d.max()]),
        out_type="int8")
    qw, wmin, wmax = mx.nd.contrib.quantize(
        nd.array(w), nd.array([w.min()]), nd.array([w.max()]),
        out_type="int8")
    acc, amin, amax = mx.nd.contrib.quantized_conv(
        qd, qw, dmin, dmax, wmin, wmax, kernel=(3, 3), num_filter=4,
        pad=(1, 1), no_bias=True)
    out = mx.nd.contrib.dequantize(acc, amin, amax).asnumpy()
    ref = mx.nd.Convolution(nd.array(d), nd.array(w), kernel=(3, 3),
                            num_filter=4, pad=(1, 1), no_bias=True).asnumpy()
    assert np.abs(out - ref).max() < 0.25
    assert np.corrcoef(out.ravel(), ref.ravel())[0, 1] > 0.999


def test_requantize_with_calibration():
    acc = nd.array(np.array([1000000, -500000, 0], np.int32))
    mn, mx_ = nd.array([-1.0]), nd.array([1.0])
    q, qmn, qmx = mx.nd.contrib.requantize(
        acc, mn, mx_, min_calib_range=-0.001, max_calib_range=0.001)
    assert q.asnumpy().dtype == np.int8
    np.testing.assert_allclose(qmx.asnumpy(), 0.001, rtol=1e-5)


def _mlp_and_params(rng):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(data=act, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    arg_shapes, _, _ = net.infer_shape(data=(32, 8))
    args = {n: nd.array(rng.rand(*s).astype(np.float32) - 0.5)
            for n, s in zip(net.list_arguments(), arg_shapes)
            if n not in ("data", "softmax_label")}
    return net, args


@pytest.mark.parametrize("calib_mode", ["none", "naive"])
def test_quantize_model(calib_mode):
    rng = np.random.RandomState(0)
    net, args = _mlp_and_params(rng)
    calib = None
    if calib_mode == "naive":
        calib = mx.io.NDArrayIter(rng.rand(32, 8).astype(np.float32),
                                  np.zeros(32, np.float32), batch_size=16)
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        net, args, {}, calib_mode=calib_mode, calib_data=calib,
        num_calib_examples=32)
    # quantized weights became int8
    assert qargs["fc1_weight"].asnumpy().dtype == np.int8
    xt = rng.rand(8, 8).astype(np.float32)
    outs = []
    for sym, params in ((net, args), (qsym, qargs)):
        ex = sym.simple_bind(mx.cpu(), data=(8, 8), grad_req="null")
        ex.arg_dict["data"][:] = xt
        for n, arr in ex.arg_dict.items():
            if n in params:
                arr._data = params[n]._data
        outs.append(ex.forward(is_train=False)[0].asnumpy())
    assert np.abs(outs[0] - outs[1]).max() < 0.05
    assert (np.argmax(outs[0], 1) == np.argmax(outs[1], 1)).mean() >= 0.75


def test_quantize_model_excluded_and_errors():
    rng = np.random.RandomState(2)
    net, args = _mlp_and_params(rng)
    qsym, qargs, _ = mx.contrib.quantization.quantize_model(
        net, args, {}, excluded_sym_names=["fc1", "fc2"], calib_mode="none")
    # nothing quantized: weights stay float
    assert qargs["fc1_weight"].asnumpy().dtype == np.float32
    with pytest.raises(mx.MXNetError):
        mx.contrib.quantization.quantize_model(net, args, {},
                                               calib_mode="naive")
    with pytest.raises(mx.MXNetError):
        mx.contrib.quantization.quantize_model(net, args, {},
                                               quantized_dtype="uint4")


def test_entropy_calibration_beats_naive_on_outliers():
    """KL threshold search (reference contrib/quantization.py:244-317):
    on a distribution with rare extreme outliers, the entropy threshold
    must clip well inside the absolute max, and quantizing with it must
    reconstruct the bulk of the distribution with lower MSE than the
    naive (min/max) threshold."""
    from mxnet_tpu.contrib.quantization import _get_optimal_threshold

    rs = np.random.RandomState(0)
    bulk = rs.randn(200_000).astype(np.float32)     # ~N(0,1)
    outliers = rs.choice([-60.0, 60.0], 32).astype(np.float32)
    arr = np.concatenate([bulk, outliers])

    mn, mx, opt_mn, opt_mx = _get_optimal_threshold(arr)
    assert abs(mx) >= 59.0                      # naive range sees outliers
    assert opt_mx < 15.0, opt_mx                # KL clips them away
    assert opt_mn == -opt_mx                    # symmetric

    def int8_roundtrip_mse(x, th):
        q = np.clip(np.round(np.clip(x, -th, th) * (127.0 / th)), -127, 127)
        return float(np.mean((q * (th / 127.0) - np.clip(x, -th, th)) ** 2))

    naive_th = max(abs(mn), abs(mx))
    mse_naive = int8_roundtrip_mse(bulk, naive_th)
    mse_kl = int8_roundtrip_mse(bulk, opt_mx)
    assert mse_kl < mse_naive / 10, (mse_kl, mse_naive)


def test_entropy_calibration_no_outliers_close_to_naive():
    """On a clean bounded distribution the KL threshold stays near the
    true range (no over-clipping)."""
    from mxnet_tpu.contrib.quantization import _get_optimal_threshold

    rs = np.random.RandomState(1)
    arr = rs.uniform(-2.0, 2.0, 100_000).astype(np.float32)
    _, _, _, opt_mx = _get_optimal_threshold(arr)
    assert 1.6 < opt_mx <= 2.01, opt_mx
