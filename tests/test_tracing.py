"""telemetry v2 — request tracing, flight recorder, SLO engine, httpd.

Covers the ISSUE-15 acceptance surface: per-request traces that
reconstruct the full queue→admission→prefill→ticks→terminal chain under
a chaos-seeded decode soak (faults + eviction + deadline expiry + live
weight swap, shed/deferred requests included), sampling=0 producing zero
events with zero added locking, the MXNET_TELEMETRY=0 zero-lock path
extended end to end, the flight recorder's bounded ring + atomic dump,
the post-mortem acceptance (SIGTERM mid-soak → reconstruct the failing
tick's in-flight set + tenants + the preceding fault from the dump
alone), the live SLO engine's burn/invariant alerts + audit, and the
stdlib introspection endpoint.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.resilience import RetryPolicy, chaos
from mxnet_tpu.telemetry import flightrec, httpd, slo, tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    chaos.disable()
    tracing.set_sample(None)
    tracing.clear()
    flightrec.clear()
    yield
    chaos.disable()
    tracing.set_sample(None)
    tracing.clear()
    flightrec.clear()
    slo.reset()
    telemetry.set_enabled(True)


def _tiny_engine(name, **kw):
    model = serving.TinyDecoder(vocab_size=32, num_layers=1, num_heads=2,
                                head_dim=4)
    params = model.init_params(0)
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("timeout_ms", 0)
    return model, params, serving.DecodeEngine(model, params, name=name,
                                               **kw)


# ---------------------------------------------------------------------------
# tracing unit surface
# ---------------------------------------------------------------------------

def test_sampling_gates_trace_minting():
    assert tracing.start_trace("decode", "s", "t", sample=0.0) is None
    t = tracing.start_trace("decode", "s", "t", sample=1.0)
    assert t is not None and t.plane == "decode"
    # MXNET_TELEMETRY=0 extends to tracing
    telemetry.set_enabled(False)
    assert tracing.start_trace("decode", "s", "t", sample=1.0) is None
    telemetry.set_enabled(True)


def test_trace_chain_and_get_trace():
    t = tracing.start_trace("decode", "s", "gold", sample=1.0)
    tracing.event(t, "enqueue", depth=3)
    tracing.event(t, "admit", slot=1)
    tracing.finish(t, "complete", tokens=4)
    got = telemetry.get_trace(t.trace_id)
    kinds = [e["kind"] for e in got["events"]]
    assert kinds == ["enqueue", "admit", "complete"]
    assert got["events"][-1]["terminal"] is True
    assert got["tenant"] == "gold" and got["done"]
    # monotonic timestamps
    ts = [e["t"] for e in got["events"]]
    assert ts == sorted(ts)
    # terminal is idempotent: a racing second verdict must not append
    tracing.finish(t, "error")
    assert len(telemetry.get_trace(t.trace_id)["events"]) == 3
    assert telemetry.get_trace("not-a-trace") is None


def test_trace_store_capacity_evicts_oldest(monkeypatch):
    monkeypatch.setenv("MXNET_TRACE_CAPACITY", "4")
    ids = [tracing.start_trace("p", "s", "t", sample=1.0).trace_id
           for _ in range(7)]
    alive = tracing.trace_ids()
    assert len(alive) == 4 and alive == ids[-4:]


def test_trace_event_cap_keeps_terminal(monkeypatch):
    monkeypatch.setenv("MXNET_TRACE_MAX_EVENTS", "8")
    t = tracing.start_trace("p", "s", "t", sample=1.0)
    for i in range(20):
        tracing.event(t, "tick", token_index=i)
    tracing.finish(t, "complete")
    got = telemetry.get_trace(t.trace_id)
    assert got["truncated"]
    assert len(got["events"]) == 8
    assert got["events"][-1]["kind"] == "complete"  # terminal survives


def test_export_chrome_renders_hops_as_slices(tmp_path):
    t = tracing.start_trace("decode", "s", "t", sample=1.0)
    tracing.event(t, "enqueue")
    tracing.event(t, "admit")
    tracing.finish(t, "complete")
    path = str(tmp_path / "trace.json")
    doc = tracing.export_chrome(path)
    evs = [e for e in doc["traceEvents"] if e.get("cat") == "trace"]
    # two slices (enqueue->admit, admit->complete) + one terminal instant
    assert [e["ph"] for e in evs] == ["X", "X", "i"]
    assert json.load(open(path))["traceEvents"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flightrec_ring_is_bounded_and_ordered():
    flightrec.configure(capacity=16)
    try:
        for i in range(50):
            flightrec.record("ev", i=i)
        events = flightrec.tail(0)
        assert len(events) == 16
        assert [e["i"] for e in events] == list(range(34, 50))
        assert flightrec.tail(4)[-1]["i"] == 49
    finally:
        flightrec.configure(capacity=4096)


def test_flightrec_dump_commits_readable_json(tmp_path):
    flightrec.record("breaker", site="serving.x", to="open")
    path = str(tmp_path / "box.json")
    assert flightrec.dump("unit-test", path) == path
    assert flightrec.last_dump_path() == path
    doc = json.load(open(path))
    assert doc["reason"] == "unit-test" and doc["pid"] == os.getpid()
    assert any(e["kind"] == "breaker" and e["to"] == "open"
               for e in doc["events"])
    # unserializable fields degrade through repr, never raise
    flightrec.record("weird", obj=object())
    assert flightrec.dump("unit-test-2", path) == path
    json.load(open(path))


def test_flightrec_disabled_is_free():
    telemetry.set_enabled(False)
    flightrec.record("never")
    telemetry.set_enabled(True)
    assert flightrec.tail() == []


# ---------------------------------------------------------------------------
# zero-lock proofs: MXNET_TELEMETRY=0 end to end, and sampling=0
# ---------------------------------------------------------------------------

class _Poison:
    def __enter__(self):
        raise AssertionError("disabled/unsampled path took a lock")

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **kw):
        raise AssertionError("disabled/unsampled path took a lock")

    release = acquire
    append = acquire  # doubles as a poisoned ring


def test_sampling_zero_takes_no_lock_and_records_nothing():
    real = tracing._LOCK
    tracing._LOCK = _Poison()
    try:
        assert tracing.start_trace("decode", "s", "t", sample=0.0) is None
        tracing.event(None, "tick")
        tracing.finish(None, "complete")
    finally:
        tracing._LOCK = real
    assert tracing.trace_ids() == []


def test_telemetry_off_zero_locks_end_to_end():
    """MXNET_TELEMETRY=0 must keep the WHOLE request path lock-free on
    the telemetry side: tracing mint, flight-recorder appends, SLO
    evaluation — while the engine itself still serves correctly."""
    model, params, eng = _tiny_engine("off-e2e")
    eng.warmup()
    telemetry.set_enabled(False)
    real_lock, real_ring = tracing._LOCK, flightrec._RING
    tracing._LOCK = _Poison()
    flightrec._RING = _Poison()
    tracing.set_sample(1.0)  # even at sample 1.0: the master switch wins
    try:
        out = eng.submit([1, 2, 3], 4).result(timeout=60)
        assert out.shape == (4,)
        st = eng.stats()
        assert st["alerts"] == []  # SLO evaluate short-circuits
    finally:
        tracing._LOCK, flightrec._RING = real_lock, real_ring
        tracing.set_sample(None)
        telemetry.set_enabled(True)
        eng.close()
    assert tracing.trace_ids() == []


# ---------------------------------------------------------------------------
# trace propagation: the chaos-seeded decode soak (ISSUE-15 satellite)
# ---------------------------------------------------------------------------

_TERMINALS = {"complete", "evict", "timeout", "shed", "error", "rejected",
              "closed"}


def _chain_of(trace):
    return [e["kind"] for e in trace["events"]]


def test_trace_propagation_chaos_soak():
    """Every submitted request's trace reconstructs a complete
    queue→admission→prefill→ticks→terminal chain under faults +
    eviction + deadline expiry + a live weight swap — shed and deferred
    requests included — and tracing holds steady-state recompiles at 0."""
    tracing.set_sample(1.0)
    model, params, eng = _tiny_engine(
        "soak-trace", num_slots=2, max_seq_len=48,
        retry_policy=RetryPolicy(max_attempts=1),
        breaker_threshold=1000)  # engine breaker must not shed the soak
    eng.warmup()
    futs = []
    # at= schedules: deterministic fault placement regardless of tick
    # interleaving (call counts only ever grow)
    with chaos.active("seed=5,site=serving.decode,at=9:25;"
                      "seed=5,site=serving.decode.prefill,at=4"):
        for i in range(18):
            tenant = ("gold", "bronze", None)[i % 3]
            try:
                futs.append(eng.submit([1 + i % 7, 2, 3], 6,
                                       tenant=tenant))
            except Exception:  # noqa: BLE001 - sheds are part of the soak
                pass
            if i == 8:
                # live weight swap mid-soak (same signature: no drops)
                eng.swap_params(params, variant="mid-soak", wait=True,
                                timeout=60)
            if i == 10:
                # a deadline the queue wait will blow: timeout terminal
                try:
                    futs.append(eng.submit([9, 9, 9], 6, timeout_ms=0.01,
                                           tenant="gold"))
                except Exception:  # noqa: BLE001
                    pass
        for f in futs:
            try:
                f.result(timeout=120)
            except Exception:  # noqa: BLE001 - evictions/timeouts expected
                pass
    stats = eng.stats()
    eng.close()
    # tracing must not perturb the compile-once contract
    assert stats.get("steady_state_recompiles") == 0
    traces = [telemetry.get_trace(tid) for tid in tracing.trace_ids()]
    soak = [t for t in traces if t["server"] == "soak-trace"]
    # every submitted request minted a trace at sample=1.0
    assert len(soak) >= len(futs)
    outcomes = set()
    for t in soak:
        kinds = _chain_of(t)
        assert kinds[0] == "submit", kinds
        assert t["done"], "no terminal on %s" % kinds
        terminal = t["events"][-1]
        assert terminal.get("terminal") and terminal["kind"] in _TERMINALS
        outcomes.add(terminal["kind"])
        ts = [e["t"] for e in t["events"]]
        assert ts == sorted(ts), "non-monotonic timestamps"
        if terminal["kind"] == "complete":
            # the full chain: queue -> admission -> prefill -> ticks
            assert "enqueue" in kinds
            assert "admission_verdict" in kinds and "admit" in kinds
            assert "prefill" in kinds or "prefill_chunk" in kinds
            assert "first_token" in kinds
            assert kinds.index("enqueue") < kinds.index("admit") \
                < kinds.index("first_token")
            # 6 requested tokens -> first_token + 5 ticks (EOS-free vocab)
            assert kinds.count("tick") == terminal["tokens"] - 1
    # the soak genuinely exercised more than the happy path
    assert "complete" in outcomes
    assert outcomes & {"evict", "timeout", "error"}, outcomes
    # the swap left its mark in the black box
    assert any(e["kind"] == "decode.weight_swap"
               for e in flightrec.tail(10000))


def test_deferred_request_trace_records_the_verdict():
    """A tenant at its page budget defers — the trace says so, then
    completes once pages free (the per-hop causality the WFQ counters
    cannot give)."""
    tracing.set_sample(1.0)
    model, params, eng = _tiny_engine("defer-trace", num_slots=2,
                                      max_seq_len=48, page_size=4)
    # each request worst-cases 3 + 8 = 11 tokens -> 3 pages of 4; a
    # 3-page budget admits exactly one at a time: the second DEFERS
    eng.tenants.register("capped", page_budget=3)
    eng.warmup()
    f1 = eng.submit([1, 2, 3], 8, tenant="capped")
    f2 = eng.submit([4, 5, 6], 8, tenant="capped")
    f1.result(timeout=60)
    f2.result(timeout=60)
    eng.close()
    deferred = [telemetry.get_trace(tid) for tid in tracing.trace_ids()]
    deferred = [t for t in deferred
                if t["server"] == "defer-trace"
                and any(e["kind"] == "defer" for e in t["events"])]
    assert deferred, "second request never recorded its deferral"
    t = deferred[-1]
    kinds = _chain_of(t)
    assert kinds.index("defer") < kinds.index("admit")
    reason = next(e for e in t["events"] if e["kind"] == "defer")["reason"]
    assert reason in ("pages_budget", "pages_global")
    assert t["events"][-1]["kind"] == "complete"


# ---------------------------------------------------------------------------
# post-mortem acceptance: SIGTERM mid-soak, reconstruct from the dump alone
# ---------------------------------------------------------------------------

_BLACKBOX_CHILD = r"""
import os, signal, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from mxnet_tpu import serving
from mxnet_tpu.telemetry import flightrec
from mxnet_tpu.resilience import RetryPolicy, chaos

flightrec.install_signal_dump()
chaos.configure("seed=2,site=serving.decode,at=6")  # THE fault before death
model = serving.TinyDecoder(vocab_size=32, num_layers=1, num_heads=2,
                            head_dim=4)
params = model.init_params(0)
eng = serving.DecodeEngine(model, params, num_slots=2, max_seq_len=64,
                           prefill_buckets=(8,), name="blackbox",
                           timeout_ms=0,
                           retry_policy=RetryPolicy(max_attempts=1))
eng.warmup()
futs = [eng.submit([1 + i, 2, 3], 40, tenant=("gold", "bronze")[i % 2])
        for i in range(4)]
deadline = time.time() + 60
while time.time() < deadline:
    if any(e["kind"] == "chaos.fault" for e in flightrec.tail(0)):
        break
    time.sleep(0.01)
else:
    sys.exit(97)  # fault never fired: the test setup is broken
time.sleep(0.05)  # a few more ticks so death lands MID-decode
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(30)
sys.exit(98)  # unreachable when the SIGTERM dump path works
"""


def test_postmortem_blackbox_reconstructs_failing_tick(tmp_path):
    """ISSUE-15 acceptance: kill a chaos-soaked decode engine mid-tick
    (SIGTERM path) and reconstruct, from the committed flight-recorder
    dump ALONE, the failing tick's in-flight request set, their tenants,
    and the fault event that preceded death."""
    box = str(tmp_path / "blackbox.json")
    child = str(tmp_path / "child.py")
    with open(child, "w") as f:
        f.write(_BLACKBOX_CHILD)
    env = dict(os.environ, MXNET_FLIGHTREC_PATH=box,
               JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run([sys.executable, child], env=env, cwd=REPO,
                          timeout=180, capture_output=True)
    # killed by the re-delivered default SIGTERM, after the dump
    assert proc.returncode == -signal.SIGTERM, \
        (proc.returncode, proc.stdout[-500:], proc.stderr[-800:])
    doc = json.load(open(box))
    assert doc["reason"] == "SIGTERM"
    events = doc["events"]
    # the fault that preceded death, by site and order
    fault_idx = [i for i, e in enumerate(events)
                 if e["kind"] == "chaos.fault"
                 and e["site"] == "serving.decode"]
    assert fault_idx, "no chaos fault in the dump"
    # the failing tick: the last in-flight set recorded at or before the
    # fault — reconstructed from the dump alone
    ticks = [i for i, e in enumerate(events)
             if e["kind"] == "decode.tick" and i <= fault_idx[0]]
    assert ticks, "no decode.tick before the fault"
    failing = events[ticks[-1]]
    assert failing["server"] == "blackbox"
    reqs = failing["reqs"]
    assert 1 <= len(reqs) <= 2  # 2 slots
    for rid, tenant, phase in reqs:
        assert isinstance(rid, int) and rid >= 1
        assert tenant in ("gold", "bronze")
        assert phase in ("decode", "prefill")
    # the eviction the fault caused is on the record too
    assert any(e["kind"] == "decode.evict" for e in events[fault_idx[0]:])
    # the SIGTERM itself is the last chapter
    assert any(e["kind"] == "signal" for e in events)


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

def test_slo_queue_burn_fires_fast_and_slow_windows():
    eng = slo.SLOEngine(fast_s=60, slow_s=600)
    eng.note_bound("queue_depth", "sloq", 10)
    g = telemetry.gauge("mxnet_serving_queue_depth",
                        labels=("server",))
    g.set(9.5, server="sloq")
    fired = eng.evaluate()
    mine = [a for a in fired if a["alert"] == "QueueDepthBurn"
            and a["instance"] == "sloq"]
    assert mine and mine[0]["level"] == "page" and mine[0]["burn"] > 1
    assert telemetry.REGISTRY.get("mxnet_slo_burn").value(
        alert="QueueDepthBurn") > 1
    # drops to the slow/warn rung when the mean sits between 0.5 and 0.9
    eng2 = slo.SLOEngine(fast_s=60, slow_s=600)
    eng2.note_bound("queue_depth", "sloq", 10)
    g.set(6.0, server="sloq")
    fired = eng2.evaluate()
    mine = [a for a in fired if a["alert"] == "QueueDepthBurn"
            and a["instance"] == "sloq"]
    assert mine and mine[0]["level"] == "warn"
    g.set(0.0, server="sloq")


def test_slo_invariant_alerts_and_flightrec_edges():
    eng = slo.SLOEngine(fast_s=60, slow_s=600)
    eng.note_bound("tenant_pages", "slos/gold", 8)
    telemetry.gauge("mxnet_tenant_pages_in_use",
                    labels=("server", "tenant")).set(
        11, server="slos", tenant="gold")
    telemetry.gauge("mxnet_steady_state_recompiles",
                    labels=("site",)).set(2, site="serving.slos")
    fired = eng.evaluate()
    names = {a["alert"] for a in fired}
    assert "TenantPagesOverBudget" in names
    assert "RecompileStorm" in names
    # rising edges hit the black box
    kinds = [e for e in flightrec.tail(100) if e["kind"] == "slo.alert"]
    assert {k["alert"] for k in kinds} >= {"TenantPagesOverBudget",
                                           "RecompileStorm"}
    # audit: engine agrees with its raw inputs
    assert eng.audit() == []
    # clear the gauges -> alerts clear, edges recorded
    telemetry.gauge("mxnet_tenant_pages_in_use",
                    labels=("server", "tenant")).set(
        0, server="slos", tenant="gold")
    telemetry.gauge("mxnet_steady_state_recompiles",
                    labels=("site",)).set(0, site="serving.slos")
    assert [a for a in eng.evaluate()
            if a["instance"] in ("slos/gold", "serving.slos")] == []
    assert any(e["kind"] == "slo.clear" for e in flightrec.tail(100))


def test_slo_audit_reports_contradictions():
    eng = slo.SLOEngine(fast_s=60, slow_s=600)
    telemetry.gauge("mxnet_steady_state_recompiles",
                    labels=("site",)).set(3, site="serving.contra")
    # raw gauge says storm, but the engine never evaluated -> audit flags
    out = eng.audit()
    assert out and "RecompileStorm" in out[0]
    telemetry.gauge("mxnet_steady_state_recompiles",
                    labels=("site",)).set(0, site="serving.contra")


def test_decode_stats_carries_alerts():
    model, params, eng = _tiny_engine("stats-alerts")
    eng.warmup()
    st = eng.stats()
    eng.close()
    assert isinstance(st["alerts"], list)


# ---------------------------------------------------------------------------
# introspection endpoint
# ---------------------------------------------------------------------------

def _get(port, path):
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_httpd_serves_metrics_health_state_and_traces():
    t = tracing.start_trace("decode", "httpd-t", "gold", sample=1.0)
    tracing.event(t, "enqueue")
    tracing.finish(t, "complete")
    flightrec.record("breaker", site="serving.h", to="open")
    telemetry.counter("mxnet_httpd_probe_total").inc()
    server = httpd.start_httpd(port=0)
    try:
        port = server.server_address[1]
        code, body = _get(port, "/metrics")
        assert code == 200 and b"mxnet_httpd_probe_total" in body
        code, body = _get(port, "/healthz")
        # 200 ok / 503 degraded: earlier suites may have left an open
        # breaker gauge in the process registry — both are valid answers
        assert code in (200, 503)
        doc = json.loads(body)
        assert doc["status"] in ("ok", "degraded") and "alerts" in doc
        code, body = _get(port, "/debug/state")
        doc = json.loads(body)
        assert "snapshot" in doc
        assert any(e["kind"] == "breaker" for e in doc["flightrec"])
        code, body = _get(port, "/debug/traces")
        assert t.trace_id in json.loads(body)["trace_ids"]
        code, body = _get(port, "/debug/trace/" + t.trace_id)
        assert code == 200
        assert [e["kind"] for e in json.loads(body)["events"]] == \
            ["enqueue", "complete"]
        code, _body = _get(port, "/debug/trace/unknown")
        assert code == 404
    finally:
        httpd.stop_httpd()
    assert httpd.httpd_address() is None
