"""mxnet_tpu.serving — dynamic-batching inference server (tier-1, CPU).

Covers the ISSUE-2 acceptance surface: bucket selection/padding,
compile-once via jit cache-miss counting, concurrent submit, per-request
timeout, shed-on-full-queue, graceful drain, error isolation, StableHLO
backend parity with the live block, and the batched predict-ABI entry.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, serving
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# deterministic engines driving the batcher's policy paths
# ---------------------------------------------------------------------------

class _DoubleEngine(serving.Engine):
    """Pure-numpy engine (result == 2 * request, exactly checkable)."""

    def __init__(self):
        self.batch_sizes = []

    def run(self, batch):
        self.batch_sizes.append(batch.shape[0])
        return batch * 2.0


class _GateEngine(_DoubleEngine):
    """Blocks inside run() until released — freezes the batcher mid-batch
    so queue states (full, stale, closed) can be staged deterministically."""

    def __init__(self, hold_s=0.3):
        super().__init__()
        self.started = threading.Event()
        self.gate = threading.Event()
        self.hold_s = hold_s

    def run(self, batch):
        self.started.set()
        self.gate.wait(self.hold_s)
        return super().run(batch)


class _PoisonEngine(_DoubleEngine):
    """Raises on any batch containing the poison marker in row position 0."""

    POISON = 42.0

    def run(self, batch):
        if np.any(batch[:, 0] == self.POISON):
            raise ValueError("poisoned batch")
        return super().run(batch)


class _MultiOutEngine(serving.Engine):
    def run(self, batch):
        return batch * 2.0, batch + 1.0


def _mlp(in_dim=8, out_dim=4):
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(out_dim))
    net.initialize()
    net(nd.array(np.zeros((1, in_dim), np.float32)))  # materialize params
    return net


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

def test_bucket_ladder_default_and_env(monkeypatch):
    assert serving.bucket_ladder() == (1, 4, 16, 32)
    monkeypatch.setenv("MXNET_SERVING_BUCKETS", "2,8")
    assert serving.bucket_ladder() == (2, 8)  # cache=False: re-read post-import
    assert serving.bucket_ladder([32, 1, 8]) == (1, 8, 32)  # explicit wins


def test_bucket_ladder_rejects_garbage():
    with pytest.raises(MXNetError):
        serving.bucket_ladder([0, 4])
    with pytest.raises(MXNetError):
        serving.bucket_ladder([4, 4])
    with pytest.raises(MXNetError):
        serving.bucket_ladder([])


def test_select_bucket():
    ladder = (1, 4, 16)
    assert [serving.select_bucket(n, ladder) for n in (1, 2, 4, 5, 16)] == \
        [1, 4, 4, 16, 16]
    assert serving.select_bucket(99, ladder) == 16  # overflow -> top rung
    with pytest.raises(MXNetError):
        serving.select_bucket(0, ladder)


def test_pad_to_bucket():
    rows = [np.full((3,), i, np.float32) for i in range(3)]
    out = serving.pad_to_bucket(rows, 4)
    assert out.shape == (4, 3) and out.dtype == np.float32
    np.testing.assert_array_equal(out[:3], np.stack(rows))
    np.testing.assert_array_equal(out[3], np.zeros(3))
    with pytest.raises(MXNetError):
        serving.pad_to_bucket(rows, 2)  # more rows than bucket


# ---------------------------------------------------------------------------
# server correctness
# ---------------------------------------------------------------------------

def test_serve_block_matches_direct_forward():
    net = _mlp()
    rs = np.random.RandomState(0)
    x = rs.randn(6, 8).astype(np.float32)
    expect = net(nd.array(x)).asnumpy()
    with serving.serve_block(net, (8,), buckets=[1, 4, 16],
                             max_delay_ms=5.0) as srv:
        futs = [srv.submit(x[i]) for i in range(6)]
        got = np.stack([f.result(timeout=10) for f in futs])
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_hybrid_block_functional_engine_and_refresh():
    """HybridBlocks serve through the functional path: the param pytree is
    a traced operand (one device copy across rungs) and refresh_params()
    picks up retrained weights without invalidating compiled shapes."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    x = np.random.RandomState(4).randn(3, 8).astype(np.float32)
    net(nd.array(x))
    eng = serving.BlockEngine(net)
    assert eng._functional
    with serving.Server(eng, (8,), buckets=[1, 4], max_delay_ms=5.0) as srv:
        srv.warmup()
        compiled = eng.compile_count
        got = srv.submit(x[0]).result(timeout=10)
        np.testing.assert_allclose(got, net(nd.array(x[:1])).asnumpy()[0],
                                   rtol=1e-5, atol=1e-6)
        # "retrain": perturb a weight, re-snapshot, same compiled shapes
        w = net[1].weight
        w.set_data(w.data() * 2.0)
        eng.refresh_params()
        got2 = srv.submit(x[0]).result(timeout=10)
        np.testing.assert_allclose(got2, net(nd.array(x[:1])).asnumpy()[0],
                                   rtol=1e-5, atol=1e-6)
        assert not np.allclose(got, got2)
        assert eng.compile_count == compiled  # buffers swapped, no re-jit


def test_compile_once_across_traffic():
    """The tentpole guarantee: after warmup, traffic of every size hits a
    warm jit cache entry — the cache-miss count never moves again."""
    srv = serving.serve_block(_mlp(), (8,), buckets=[1, 2, 4],
                              max_delay_ms=2.0)
    assert srv.warmup() == 3  # one executable per rung
    rs = np.random.RandomState(1)
    for wave in (1, 2, 3, 4, 7, 1, 5):
        futs = [srv.submit(rs.randn(8).astype(np.float32))
                for _ in range(wave)]
        for f in futs:
            f.result(timeout=10)
    st = srv.stats()
    srv.close()
    assert st["compile_count"] == 3  # zero steady-state recompiles
    assert st["completed"] == 23
    assert st["p50_ms"] > 0 and st["p99_ms"] >= st["p50_ms"]
    assert 0 < st["batch_fill"] <= 1


def test_concurrent_submit_exact_results():
    eng = _DoubleEngine()
    srv = serving.Server(eng, (4,), buckets=[1, 4, 16], max_delay_ms=1.0,
                         queue_depth=1024)
    n_threads, per = 4, 30
    results = {}

    def client(tid):
        futs = []
        for i in range(per):
            row = np.full((4,), tid * 1000 + i, np.float32)
            futs.append((row, srv.submit(row)))
        results[tid] = [(row, f.result(timeout=10)) for row, f in futs]

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.close()
    assert sorted(results) == list(range(n_threads))
    for tid in results:
        for row, got in results[tid]:
            np.testing.assert_array_equal(got, row * 2.0)
    assert max(eng.batch_sizes) <= 16


def test_submit_validates_shape_before_enqueue():
    with serving.Server(_DoubleEngine(), (4,), buckets=[1]) as srv:
        with pytest.raises(MXNetError):
            srv.submit(np.zeros((5,), np.float32))
        st = srv.stats()
        assert st["submitted"] == 0  # rejected on the caller's thread


def test_multi_output_delivery():
    with serving.Server(_MultiOutEngine(), (3,), buckets=[4],
                        max_delay_ms=1.0) as srv:
        row = np.arange(3, dtype=np.float32)
        out = srv.submit(row).result(timeout=10)
    assert isinstance(out, tuple) and len(out) == 2
    np.testing.assert_array_equal(out[0], row * 2.0)
    np.testing.assert_array_equal(out[1], row + 1.0)


# ---------------------------------------------------------------------------
# robustness policy
# ---------------------------------------------------------------------------

def test_timeout_of_stale_queued_request():
    eng = _GateEngine(hold_s=0.5)
    srv = serving.Server(eng, (2,), buckets=[1], max_delay_ms=0.0,
                         timeout_ms=0)
    f1 = srv.submit(np.zeros(2, np.float32))          # no deadline
    assert eng.started.wait(5)                        # batcher inside run()
    f2 = srv.submit(np.ones(2, np.float32), timeout_ms=50)
    time.sleep(0.1)                                   # f2 goes stale queued
    eng.gate.set()
    np.testing.assert_array_equal(f1.result(timeout=10), np.zeros(2))
    with pytest.raises(serving.RequestTimeoutError):
        f2.result(timeout=10)
    st = srv.stats()
    srv.close()
    assert st["timeouts"] == 1 and st["completed"] == 1


def test_shed_on_full_queue():
    eng = _GateEngine(hold_s=1.0)
    srv = serving.Server(eng, (2,), buckets=[1], max_delay_ms=0.0,
                         queue_depth=2, timeout_ms=0)
    first = srv.submit(np.zeros(2, np.float32))
    assert eng.started.wait(5)  # in-flight; queue now empty
    q1 = srv.submit(np.ones(2, np.float32))
    q2 = srv.submit(np.ones(2, np.float32))
    with pytest.raises(serving.QueueFullError):
        srv.submit(np.ones(2, np.float32))  # depth 2 exceeded -> shed
    eng.gate.set()
    for f in (first, q1, q2):
        f.result(timeout=10)  # shed didn't hurt accepted requests
    st = srv.stats()
    srv.close()
    assert st["shed"] == 1 and st["completed"] == 3


def test_graceful_drain_on_close():
    eng = _DoubleEngine()
    srv = serving.Server(eng, (2,), buckets=[1, 4], max_delay_ms=20.0,
                         queue_depth=256, timeout_ms=0)
    futs = [srv.submit(np.full(2, i, np.float32)) for i in range(25)]
    srv.close()  # drain=True: everything queued still gets served
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=0.001),
                                      np.full(2, 2 * i))
    assert srv.stats()["completed"] == 25


def test_close_without_drain_fails_queued():
    eng = _GateEngine(hold_s=0.3)
    srv = serving.Server(eng, (2,), buckets=[1], max_delay_ms=0.0,
                         timeout_ms=0)
    f1 = srv.submit(np.zeros(2, np.float32))
    assert eng.started.wait(5)
    f2 = srv.submit(np.ones(2, np.float32))
    srv.close(drain=False)
    np.testing.assert_array_equal(f1.result(timeout=10), np.zeros(2))
    with pytest.raises(serving.ServerClosedError):
        f2.result(timeout=10)
    with pytest.raises(serving.ServerClosedError):
        srv.submit(np.zeros(2, np.float32))  # intake is closed


def test_error_isolation_poisoned_request():
    eng = _PoisonEngine()
    srv = serving.Server(eng, (4,), buckets=[4], max_delay_ms=100.0,
                         timeout_ms=0)
    rows = [np.full((4,), i + 1, np.float32) for i in range(3)]
    rows.append(np.full((4,), _PoisonEngine.POISON, np.float32))
    futs = [srv.submit(r) for r in rows]
    for r, f in zip(rows[:3], futs[:3]):
        np.testing.assert_array_equal(f.result(timeout=10), r * 2.0)
    with pytest.raises(ValueError):  # only the poisoned future fails
        futs[3].result(timeout=10)
    st = srv.stats()
    srv.close()
    assert st["isolation_retries"] >= 1
    assert st["errors"] == 1 and st["completed"] == 3


def test_batcher_survives_malformed_engine_output():
    class _BadOnceEngine(serving.Engine):
        def __init__(self):
            self.calls = 0

        def run(self, batch):
            self.calls += 1
            if self.calls == 1:
                return batch[:1] * 2.0  # malformed: fewer rows than bucket
            return batch * 2.0

    srv = serving.Server(_BadOnceEngine(), (2,), buckets=[2],
                         max_delay_ms=50.0, timeout_ms=0)
    f1 = srv.submit(np.zeros(2, np.float32))
    f2 = srv.submit(np.ones(2, np.float32))
    # the malformed delivery must fail (at least) the short row's future,
    # not kill the batcher thread
    with pytest.raises(Exception):
        f1.result(timeout=10), f2.result(timeout=10)
    # ...and the server still serves afterwards
    f3 = srv.submit(np.full(2, 3.0, np.float32))
    f4 = srv.submit(np.full(2, 4.0, np.float32))
    np.testing.assert_array_equal(f3.result(timeout=10), np.full(2, 6.0))
    np.testing.assert_array_equal(f4.result(timeout=10), np.full(2, 8.0))
    srv.close()


def test_env_knob_defaults(monkeypatch):
    monkeypatch.setenv("MXNET_SERVING_QUEUE_DEPTH", "3")
    monkeypatch.setenv("MXNET_SERVING_BUCKETS", "1,2")
    srv = serving.Server(_DoubleEngine(), (2,))
    try:
        assert srv._queue_depth == 3  # cache=False knobs: read at ctor time
        assert srv._ladder == (1, 2)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# StableHLO backend parity
# ---------------------------------------------------------------------------

def test_stablehlo_backend_parity_with_block(tmp_path):
    from mxnet_tpu import aot

    net = _mlp()
    out_dir = str(tmp_path / "aot")
    manifest = aot.export_model(net, (1, 8), out_dir, save_tf=False,
                                poly_batch=True)
    assert manifest["poly_batch"] is True
    rs = np.random.RandomState(2)
    x = rs.randn(5, 8).astype(np.float32)
    expect = net(nd.array(x)).asnumpy()
    with serving.serve_stablehlo(out_dir, buckets=[1, 4],
                                 max_delay_ms=5.0) as srv:
        srv.warmup()
        futs = [srv.submit(x[i]) for i in range(5)]
        got = np.stack([f.result(timeout=10) for f in futs])
        st = srv.stats()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    assert st["compile_count"] == 2  # one per bucket, poly artifact


def test_fixed_shape_artifact_defaults_to_its_own_bucket(tmp_path):
    from mxnet_tpu import aot

    net = _mlp()
    out_dir = str(tmp_path / "aot_fixed")
    aot.export_model(net, (2, 8), out_dir, save_tf=False)  # fixed batch 2
    rs = np.random.RandomState(3)
    x = rs.randn(2, 8).astype(np.float32)
    expect = net(nd.array(x)).asnumpy()
    with serving.serve_stablehlo(out_dir, max_delay_ms=20.0) as srv:
        assert srv._ladder == (2,)  # ladder collapsed to the exported size
        futs = [srv.submit(x[i]) for i in range(2)]
        got = np.stack([f.result(timeout=10) for f in futs])
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_poly_batch_rejects_save_tf(tmp_path):
    from mxnet_tpu import aot

    with pytest.raises(ValueError):
        aot.export_model(_mlp(), (1, 8), str(tmp_path), save_tf=True,
                         poly_batch=True)


# ---------------------------------------------------------------------------
# batched predict-ABI entry point
# ---------------------------------------------------------------------------

def test_predict_embed_forward_batch(tmp_path):
    from mxnet_tpu import _predict_embed as pe
    from mxnet_tpu import model

    data = mx.symbol.var("data")
    hid = mx.symbol.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.symbol.Activation(hid, act_type="relu", name="relu1")
    sym = mx.symbol.FullyConnected(act, num_hidden=3, name="fc2")
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = sym.infer_shape(data=(1, 5))
    args = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.3)
            for n, s in zip(sym.list_arguments(), arg_shapes) if n != "data"}
    prefix = str(tmp_path / "mlp")
    model.save_checkpoint(prefix, 0, sym, args, {})
    with open(prefix + "-0000.params", "rb") as f:
        param_bytes = f.read()

    hdl = pe.create(sym.tojson(), param_bytes, 1, ["data"], [[1, 5]])
    try:
        xs = (rs.randn(6, 5).astype(np.float32) * 0.1)
        # sequential reference through the one-at-a-time ABI
        seq = []
        for i in range(6):
            pe.set_input(hdl, "data", xs[i:i + 1].tobytes())
            pe.forward(hdl)
            seq.append(np.frombuffer(pe.get_output(hdl, 0), np.float32))
        # batched entry: one padded bucketed execution behind the scenes
        got = pe.forward_batch(hdl, [xs[i].tobytes() for i in range(6)])
        for g, s in zip(got, seq):
            np.testing.assert_allclose(np.frombuffer(g, np.float32), s,
                                       rtol=1e-5, atol=1e-6)
    finally:
        pe.free(hdl)  # also closes the per-handle server


def test_predict_embed_forward_batch_larger_than_queue(tmp_path, monkeypatch):
    """forward_batch owns its whole batch: N beyond the queue depth must
    apply backpressure, not shed its own requests."""
    from mxnet_tpu import _predict_embed as pe
    from mxnet_tpu import model

    monkeypatch.setenv("MXNET_SERVING_QUEUE_DEPTH", "8")
    data = mx.symbol.var("data")
    sym = mx.symbol.FullyConnected(data, num_hidden=2, name="fc")
    rs = np.random.RandomState(1)
    arg_shapes, _, _ = sym.infer_shape(data=(1, 3))
    args = {n: mx.nd.array(rs.randn(*s).astype(np.float32))
            for n, s in zip(sym.list_arguments(), arg_shapes) if n != "data"}
    prefix = str(tmp_path / "m")
    model.save_checkpoint(prefix, 0, sym, args, {})
    with open(prefix + "-0000.params", "rb") as f:
        pb = f.read()
    hdl = pe.create(sym.tojson(), pb, 1, ["data"], [[1, 3]])
    try:
        xs = rs.randn(40, 3).astype(np.float32)  # 5x the queue depth
        outs = pe.forward_batch(hdl, [x.tobytes() for x in xs])
        assert len(outs) == 40
        pe.set_input(hdl, "data", xs[:1].tobytes())
        pe.forward(hdl)
        ref0 = np.frombuffer(pe.get_output(hdl, 0), np.float32)
        np.testing.assert_allclose(np.frombuffer(outs[0], np.float32), ref0,
                                   rtol=1e-5, atol=1e-6)
    finally:
        pe.free(hdl)
    # freed handles refuse to rebuild a server
    with pytest.raises(KeyError):
        pe.forward_batch(hdl, [xs[0].tobytes()])
