"""Tests for the native C++ runtime (src/*.cc) and its Python surface.

Mirrors the reference's native-layer test strategy (SURVEY §4.1):
tests/cpp/engine/threaded_engine_test.cc (dependency correctness under a
random DAG), tests/cpp/storage/storage_test.cc (allocator reuse),
tests/python/unittest/test_exc_handling.py (async exception propagation at
WaitForVar) and the recordio roundtrip tests — here driven from Python
through the ctypes ABI.
"""
import os
import struct
import subprocess
import sys
import threading
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu import _native, engine, recordio
from mxnet_tpu.base import MXNetError

pytestmark = pytest.mark.skipif(not _native.native_available(),
                                reason="native library unavailable")


# ---------------------------------------------------------------------------
# storage manager
# ---------------------------------------------------------------------------

def _stats():
    import ctypes

    lib = _native.get_lib()
    vals = [ctypes.c_uint64() for _ in range(5)]
    _native.check_call(lib.MXTPUStorageStats(*[ctypes.byref(v) for v in vals]))
    in_use, pooled, peak, num_alloc, num_hit = [v.value for v in vals]
    return dict(in_use=in_use, pooled=pooled, peak=peak,
                num_alloc=num_alloc, num_hit=num_hit)


def test_storage_pool_reuse():
    import ctypes

    lib = _native.get_lib()
    before = _stats()
    p = ctypes.c_void_p()
    _native.check_call(lib.MXTPUStorageAlloc(5000, ctypes.byref(p)))
    _native.check_call(lib.MXTPUStorageFree(p))
    q = ctypes.c_void_p()
    # same bucket (8192): must come from the pool
    _native.check_call(lib.MXTPUStorageAlloc(4100, ctypes.byref(q)))
    after = _stats()
    assert after["num_hit"] == before["num_hit"] + 1
    assert q.value == p.value
    _native.check_call(lib.MXTPUStorageFree(q))
    _native.check_call(lib.MXTPUStorageReleaseAll())
    assert _stats()["pooled"] == 0


def test_storage_unknown_pointer_errors():
    import ctypes

    lib = _native.get_lib()
    rc = lib.MXTPUStorageFree(ctypes.c_void_p(0xDEAD0))
    assert rc == -1
    with pytest.raises(MXNetError):
        _native.check_call(rc)


# ---------------------------------------------------------------------------
# dependency engine (python surface: mxnet_tpu.engine)
# ---------------------------------------------------------------------------

def test_engine_write_serialization():
    var = engine.new_var()
    order = []

    def make(i, delay):
        def fn():
            time.sleep(delay)
            order.append(i)
        return fn

    for i in range(6):
        engine.push(make(i, 0.02 if i == 0 else 0), mutable_vars=[var])
    engine.wait_for_var(var)
    assert order == list(range(6))
    engine.delete_var(var)


def test_engine_concurrent_reads():
    var = engine.new_var()
    t0 = time.time()
    for _ in range(2):
        engine.push(lambda: time.sleep(0.25), const_vars=[var])
    engine.wait_for_all()
    assert time.time() - t0 < 0.45  # the two readers overlapped
    engine.delete_var(var)


def test_engine_read_write_ordering():
    """Writer → readers → writer FIFO: readers see the first write, the
    second write waits for the readers."""
    var = engine.new_var()
    log = []
    engine.push(lambda: (time.sleep(0.05), log.append("w1")), mutable_vars=[var])
    for i in range(3):
        engine.push(lambda i=i: log.append("r"), const_vars=[var])
    engine.push(lambda: log.append("w2"), mutable_vars=[var])
    engine.wait_for_var(var)
    assert log[0] == "w1" and log[-1] == "w2" and log.count("r") == 3
    engine.delete_var(var)


def test_engine_async_exception_propagation():
    var = engine.new_var()

    def boom():
        raise ValueError("async boom")

    engine.push(boom, mutable_vars=[var])
    with pytest.raises(ValueError, match="async boom"):
        engine.wait_for_var(var)
    # rethrow-once: the next wait succeeds (reference WaitForVar contract)
    engine.wait_for_var(var)
    engine.delete_var(var)


def test_engine_duplicate_mutable_var_no_deadlock():
    """A var listed twice in mutable_vars must not deadlock (dedup in Push)."""
    var = engine.new_var()
    hits = []
    engine.push(lambda: hits.append(1), mutable_vars=[var, var])
    engine.wait_for_var(var)
    assert hits == [1]
    engine.delete_var(var)


def test_recordio_empty_first_record(tmp_path):
    """An empty record at the start of a file must not read as EOF."""
    path = str(tmp_path / "empty_first.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"")
    w.write(b"after")
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b""
    assert r.read() == b"after"
    assert r.read() is None
    r.close()


def test_engine_const_and_mutable_overlap_rejected():
    var = engine.new_var()
    with pytest.raises(MXNetError, match="both const and mutable"):
        engine.push(lambda: None, const_vars=[var], mutable_vars=[var])
    engine.delete_var(var)


def test_engine_random_dag_stress():
    """Random DAG over a handful of vars; verify writer-exclusive,
    FIFO-per-var semantics via a per-var token counter (the pattern of
    reference tests/cpp/engine/threaded_engine_test.cc)."""
    import random

    rng = random.Random(42)
    nvars = 6
    variables = [engine.new_var() for _ in range(nvars)]
    counters = [0] * nvars
    expected = [0] * nvars
    lock = threading.Lock()

    def writer(vi):
        def fn():
            # not atomic on purpose: engine must serialize writers per var
            cur = counters[vi]
            time.sleep(0.0005)
            counters[vi] = cur + 1
        return fn

    for _ in range(120):
        vi = rng.randrange(nvars)
        if rng.random() < 0.6:
            expected[vi] += 1
            cv = [variables[j] for j in range(nvars) if j != vi and rng.random() < 0.3]
            engine.push(writer(vi), const_vars=cv, mutable_vars=[variables[vi]])
        else:
            engine.push(lambda: None, const_vars=[variables[vi]])
    engine.wait_for_all()
    assert counters == expected
    for v in variables:
        engine.delete_var(v)


def test_engine_naive_subprocess():
    """MXNET_ENGINE_TYPE=NaiveEngine runs synchronously on the caller thread."""
    code = """
import os, threading
from mxnet_tpu import engine
assert engine.is_naive_mode()
main = threading.get_ident()
seen = []
var = engine.new_var()
engine.push(lambda: seen.append(threading.get_ident()), mutable_vars=[var])
assert seen == [main], seen
import ctypes
from mxnet_tpu import _native
lib = _native.get_lib()
out = ctypes.c_int()
lib.MXTPUEngineIsNaive(ctypes.byref(out))
assert out.value == 1
print("NAIVE_OK")
"""
    env = dict(os.environ, MXNET_ENGINE_TYPE="NaiveEngine", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120, env=env)
    assert "NAIVE_OK" in out.stdout, out.stderr


def test_naive_mode_eager_sync():
    """set_naive_mode(True) makes every eager op block (debug semantics)."""
    prev = engine.set_naive_mode(True)
    try:
        a = mx.nd.ones((4, 4))
        b = mx.nd.dot(a, a)
        assert b.asnumpy().sum() == 64
    finally:
        engine.set_naive_mode(prev)


def test_bulk_context():
    prev = engine.set_bulk_size(0)
    with engine.bulk(16):
        assert engine.set_bulk_size(16) == 16
        a = mx.nd.ones((2, 2)) + 1
    assert engine.set_bulk_size(prev) == 0
    assert a.asnumpy().sum() == 8


# ---------------------------------------------------------------------------
# recordio: native vs pure-python cross-compatibility
# ---------------------------------------------------------------------------

MAGIC_BYTES = struct.pack("<I", 0xCED7230A)


def _payloads():
    return [b"hello", b"x" * 1031, b"A" * 7 + MAGIC_BYTES + b"B" * 9,
            MAGIC_BYTES + MAGIC_BYTES, b""]


def test_recordio_roundtrip_native(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    assert w._nat is not None  # native path active
    for p in _payloads():
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = [r.read() for _ in _payloads()]
    assert got == _payloads()
    assert r.read() is None
    r.close()


def test_recordio_indexed_native(tmp_path):
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i, p in enumerate(_payloads()):
        w.write_idx(i, p)
    w.close()
    assert os.path.isfile(idx)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    # random access out of order
    assert r.read_idx(2) == _payloads()[2]
    assert r.read_idx(0) == _payloads()[0]
    assert r.read_idx(4) == _payloads()[4]
    r.close()


def test_recordio_python_reads_native_file(tmp_path):
    """A file written by the native writer must parse with the pure-Python
    reader (and vice versa) — byte-level format compatibility."""
    path = str(tmp_path / "cross.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in _payloads():
        w.write(p)
    w.close()
    code = f"""
import os
os.environ["MXNET_USE_NATIVE"] = "0"
from mxnet_tpu import recordio, _native
assert not _native.native_available()
r = recordio.MXRecordIO({path!r}, "r")
import struct
MAGIC = struct.pack("<I", 0xCED7230A)
expected = [b"hello", b"x" * 1031, b"A" * 7 + MAGIC + b"B" * 9, MAGIC + MAGIC, b""]
got = [r.read() for _ in expected]
assert got == expected, got
assert r.read() is None
# now write with pure python for the reverse direction
w = recordio.MXRecordIO({path!r} + ".py", "w")
for p in expected:
    w.write(p)
w.close()
print("PY_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120, env=env)
    assert "PY_OK" in out.stdout, out.stderr
    r = recordio.MXRecordIO(path + ".py", "r")
    got = [r.read() for _ in _payloads()]
    assert got == _payloads()
    r.close()


def test_recordio_pack_unpack_through_native(tmp_path):
    path = str(tmp_path / "p.rec")
    w = recordio.MXRecordIO(path, "w")
    header = recordio.IRHeader(0, 7.0, 123, 0)
    w.write(recordio.pack(header, b"payload"))
    w.close()
    r = recordio.MXRecordIO(path, "r")
    h, s = recordio.unpack(r.read())
    assert h.label == 7.0 and h.id == 123 and s == b"payload"
    r.close()


def test_waitall_drains_host_engine():
    var = engine.new_var()
    done = []
    engine.push(lambda: (time.sleep(0.05), done.append(1)), mutable_vars=[var])
    mx.nd.waitall()
    assert done == [1]
    engine.delete_var(var)


def test_cpp_unit_suite(tmp_path):
    """Build and run the pure-C++ test binary against the ABI (the
    reference's tests/cpp layer)."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    from pathlib import Path

    from mxnet_tpu import _native

    _native.build_lib()
    repo = Path(__file__).resolve().parent.parent
    binary = str(tmp_path / "native_runtime_test")
    build = subprocess.run(
        ["g++", "-std=c++17", "-O2", str(repo / "tests" / "cpp" /
                                        "native_runtime_test.cc"),
         "-I", str(repo / "src"), "-L", str(repo / "src" / "build"),
         "-lmxtpu", "-Wl,-rpath," + str(repo / "src" / "build"),
         "-o", binary], capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run([binary], capture_output=True, text=True, timeout=180)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "ALL C++ TESTS PASSED" in run.stdout


def test_recordio_oversize_record_rejected(tmp_path):
    """dmlc-core hard-checks record size < 1<<29; both writers must raise
    rather than mask the length into a corrupt frame (ADVICE r4)."""
    from mxnet_tpu import recordio
    from mxnet_tpu.base import MXNetError

    big = bytes(recordio._LREC_MASK + 1)  # 512 MiB of zeros (memset, fast)
    # native-backed writer
    w = recordio.MXRecordIO(str(tmp_path / "big.rec"), "w")
    try:
        with pytest.raises(MXNetError, match="too large"):
            w.write(big)
        w.write(b"after")  # writer still usable after the rejection
    finally:
        w.close()
    r = recordio.MXRecordIO(str(tmp_path / "big.rec"), "r")
    try:
        assert r.read() == b"after"
    finally:
        r.close()
