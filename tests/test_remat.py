"""Rematerialization (activation recompute) tests.

The reference's MXNET_BACKWARD_DO_MIRROR (graph_executor.cc:259) trades
recompute FLOPs for activation memory; here the policy is jax.checkpoint
over the whole graph function. Gradients must be bit-comparable with and
without remat, for both the symbolic executor and the fused TrainStep.
"""
import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import base as mx_base


def _mlp_sym():
    data = mx.sym.var("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=16,
                                                name="rfc1"),
                          act_type="tanh")
    return mx.sym.FullyConnected(h, num_hidden=4, name="rfc2")


def _grads(sym, binds, mirror):
    prev = mx_base._ENV_CACHE.get("MXNET_BACKWARD_DO_MIRROR")
    mx_base._ENV_CACHE["MXNET_BACKWARD_DO_MIRROR"] = 1 if mirror else 0
    try:
        ex = sym.simple_bind(mx.cpu(), grad_req="write",
                             **{k: v.shape for k, v in binds.items()})
        ex.copy_params_from({k: mx.nd.array(v) for k, v in binds.items()})
        ex.forward(is_train=True)
        ex.backward(out_grads=mx.nd.ones((4, 4)))
        return {k: g.asnumpy() for k, g in ex.grad_dict.items()}
    finally:
        if prev is None:
            mx_base._ENV_CACHE.pop("MXNET_BACKWARD_DO_MIRROR", None)
        else:
            mx_base._ENV_CACHE["MXNET_BACKWARD_DO_MIRROR"] = prev


def test_executor_mirror_gradients_match():
    rs = np.random.RandomState(0)
    sym = _mlp_sym()
    arg_shapes, _, _ = sym.infer_shape(data=(4, 8))
    binds = {n: rs.randn(*s).astype(np.float32) * 0.3
             for n, s in zip(sym.list_arguments(), arg_shapes)}
    g_plain = _grads(sym, binds, mirror=False)
    g_remat = _grads(sym, binds, mirror=True)
    assert set(g_plain) == set(g_remat)
    for k in g_plain:
        np.testing.assert_allclose(g_plain[k], g_remat[k], rtol=1e-6,
                                   atol=1e-7, err_msg=k)


def test_trainstep_remat_parity():
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    def build():
        mx.random.seed(11)
        net = nn.HybridSequential(prefix="remat_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
        net.initialize()
        net(mx.nd.ones((2, 6)))
        return net

    rs = np.random.RandomState(1)
    x = mx.nd.array(rs.randn(8, 6).astype(np.float32))
    y = mx.nd.array(rs.randint(0, 3, (8,)))
    mesh = parallel.device_mesh(1, devices=[jax.devices()[0]])
    results = {}
    for remat in (False, True):
        net = build()
        step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  "sgd", mesh,
                                  optimizer_params={"learning_rate": 0.1},
                                  remat=remat)
        for _ in range(2):
            loss = step(x, y)
        results[remat] = ({k: np.asarray(v) for k, v in step.params.items()},
                          float(loss.asnumpy()))
    p0, l0 = results[False]
    p1, l1 = results[True]
    assert abs(l0 - l1) < 1e-6
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_remat_present_in_jaxpr():
    """The checkpointed path really does emit a remat region."""
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn

    mx.random.seed(3)
    net = nn.HybridSequential(prefix="rjx_")
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.ones((2, 3)))
    mesh = parallel.device_mesh(1, devices=[jax.devices()[0]])
    step = parallel.TrainStep(net, gluon.loss.L2Loss(), "sgd", mesh,
                              remat=True)
    # trigger trace; the compiled step's jaxpr carries a remat/checkpoint eqn
    step(mx.nd.ones((2, 3)), mx.nd.ones((2, 4)))
    assert step._step_jits, "step cache empty"
