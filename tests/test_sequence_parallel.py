"""Ring attention / Ulysses sequence parallelism tests.

Run on the 8-virtual-device CPU mesh: both algorithms must match a
single-device softmax-attention oracle exactly (fp tolerance), causal and
full, and be differentiable through the collectives.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sequence_parallel as sp

NDEV = 8


def _needs_mesh():
    if len(jax.devices()) < NDEV:
        pytest.skip("needs the %d-device CPU mesh" % NDEV)


def _qkv(b=2, h=4, s=32, d=8, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: rs.randn(b, h, s, d).astype(np.float32) * 0.5
    return mk(), mk(), mk()


def _oracle(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = scores.shape[-1]
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    scores = scores - scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(causal):
    _needs_mesh()
    q, k, v = _qkv()
    mesh = sp.sequence_mesh(NDEV)
    out = sp.ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(causal):
    _needs_mesh()
    q, k, v = _qkv(h=8)
    mesh = sp.sequence_mesh(NDEV)
    out = sp.ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_ring_equals_ulysses():
    _needs_mesh()
    q, k, v = _qkv(h=8, s=64, seed=3)
    mesh = sp.sequence_mesh(NDEV)
    a = sp.ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh=mesh, causal=True)
    b = sp.ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_differentiable():
    _needs_mesh()
    q, k, v = _qkv(s=16, seed=5)
    mesh = sp.sequence_mesh(NDEV)

    def loss(q_, k_, v_):
        return jnp.sum(sp.ring_attention(q_, k_, v_, mesh=mesh, causal=True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def loss_ref(q_, k_, v_):
        scale = 1.0 / jnp.sqrt(jnp.asarray(float(q.shape[-1])))
        scores = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
        s = scores.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v_) ** 2)

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=5e-4,
                                   atol=5e-5)


def test_ring_attention_ndarray_interface():
    _needs_mesh()
    q, k, v = _qkv(s=16, seed=7)
    out = sp.ring_attention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
                            mesh=sp.sequence_mesh(NDEV))
    assert isinstance(out, mx.nd.NDArray)
    np.testing.assert_allclose(out.asnumpy(), _oracle(q, k, v, False),
                               rtol=2e-4, atol=2e-5)


def test_uneven_shapes_rejected():
    _needs_mesh()
    q, k, v = _qkv(s=30)
    with pytest.raises(mx.MXNetError, match="not divisible"):
        sp.ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh=sp.sequence_mesh(NDEV))


def test_flash_attention_kernel_matches_oracle():
    """Pallas flash attention (online softmax, no (S,S) HBM tensor) ==
    dense-softmax oracle, both maskings, non-block-aligned lengths."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    rs = np.random.RandomState(5)
    for (b, h, s, d), causal in [((2, 2, 64, 16), False),
                                 ((1, 2, 100, 32), True),
                                 ((1, 1, 9, 8), True)]:
        q, k, v = (jnp.asarray(rs.randn(b, h, s, d).astype(np.float32) * 0.5)
                   for _ in range(3))
        got = pk.flash_attention(q, k, v, causal=causal)
        scale = 1.0 / np.sqrt(d)
        scores = np.einsum("bhqd,bhkd->bhqk", np.asarray(q),
                           np.asarray(k)) * scale
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            scores = np.where(mask[None, None], scores, -1e30)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expect = np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))
        np.testing.assert_allclose(np.asarray(got), expect, rtol=2e-4,
                                   atol=2e-5)


def test_flash_attention_gradients_match_reference():
    """custom-vjp backward (recompute) == autodiff of the dense oracle."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    rs = np.random.RandomState(6)
    q, k, v = (jnp.asarray(rs.randn(1, 2, 32, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    gf = jax.grad(lambda a, b, c:
                  (pk.flash_attention(a, b, c, causal=True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c:
                  (pk._attention_reference(a, b, c, 0.25, True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_registered_op():
    """The registry surface: _contrib_flash_attention through invoke, and
    autograd tapes through the custom-vjp."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.ndarray.ndarray import invoke

    rs = np.random.RandomState(7)
    q = nd.array(rs.randn(1, 2, 16, 8).astype(np.float32) * 0.5)
    k = nd.array(rs.randn(1, 2, 16, 8).astype(np.float32) * 0.5)
    v = nd.array(rs.randn(1, 2, 16, 8).astype(np.float32) * 0.5)
    out = invoke("_contrib_flash_attention", q, k, v, causal=True)
    assert out.shape == (1, 2, 16, 8)
    q.attach_grad()
    with autograd.record():
        y = invoke("_contrib_flash_attention", q, k, v, causal=True)
        loss = (y * y).sum()
    loss.backward()
    assert np.abs(q.grad.asnumpy()).sum() > 0
