"""KVStore tests (reference tests/python/unittest/test_kvstore.py +
tests/nightly/dist_sync_kvstore.py exact-value discipline, run here on the
conftest 8-virtual-device CPU mesh so the 'tpu' store reduces over DISTINCT
devices)."""
import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

N = min(8, len(jax.devices()))
DEVICES = jax.devices()[:N]

SHAPE = (4, 5)


def _per_device_copies(vals):
    """One NDArray per device holding vals[i]."""
    return [mx.nd.NDArray(jax.device_put(np.asarray(v, np.float32), d),
                          mx.cpu())
            for v, d in zip(vals, DEVICES)]


def test_kv_alias():
    # reference python/mxnet/__init__.py:56
    assert mx.kv is mx.kvstore


@pytest.mark.parametrize("kv_type", ["local", "device", "tpu"])
def test_single_kv_pair(kv_type):
    """Push without an updater REPLACES the stored value with the reduced
    result (reference kvstore_local.h PushImpl: ``local = merged``)."""
    kv = mx.kv.create(kv_type)
    kv.init(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    kv.push(3, nd.ones(SHAPE) * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 4.0)


@pytest.mark.parametrize("kv_type", ["local", "device", "tpu"])
def test_list_kv_pairs(kv_type):
    kv = mx.kv.create(kv_type)
    keys = [5, 7, 9]
    kv.init(keys, [nd.ones(SHAPE)] * len(keys))
    kv.push(keys, [nd.ones(SHAPE) * 2] * len(keys))
    outs = [nd.zeros(SHAPE) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), 2.0)


def test_push_per_device_copies_aggregates():
    """Push with one gradient copy per distinct device sums them all —
    the reference comm.h Reduce contract, here one fused XLA allreduce."""
    kv = mx.kv.create("tpu")
    kv.init("w", nd.zeros(SHAPE))
    grads = _per_device_copies(
        [np.full(SHAPE, i + 1.0) for i in range(N)])
    kv.push("w", grads)
    outs = [nd.zeros(SHAPE) for _ in range(N)]
    kv.pull("w", out=outs)
    expect = sum(range(1, N + 1))
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), expect, rtol=1e-6)
    # a second push replaces the (now device-committed) entry, not adds
    kv.push("w", grads)
    out = nd.zeros(SHAPE)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)


def test_local_push_multi_copy():
    kv = mx.kv.create("local")
    kv.init("a", nd.zeros(SHAPE))
    kv.push("a", [nd.ones(SHAPE), nd.ones(SHAPE) * 2, nd.ones(SHAPE) * 3])
    out = nd.zeros(SHAPE)
    kv.pull("a", out=out)
    np.testing.assert_allclose(out.asnumpy(), 6.0)


def test_updater_runs_server_side():
    """set_optimizer runs the update inside the store on push (reference
    KVStore::set_updater, kvstore.py:450)."""
    kv = mx.kv.create("tpu")
    kv.init("w", nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push("w", _per_device_copies([np.ones(SHAPE)] * N))
    out = nd.zeros(SHAPE)
    kv.pull("w", out=out)
    # w <- w - lr * sum(grads) = 1 - 0.1 * N
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.1 * N, rtol=1e-6)


def test_uninitialized_key_raises():
    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError):
        kv.push("nope", nd.ones(SHAPE))
    with pytest.raises(mx.MXNetError):
        kv.pull("nope", out=nd.zeros(SHAPE))


def test_rank_and_num_workers():
    kv = mx.kv.create("tpu")
    assert kv.rank == jax.process_index()
    assert kv.num_workers == jax.process_count()
    kv._barrier()  # completes without error
    local = mx.kv.create("local")
    assert (local.rank, local.num_workers) == (0, 1)


def test_factory_aliases_and_errors():
    assert mx.kv.create("dist_sync").type == "dist_sync"
    assert mx.kv.create("dist").type == "dist_sync"
    assert mx.kv.create("device").type == "device"
    with pytest.raises(mx.MXNetError):
        mx.kv.create("bogus")
    with pytest.raises(TypeError):
        mx.kv.create(7)


def test_two_bit_compression_roundtrip():
    """2-bit quantization with error feedback (reference
    gradient_compression.h:52-134): each push quantizes grad+residual to
    {-t, 0, +t}; the residual carries the quantization error so the sum
    over steps converges to the true gradient sum."""
    kv = mx.kv.create("tpu")
    kv.init("g", nd.zeros((6,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    # |grad| < threshold: the residual stays bounded so the accumulated
    # quantized sum tracks the true gradient sum within one quantum
    # (for |grad| > t the per-step magnitude saturates at t by design)
    grad = np.array([0.3, -0.3, 0.45, -0.1, 0.0, 0.2], np.float32)
    total = np.zeros_like(grad)
    out = nd.zeros((6,))
    for _ in range(8):
        kv.push("g", nd.array(grad))
        kv.pull("g", out=out)
        pulled = out.asnumpy()
        # each push stores exactly one quantum per element
        assert set(np.unique(pulled)) <= {-0.5, 0.0, 0.5}
        total += pulled
    # error feedback: accumulated quantized sum tracks 8*grad within one t
    np.testing.assert_allclose(total, 8 * grad, atol=0.5 + 1e-5)

    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "1bit"})


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.arange(20, dtype=np.float32).reshape(5, 4)
    kv.init("emb", nd.array(w))
    out = nd.zeros((5, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array(np.array([1, 3])))
    expect = np.zeros_like(w)
    expect[[1, 3]] = w[[1, 3]]
    np.testing.assert_allclose(out.asnumpy(), expect)


def test_row_sparse_pull_multi_out():
    kv = mx.kv.create("tpu")
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    kv.init("emb", nd.array(w))
    outs = [nd.zeros((3, 4)) for _ in range(2)]
    kv.row_sparse_pull("emb", out=outs,
                       row_ids=nd.array(np.array([0, 2])))
    expect = np.zeros_like(w)
    expect[[0, 2]] = w[[0, 2]]
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), expect)


def test_optimizer_state_save_load(tmp_path):
    kv = mx.kv.create("local")
    kv.init("w", nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push("w", nd.ones((3,)))
    fname = str(tmp_path / "states")
    kv.save_optimizer_states(fname)
    # resume: same weight AND same momentum state -> identical next update
    w_now = nd.zeros((3,))
    kv.pull("w", out=w_now)
    kv2 = mx.kv.create("local")
    kv2.init("w", w_now)
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(fname)
    # same momentum state -> same next update
    kv.push("w", nd.ones((3,)))
    kv2.push("w", nd.ones((3,)))
    o1, o2 = nd.zeros((3,)), nd.zeros((3,))
    kv.pull("w", out=o1)
    kv2.pull("w", out=o2)
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)


def test_dist_async_applies_updates_per_copy():
    """dist_async: with a server-side updater each gradient copy applies
    immediately and independently (reference kvstore_dist_server.h:346-351
    else-branch) — N copies = N sequential optimizer steps, unlike sync
    mode's single aggregated step."""
    def build(kv_type):
        kv = mx.kv.create(kv_type)
        kv.init("w", nd.ones(SHAPE))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
        return kv

    grads = _per_device_copies([np.ones(SHAPE)] * N)
    sync, async_ = build("dist_sync"), build("dist_async")
    sync.push("w", grads)
    async_.push("w", grads)
    o_sync, o_async = nd.zeros(SHAPE), nd.zeros(SHAPE)
    sync.pull("w", out=o_sync)
    async_.pull("w", out=o_async)
    # sync: one step with summed grad: m=-0.1*N, w=1-0.1*N
    np.testing.assert_allclose(o_sync.asnumpy(), 1.0 - 0.1 * N, rtol=1e-5)
    # async: N momentum steps with grad 1 each
    w, m = 1.0, 0.0
    for _ in range(N):
        m = 0.9 * m - 0.1 * 1.0
        w = w + m
    np.testing.assert_allclose(o_async.asnumpy(), w, rtol=1e-5)
    assert not np.allclose(o_sync.asnumpy(), o_async.asnumpy())
