"""mxnet_tpu.serving.tenancy — the multi-tenant serving control plane
(tier-1, CPU).

Covers the ISSUE-13 acceptance surface: weighted-fair admission (DRR
ratios, priority classes, guard deferral without head-of-line blocking),
per-tenant bounded sub-queues shedding before the global queue, KV page
quotas (budget never exceeded at any tick) and token-rate budgets,
sliding-window tenant breakers, the chaos tenant-isolation proof (faults
scheduled against tenant A open only A's breaker; B/C answered
oracle-exact with p99 within tolerance of the fault-free run), deadline
eviction at tick boundaries, and the live weight swap (zero dropped
requests, zero steady-state recompiles) on both serving planes."""
import time

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving import tenancy
from mxnet_tpu.serving.tenancy import (TenantBreaker, TenantRegistry,
                                       TenantUnavailableError,
                                       WeightedFairQueue, parse_tenants)


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.disable()
    yield
    chaos.disable()


def _uname(prefix="tn"):
    return "%s%d" % (prefix, np.random.randint(1 << 30))


# ---------------------------------------------------------------------------
# spec DSL + registry
# ---------------------------------------------------------------------------

def test_parse_tenants_spec():
    cfgs = parse_tenants(
        "gold,weight=4,priority=interactive,pages=64,rate=500,burst=900;"
        "id=bronze,weight=1,priority=batch,depth=32")
    assert cfgs[0] == {"tenant_id": "gold", "weight": 4.0, "priority": 0,
                       "page_budget": 64, "rate": 500.0, "burst": 900.0}
    assert cfgs[1] == {"tenant_id": "bronze", "weight": 1.0, "priority": 2,
                       "queue_depth": 32}
    assert parse_tenants("") == []
    with pytest.raises(MXNetError, match="unknown key"):
        parse_tenants("a,wieght=2")
    with pytest.raises(MXNetError, match="bad value"):
        parse_tenants("a,weight=fast")
    with pytest.raises(MXNetError, match="names no tenant id"):
        parse_tenants("weight=2")


def test_registry_defaults_resolve_and_order():
    reg = TenantRegistry(server=_uname("reg"), spec="a,weight=2;b",
                         max_cost=8.0)
    assert [t.tenant_id for t in reg] == ["a", "b"]
    # untagged -> default tenant, unknown ids auto-register
    d = reg.resolve(None)
    assert d.tenant_id == tenancy.DEFAULT_TENANT
    x = reg.resolve("newcomer")
    assert x.weight == 1.0 and x.page_budget is None
    assert [t.tenant_id for t in reg] == ["a", "b", "default", "newcomer"]
    # get-or-create: re-register returns the existing tenant unchanged
    assert reg.register("a", weight=99).weight == 2.0


# ---------------------------------------------------------------------------
# weighted-fair queue (unit, no engine)
# ---------------------------------------------------------------------------

class _FakeReq:
    def __init__(self, cost=1.0, deadline=None):
        self.cost = float(cost)
        self.t_submit = time.perf_counter()
        self.deadline = deadline


def _wfq(spec, max_cost=1.0):
    reg = TenantRegistry(server=_uname("wfq"), spec=spec, max_cost=max_cost)
    return reg, WeightedFairQueue(reg, cost_fn=lambda r: r.cost)


def test_wfq_drr_ratio_follows_weights():
    reg, q = _wfq("a,weight=3;b,weight=1")
    a, b = reg.get("a"), reg.get("b")
    for _ in range(40):
        q.push(a, _FakeReq())
        q.push(b, _FakeReq())
    picks = [q.pop()[0].tenant_id for _ in range(32)]
    assert picks.count("a") == 24 and picks.count("b") == 8
    # a's service comes in weight-sized runs, not one giant burst
    assert max(len(run) for run in "".join(picks).split("b") if run) <= 3


def test_wfq_priority_classes_are_strict():
    reg, q = _wfq("fg,priority=interactive;bg,priority=batch,weight=100")
    fg, bg = reg.get("fg"), reg.get("bg")
    for _ in range(3):
        q.push(bg, _FakeReq())
        q.push(fg, _FakeReq())
    picks = [q.pop()[0].tenant_id for _ in range(6)]
    # weight 100 does not matter across classes: interactive first, always
    assert picks == ["fg", "fg", "fg", "bg", "bg", "bg"]


def test_wfq_guard_defers_one_tenant_without_blocking():
    reg, q = _wfq("a;b")
    a, b = reg.get("a"), reg.get("b")
    for _ in range(2):
        q.push(a, _FakeReq())
        q.push(b, _FakeReq())
    vetoed = {"a"}
    guard = lambda t, r: t.tenant_id not in vetoed  # noqa: E731
    assert [q.pop(guard)[0].tenant_id for _ in range(2)] == ["b", "b"]
    # a was deferred, not dropped: un-vetoing serves its queued work
    assert q.pop(guard) is None and q.total_queued() == 2
    vetoed.clear()
    assert [q.pop(guard)[0].tenant_id for _ in range(2)] == ["a", "a"]
    assert q.total_queued() == 0


def test_wfq_expire_and_drain():
    reg, q = _wfq("a;b")
    a, b = reg.get("a"), reg.get("b")
    q.push(a, _FakeReq(deadline=time.perf_counter() - 1.0))
    q.push(a, _FakeReq())
    q.push(b, _FakeReq())
    expired = q.expire(time.perf_counter())
    assert len(expired) == 1 and expired[0][0].tenant_id == "a"
    assert q.total_queued() == 2
    assert len(q.drain(a)) == 1 and q.total_queued() == 1
    assert len(q.drain()) == 1 and q.total_queued() == 0


# ---------------------------------------------------------------------------
# tenant breaker (unit)
# ---------------------------------------------------------------------------

def test_tenant_breaker_windowed_trip_and_recovery():
    br = TenantBreaker(_uname("srv"), "t", failure_threshold=2,
                       window_s=10.0, reset_timeout_s=0.05)
    assert br.state == "closed" and br.allow()
    br.on_failure()
    # interleaved successes do NOT reset the window count — the whole
    # point: a bad tenant's failures hide between other traffic
    br.on_success()
    assert br.state == "closed"
    br.on_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.state == "half_open"
    assert br.allow()       # the probe
    assert not br.allow()   # only one probe
    br.on_success()
    assert br.state == "closed" and br.allow()


def test_token_refund_restores_budget():
    # an admission vetoed AFTER the bucket was debited (breaker veto in
    # the guard) refunds: the tenant is not charged for work never run
    reg = TenantRegistry(server=_uname("reg"), spec="r,rate=10,burst=10",
                         max_cost=10.0)
    t = reg.get("r")
    assert t.take_tokens(8)
    assert not t.take_tokens(8)  # drained
    t.refund_tokens(8)
    assert t.take_tokens(8)      # restored
    t.refund_tokens(1000)        # capped at burst, never overflows
    assert t.take_tokens(10) and not t.take_tokens(10)


def test_tenant_breaker_probe_lease_expires():
    # a consumed half-open probe whose request never reports an outcome
    # (deferred after allow(), expired at batch assembly) must not wedge
    # the breaker: the lease times out and a fresh probe is admitted
    br = TenantBreaker(_uname("srv"), "t", failure_threshold=1,
                       window_s=10.0, reset_timeout_s=0.05)
    br.on_failure()
    time.sleep(0.06)
    assert br.allow()        # the probe
    assert not br.allow()    # exhausted while the probe is in flight
    time.sleep(0.06)         # ...which never reported
    assert br.allow()        # lease expired: probe re-issued, no wedge


def test_tenant_breaker_window_forgets_old_failures():
    br = TenantBreaker(_uname("srv"), "t", failure_threshold=2,
                       window_s=0.05, reset_timeout_s=10.0)
    br.on_failure()
    time.sleep(0.08)  # first failure ages out of the window
    br.on_failure()
    assert br.state == "closed"


# ---------------------------------------------------------------------------
# decode engine: fairness, quotas, sheds
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    model = serving.TinyDecoder(vocab_size=32, num_layers=1, num_heads=2,
                                head_dim=8)
    return model, model.init_params(0)


def _engine(tiny, **kw):
    model, params = tiny
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("timeout_ms", 0)
    kw.setdefault("name", _uname())
    return serving.DecodeEngine(model, params, **kw)


def test_hot_tenant_cannot_starve_background(tiny):
    # the fairness proof: one slot, a hot tenant floods 12 requests in
    # before a background tenant's 3 arrive — DRR interleaves admission,
    # so bg completes long before the hot backlog drains (pure FIFO
    # would finish bg dead last)
    with _engine(tiny, num_slots=1, max_seq_len=32,
                 tenants="hot,weight=1;bg,weight=1") as eng:
        eng.warmup()
        order = []
        futs = []
        for i in range(12):
            f = eng.submit([1 + i % 8], 3, tenant="hot")
            f.add_done_callback(lambda _f: order.append("hot"))
            futs.append(f)
        for i in range(3):
            f = eng.submit([20 + i], 3, tenant="bg")
            f.add_done_callback(lambda _f: order.append("bg"))
            futs.append(f)
        for f in futs:
            f.result(timeout=120)
        stats = eng.stats()
    assert stats["tenants"]["bg"]["completed"] == 3
    last_bg = max(i for i, t in enumerate(order) if t == "bg")
    # all bg done before the last ~3 hot requests even start finishing
    assert last_bg < len(order) - 1
    assert stats["steady_state_recompiles"] == 0


def test_page_quota_defers_without_exceeding_budget(tiny):
    # A's budget covers ONE worst-case sequence; its second request
    # defers until the first completes, while B is admitted meanwhile —
    # and A's pages-in-use high-water mark never tops its budget
    with _engine(tiny, num_slots=2, max_seq_len=32, page_size=8,
                 tenants="A,pages=2;B") as eng:
        eng.warmup()
        futs = [eng.submit([1], 10, tenant="A"),
                eng.submit([2], 10, tenant="A"),
                eng.submit([3], 10, tenant="B")]
        for f in futs:
            f.result(timeout=120)
        stats = eng.stats()
    a = stats["tenants"]["A"]
    assert a["completed"] == 2
    assert a["deferred_pages"] >= 1          # the second request waited
    assert a["pages_in_use_max"] <= 2        # budget held at EVERY tick
    assert stats["tenants"]["B"]["completed"] == 1
    assert stats["kvcache"]["pages_in_use"] == 0


def test_rate_limit_defers_only_that_tenant(tiny):
    # A has a tiny token budget (fits one request, then must refill at
    # 1 token/s); B is unlimited and keeps flowing while A waits
    with _engine(tiny, num_slots=2, max_seq_len=32,
                 tenants="A,rate=1,burst=6;B") as eng:
        eng.warmup()
        fa = eng.submit([1, 2], 4, tenant="A")  # cost 6 = the whole burst
        t0 = time.perf_counter()
        fb = [eng.submit([3 + i], 4, tenant="B") for i in range(4)]
        fa.result(timeout=120)
        for f in fb:
            f.result(timeout=120)
        b_done = time.perf_counter() - t0
        # cost 5 against a drained bucket refilling at 1 token/s: don't
        # wait the ~5s out — just assert it DEFERS while B still flows
        fa2 = eng.submit([9], 4, tenant="A")
        time.sleep(0.1)
        fb2 = eng.submit([10], 4, tenant="B")
        fb2.result(timeout=120)
        stats = eng.stats()
        assert not fa2.done() or not isinstance(fa2.exception(), Exception)
        eng.close(drain=False)
    assert stats["tenants"]["A"]["deferred_rate"] >= 1
    assert stats["tenants"]["B"]["completed"] == 5
    assert b_done < 60  # B was never blocked behind A's rate wait


def test_submit_rejects_unadmittable_tenant_requests(tiny):
    with _engine(tiny, max_seq_len=64, page_size=8,
                 tenants="A,pages=2;R,rate=10,burst=16") as eng:
        with pytest.raises(MXNetError, match="page budget"):
            eng.submit([1] * 10, 20, tenant="A")  # 30 tokens = 4 pages > 2
        with pytest.raises(MXNetError, match="burst"):
            eng.submit([1] * 10, 20, tenant="R")  # 30 tokens > burst 16
        # within budget still serves
        assert len(eng.generate([1], 4, tenant="A")) == 4


def test_per_tenant_queue_sheds_before_global(tiny):
    # tenant A's sub-queue bound (2) trips while the global queue (256)
    # is nowhere near full — and B can still submit
    with _engine(tiny, num_slots=1, max_seq_len=64,
                 tenants="A,depth=2;B") as eng:
        eng.warmup()
        blocker = eng.submit([1, 2], 40, tenant="B")  # occupies the slot
        futs = [eng.submit([3 + i], 30, tenant="A") for i in range(2)]
        with pytest.raises(serving.QueueFullError, match="tenant 'A'"):
            for _ in range(3):  # the worker may admit one meanwhile
                futs.append(eng.submit([9], 30, tenant="A"))
        assert eng.submit([7], 4, tenant="B") is not None
        stats = eng.stats()
        assert stats["tenants"]["A"]["shed"] >= 1
        eng.close(drain=False)


# ---------------------------------------------------------------------------
# chaos tenant isolation: the acceptance proof
# ---------------------------------------------------------------------------

def _isolation_traffic(eng, model, params, n_waves=8):
    """Interleaved A/B/C waves; returns per-tenant outcome lists."""
    out = {"A": [], "B": [], "C": []}
    for w in range(n_waves):
        futs = []
        for tid, k in (("A", 2), ("B", 1), ("C", 1)):
            for j in range(k):
                prompt = [1 + (w + j) % 8, 2 + w % 5]
                try:
                    futs.append((tid, prompt,
                                 eng.submit(prompt, 3, tenant=tid)))
                except TenantUnavailableError as e:
                    out[tid].append(("shed", e))
        for tid, prompt, f in futs:
            try:
                out[tid].append(("ok", prompt, f.result(timeout=120)))
            except chaos.FaultInjected as e:
                out[tid].append(("fault", e))
            except TenantUnavailableError as e:
                out[tid].append(("shed", e))
    return out


def test_chaos_tenant_isolation_suite(tiny):
    """Faults scheduled against tenant A's requests (p=0.3, seeded) stay
    inside A's breaker: A opens and is shed, the ENGINE breaker never
    trips, B/C get every request answered oracle-exact, and B/C p99 stays
    within tolerance of the fault-free run."""
    model, params = tiny

    def run(spec):
        eng = _engine(tiny, num_slots=2, max_seq_len=32)
        eng.tenants.register("A", breaker_threshold=3,
                             breaker_window_s=60.0, breaker_reset_s=60.0)
        eng.tenants.register("B")
        eng.tenants.register("C")
        eng.warmup()
        try:
            if spec:
                with chaos.active(spec):
                    out = _isolation_traffic(eng, model, params)
            else:
                out = _isolation_traffic(eng, model, params)
            return out, eng.stats(), eng._breaker.state
        finally:
            eng.close(drain=False)

    base_out, base_stats, _ = run(None)
    assert all(k[0] == "ok" for v in base_out.values() for k in v)

    spec = "seed=11,site=serving.decode.tenant.A,p=0.3"
    out, stats, engine_breaker = run(spec)

    # A: faulted, its breaker opened, and later traffic was shed — alone
    a_kinds = [o[0] for o in out["A"]]
    assert a_kinds.count("fault") >= 3
    assert a_kinds.count("shed") >= 1
    assert stats["tenants"]["A"]["breaker"] in ("open", "half_open")
    assert stats["tenants"]["A"]["shed_breaker"] >= 1

    # the engine-level breaker never saw any of it
    assert engine_breaker == "closed"
    assert stats["breaker"] == "closed"
    assert stats["evictions"] == 0
    assert stats["steady_state_recompiles"] == 0

    # B and C: every request answered, oracle-exact
    for tid in ("B", "C"):
        assert all(o[0] == "ok" for o in out[tid]), out[tid]
        for _kind, prompt, got in out[tid]:
            np.testing.assert_array_equal(
                got, model.reference_generate(params, prompt, 3))
        assert stats["tenants"][tid]["breaker"] == "closed"
        # p99 within tolerance of the fault-free run (generous bound:
        # CI timing noise dwarfs any real coupling)
        base_p99 = base_stats["tenants"][tid]["latency_p99_ms"]
        assert stats["tenants"][tid]["latency_p99_ms"] <= \
            max(10.0 * base_p99, base_p99 + 250.0)

    # and the per-tenant breaker gauge is scrape-visible
    text = telemetry.render_prometheus()
    assert 'mxnet_tenant_breaker_state{' in text


# ---------------------------------------------------------------------------
# deadline propagation into decode ticks
# ---------------------------------------------------------------------------

def test_deadline_expiring_mid_decode_evicts_at_tick_boundary(tiny):
    model, params = tiny
    with _engine(tiny, num_slots=1, max_seq_len=128) as eng:
        eng.warmup()
        # pin the race: 5ms/tick makes a 100-token generation take
        # >= 500ms, so a 100ms deadline MUST expire mid-decode (warm
        # prefill admits in a few ms — far inside the deadline)
        orig_step = eng._step_once

        def slow_step(active):
            time.sleep(0.005)
            return orig_step(active)

        eng._step_once = slow_step
        fut = eng.submit([1, 2], 100, timeout_ms=100)
        with pytest.raises(serving.RequestTimeoutError, match="mid-decode"):
            fut.result(timeout=120)
        eng._step_once = orig_step
        stats = eng.stats()
        assert stats["deadline_evictions"] == 1  # evicted, not queue-aged
        assert stats["kvcache"]["pages_in_use"] == 0  # pages freed
        # the engine keeps serving, oracle-exact, without recompiling
        np.testing.assert_array_equal(
            eng.generate([5], 4),
            model.reference_generate(params, [5], 4))
        assert eng.stats()["steady_state_recompiles"] == 0


# ---------------------------------------------------------------------------
# live weight swap
# ---------------------------------------------------------------------------

def test_live_swap_zero_drop_zero_recompile(tiny):
    model, params = tiny
    params_b = model.init_params(1)
    with _engine(tiny, num_slots=2, max_seq_len=64) as eng:
        eng.warmup()
        # in-flight load across the swap: nothing may drop
        futs = [eng.submit([1 + i], 12) for i in range(6)]
        eng.register_variant("B", params_b)
        eng.use_variant("B", timeout=60)   # applied at a tick boundary
        assert eng.active_variant == "B"
        for f in futs:
            assert len(f.result(timeout=120)) == 12  # zero dropped
        # requests submitted after the swap serve the NEW weights
        np.testing.assert_array_equal(
            eng.generate([3, 1, 4], 5),
            model.reference_generate(params_b, [3, 1, 4], 5))
        stats = eng.stats()
    assert stats["weight_swaps"] == 1
    assert stats["completed"] == 7 and stats["errors"] == 0
    # the PR-3 gauge: a swap is data movement, never a retrace
    assert stats["steady_state_recompiles"] == 0


def test_swap_applies_while_idle_and_ab_flips_back(tiny):
    model, params = tiny
    params_b = model.init_params(2)
    with _engine(tiny, num_slots=1, max_seq_len=64) as eng:
        eng.warmup()
        eng.swap_params(params_b, timeout=60)  # idle engine: still applies
        np.testing.assert_array_equal(
            eng.generate([7], 4),
            model.reference_generate(params_b, [7], 4))
        eng.swap_params(params, timeout=60)    # A/B flip back
        np.testing.assert_array_equal(
            eng.generate([7], 4),
            model.reference_generate(params, [7], 4))
        assert eng.stats()["weight_swaps"] == 2


def test_swap_rejects_mismatched_signature(tiny):
    _model, _params = tiny
    other = serving.TinyDecoder(vocab_size=32, num_layers=1, num_heads=2,
                                head_dim=16)  # different head_dim
    with _engine(tiny) as eng:
        with pytest.raises(MXNetError, match="signature differs"):
            eng.swap_params(other.init_params(0))
        with pytest.raises(MXNetError, match="signature differs"):
            eng.register_variant("bad", other.init_params(0))
        with pytest.raises(MXNetError, match="unknown variant"):
            eng.use_variant("never-registered")


# ---------------------------------------------------------------------------
# batch server plane
# ---------------------------------------------------------------------------

class _PoisonEngine(serving.Engine):
    """Doubles rows; raises on any 'poisoned' row (value > 100)."""

    kind = "poison"

    def run(self, batch):
        if (batch > 100.0).any():
            raise RuntimeError("poisoned row in batch")
        return batch * 2.0

    @property
    def compile_count(self):
        return 0


def test_server_tenant_breaker_sheds_poison_tenant_alone():
    srv = serving.Server(_PoisonEngine(), (4,), buckets=[1, 4],
                         max_delay_ms=1.0, timeout_ms=0,
                         name=_uname("srv"), breaker_threshold=100)
    srv.tenants.register("evil", breaker_threshold=3,
                         breaker_window_s=60.0, breaker_reset_s=60.0)
    srv.tenants.register("good")
    try:
        poison = np.full((4,), 200.0, np.float32)
        ok = np.ones((4,), np.float32)
        failures = 0
        shed = 0
        for i in range(8):
            try:
                f = srv.submit(poison, tenant="evil")
                with pytest.raises(RuntimeError):
                    f.result(timeout=30)
                failures += 1
            except TenantUnavailableError:
                shed += 1
            out = srv.submit(ok, tenant="good").result(timeout=30)
            np.testing.assert_allclose(out, ok * 2.0)
        stats = srv.stats()
        assert failures >= 3 and shed >= 1  # opened after 3, then shed
        assert stats["tenants"]["evil"]["breaker"] in ("open", "half_open")
        assert stats["tenants"]["good"]["completed"] == 8
        assert stats["tenants"]["good"]["breaker"] == "closed"
        # the ENGINE breaker survived: good traffic kept resetting it
        assert stats["breakers"]["primary"] == "closed"
    finally:
        srv.close(timeout=10)


class _SwappableEngine(serving.Engine):
    kind = "swappable"

    def __init__(self):
        self.source = {"scale": 2.0}
        self._scale = 2.0

    def refresh_params(self):
        self._scale = self.source["scale"]

    def run(self, batch):
        return batch * self._scale

    @property
    def compile_count(self):
        return 0


def test_server_refresh_params_is_a_live_swap():
    srv = serving.Server(_SwappableEngine(), (2,), buckets=[1, 4],
                         max_delay_ms=1.0, timeout_ms=0,
                         name=_uname("srv"))
    try:
        x = np.asarray([1.0, 2.0], np.float32)
        np.testing.assert_allclose(srv.submit(x).result(timeout=30), x * 2)
        srv._engine.source["scale"] = 3.0
        assert srv.refresh_params() == 1  # one engine in the chain swapped
        np.testing.assert_allclose(srv.submit(x).result(timeout=30), x * 3)
        assert srv.stats()["errors"] == 0
    finally:
        srv.close(timeout=10)


def test_server_weighted_fair_batch_fill():
    # hot floods 12, bg queues 3 — WFQ batch assembly interleaves, so bg
    # completes inside the first couple of batches, not dead last
    class _Slow(serving.Engine):
        kind = "slow"

        def run(self, batch):
            time.sleep(0.01)
            return batch * 2.0

        @property
        def compile_count(self):
            return 0

    srv = serving.Server(_Slow(), (2,), buckets=[2], max_delay_ms=1.0,
                         timeout_ms=0, name=_uname("srv"),
                         tenants="hot;bg")
    try:
        order = []
        futs = []
        x = np.ones((2,), np.float32)
        for i in range(12):
            f = srv.submit(x * i, tenant="hot")
            f.add_done_callback(lambda _f: order.append("hot"))
            futs.append(f)
        for i in range(3):
            f = srv.submit(x, tenant="bg")
            f.add_done_callback(lambda _f: order.append("bg"))
            futs.append(f)
        for f in futs:
            f.result(timeout=60)
        stats = srv.stats()
        assert stats["tenants"]["bg"]["completed"] == 3
        assert max(i for i, t in enumerate(order) if t == "bg") < 14
    finally:
        srv.close(timeout=10)


# ---------------------------------------------------------------------------
# telemetry rows
# ---------------------------------------------------------------------------

def test_tenant_metric_families_render(tiny):
    name = "tel-tenant-test"
    with _engine(tiny, name=name, tenants="alpha,weight=2") as eng:
        eng.warmup()
        eng.generate([1, 2], 4, tenant="alpha")
        stats = eng.stats()
    snap = stats["tenants"]["alpha"]
    assert snap["completed"] == 1 and snap["ttft_count"] == 1
    assert snap["tpot_count"] == 3
    text = telemetry.render_prometheus()
    for fam in ("mxnet_tenant_requests_total", "mxnet_tenant_queue_depth",
                "mxnet_tenant_pages_in_use", "mxnet_tenant_ttft_ms",
                "mxnet_tenant_tpot_ms", "mxnet_tenant_breaker_state"):
        assert '%s{server="%s",tenant="alpha"' % (fam, name) in text \
            or '%s_count{server="%s",tenant="alpha"' % (fam, name) in text \
            or fam in text


# ---------------------------------------------------------------------------
# prefix caching x tenancy: shared pages charge no tenant twice (ISSUE 14)
# ---------------------------------------------------------------------------

def test_shared_tenant_id_is_reserved():
    reg = TenantRegistry(server=_uname("shr"))
    with pytest.raises(MXNetError, match="reserved"):
        reg.register("shared")
    with pytest.raises(MXNetError, match="reserved"):
        parse_tenants("shared,weight=2") and reg.register(
            **parse_tenants("shared,weight=2")[0])


def test_shared_pages_not_double_charged_two_tenant_soak(tiny):
    # the budget-invariant soak: A and B share one 16-token system
    # prompt; each budget (3 pages of 8) covers exactly ONE cold
    # worst-case request (2 prompt pages + 1 generation page). Only
    # tail-only charging lets BOTH run concurrently: a sharer pays 1
    # page, not 3 — double-charging would defer every concurrent pair.
    model, params = tiny
    sysp = list(np.random.RandomState(11).randint(1, 30, 16))
    with _engine(tiny, num_slots=2, max_seq_len=32, page_size=8,
                 prefix_cache=True, tenants="A,pages=3;B,pages=3") as eng:
        eng.warmup()
        # cold lap: A prefills the shared prompt once (charged 3)
        p0 = np.asarray(sysp, np.int32)
        np.testing.assert_array_equal(
            eng.generate(p0, 8, tenant="A"),
            model.reference_generate(params, p0, 8))
        # warm soak: both tenants ride the shared prefix concurrently
        futs = []
        for i in range(6):
            futs.append((p0, eng.submit(p0, 8, tenant="A" if i % 2 else "B")))
        for p, f in futs:
            np.testing.assert_array_equal(
                f.result(timeout=120),
                model.reference_generate(params, p, 8))
        # poll a moment where both sharers were live at once
        stats = eng.stats()
    a, b = stats["tenants"]["A"], stats["tenants"]["B"]
    assert a["completed"] + b["completed"] == 7
    # the invariant: per-tenant high-water marks under tail-only charge
    assert a["pages_in_use_max"] <= 3
    assert b["pages_in_use_max"] <= 3
    # both warm sequences fit at once ONLY because shared pages charge
    # the pseudo-tenant: no deferral needed in the warm soak
    assert a["deferred_pages"] == 0 and b["deferred_pages"] == 0
    assert stats["tenants"]["shared"]["pseudo"] is True
    assert stats["kvcache"]["prefix_hits"] >= 6
    assert stats["kvcache"]["pages_in_use"] == 0
    assert stats["steady_state_recompiles"] == 0


def test_shared_pseudo_tenant_counts_refcounted_pages(tiny):
    # while two sequences share prefix pages, the `shared` pseudo row
    # reports refcount>1 pages; once everyone frees, it reads 0
    model, params = tiny
    sysp = np.asarray(list(range(1, 17)), np.int32)
    with _engine(tiny, num_slots=2, max_seq_len=64, page_size=8,
                 prefix_cache=True) as eng:
        eng.warmup()
        eng.generate(sysp, 2, tenant="A")  # seed the index
        fa = eng.submit(sysp, 30, tenant="A")
        fb = eng.submit(sysp, 30, tenant="B")
        seen_shared = 0
        deadline = time.time() + 60
        while time.time() < deadline:
            snap = eng.stats()
            seen_shared = max(
                seen_shared,
                snap["tenants"]["shared"]["pages_in_use_now"])
            if fa.done() and fb.done():
                break
            time.sleep(0.005)
        fa.result(timeout=120)
        fb.result(timeout=120)
        stats = eng.stats()
    assert seen_shared >= 2  # both mapped the 2 full prompt pages
    assert stats["tenants"]["shared"]["pages_in_use_now"] == 0
    assert stats["kvcache"]["pages_in_use"] == 0
