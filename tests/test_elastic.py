"""Elastic training tests: atomic checkpoints, resume-after-crash harness,
dead-node API surface.

The reference covers this at the ps-lite level (heartbeats/GetDeadNodes,
recovery flag); the TPU design's equivalent contract is checkpoint-commit
atomicity + automatic restart (SURVEY §5.3).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon.loss import L2Loss


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="el_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(1))
    net.initialize()
    # materialize deferred shapes
    net(mx.nd.ones((2, 4)))
    return net


def test_checkpoint_save_restore(tmp_path):
    net = _make_net(1)
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=3)
    assert cm.latest_epoch() == -1
    cm.save(0, net=net, trainer=trainer, metadata={"note": "first"})
    assert cm.latest_epoch() == 0

    want = {k: p.data().asnumpy() for k, p in net.collect_params().items()}
    net2 = _make_net(2)  # different init
    trainer2 = Trainer(net2.collect_params(), "sgd", {"learning_rate": 0.1})
    assert cm.restore(net=net2, trainer=trainer2) == 0
    for k, p in net2.collect_params().items():
        np.testing.assert_allclose(p.data().asnumpy(), want[k], rtol=1e-6,
                                   err_msg=k)


def test_checkpoint_retention(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=2)
    for e in range(5):
        cm.save(e, params={"w": mx.nd.full((2,), float(e))})
    assert cm._epochs() == [3, 4]
    params = cm.load_params()
    np.testing.assert_allclose(params["w"].asnumpy(), [4.0, 4.0])


def test_torn_checkpoint_invisible(tmp_path):
    """A params file without its manifest must not be resumable — the
    manifest write is the commit point."""
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save(0, params={"w": mx.nd.ones((2,))})
    # simulate a crash mid-save of epoch 1: params written, no manifest
    from mxnet_tpu.ndarray import io_utils

    io_utils.save(cm._params_path(1), {"w": mx.nd.zeros((2,))})
    assert cm.latest_epoch() == 0
    np.testing.assert_allclose(cm.load_params()["w"].asnumpy(), [1.0, 1.0])


def test_run_elastic_resumes(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    crashed = {"done": False}
    trained_epochs = []

    def train_fn(start_epoch, manager):
        for epoch in range(start_epoch, 6):
            if epoch == 3 and not crashed["done"]:
                crashed["done"] = True
                raise RuntimeError("injected failure")
            trained_epochs.append(epoch)
            manager.save(epoch, params={"w": mx.nd.full((1,), float(epoch))})
        return "finished"

    # restart_delay=0: the backoff schedule has its own test
    # (test_resilience.test_run_elastic_backoff_schedule)
    assert elastic.run_elastic(train_fn, cm, max_restarts=2,
                               restart_delay=0) == "finished"
    # epochs 0-2 trained, crash, resume from 3 (last committed was 2)
    assert trained_epochs == [0, 1, 2, 3, 4, 5]
    assert cm.latest_epoch() == 5


def test_run_elastic_gives_up(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))

    def always_fail(start_epoch, manager):
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        elastic.run_elastic(always_fail, cm, max_restarts=2,
                            restart_delay=0)


def test_dead_nodes_single_process():
    # no distributed runtime: nothing to detect, API still answers
    assert elastic.get_dead_nodes() == []
    assert elastic.start_heartbeat() is False
    kv = mx.kvstore.create("dist_sync")
    assert kv.get_dead_nodes() == []


def test_manifest_metadata(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    path = cm.save(2, params={"w": mx.nd.ones((1,))},
                   metadata={"lr": 0.01, "step": 1234})
    with open(path) as f:
        manifest = json.load(f)
    assert manifest["epoch"] == 2
    assert manifest["metadata"]["step"] == 1234
    assert os.path.isfile(os.path.join(str(tmp_path), manifest["files"]["params"]))


def test_async_save_ordered_and_joined(tmp_path):
    """async_save rides the host engine: writes stay ordered per manager,
    latest_epoch()/wait() join them, and the snapshot is taken at call time
    (later mutations don't leak into the checkpoint)."""
    cm = elastic.CheckpointManager(str(tmp_path))
    w = mx.nd.full((3,), 1.0)
    cm.save(0, params={"w": w}, async_save=True)
    w[:] = 999.0  # mutate AFTER the async save snapshotted
    for e in range(1, 4):
        cm.save(e, params={"w": mx.nd.full((3,), float(e))}, async_save=True)
    assert cm.latest_epoch() == 3  # joins all pending writes
    np.testing.assert_allclose(cm.load_params(0)["w"].asnumpy(), [1.0] * 3)
    np.testing.assert_allclose(cm.load_params(3)["w"].asnumpy(), [3.0] * 3)


def test_async_save_failure_surfaces_at_wait(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path / "sub"))
    import os
    import shutil

    shutil.rmtree(str(tmp_path / "sub"))  # make the write fail
    cm.save(0, params={"w": mx.nd.ones((2,))}, async_save=True)
    with pytest.raises(Exception):
        cm.wait()


def test_async_save_snapshots_trainer_state(tmp_path):
    """Optimizer state is serialized at save() time, not later on the
    engine thread — a post-save trainer.step must not leak in."""
    net = _make_net(3)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = L2Loss()
    X, Y = mx.nd.ones((4, 4)), mx.nd.ones((4, 1))
    with mx.autograd.record():
        loss = loss_fn(net(X), Y)
    loss.backward()
    trainer.step(4)  # momentum now nonzero
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save(0, net=net, trainer=trainer, async_save=True)
    # mutate AFTER the async save: another step changes momentum
    with mx.autograd.record():
        loss = loss_fn(net(X), Y)
    loss.backward()
    trainer.step(4)
    cm.wait()
    # restoring must reproduce the state AT save time: roll a fresh
    # net/trainer forward one step from the checkpoint and compare with
    # rolling the original from its post-save state — they must differ,
    # while double-restore determinism must hold
    net2 = _make_net(4)
    t2 = Trainer(net2.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        loss = loss_fn(net2(X), Y)
    loss.backward()
    t2.step(4)  # materialize updater states before load
    assert cm.restore(net=net2, trainer=t2) == 0
    states = t2._updaters[0].get_states(dump_optimizer=False)
    net3 = _make_net(5)
    t3 = Trainer(net3.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        loss = loss_fn(net3(X), Y)
    loss.backward()
    t3.step(4)
    cm.restore(net=net3, trainer=t3)
    assert t3._updaters[0].get_states(dump_optimizer=False) == states


# ---------------------------------------------------------------------------
# elastic v2: retention/commit bugfixes
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _clear_preemption():
    elastic.clear_preemption()
    yield
    elastic.clear_preemption()


def test_retention_never_retires_newest_committed(tmp_path):
    """Regression: a misconfigured (negative) retention used to retire
    EVERY epoch including the newest committed one; GC must keep >= 1."""
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=-5)
    for e in range(3):
        cm.save(e, params={"w": mx.nd.full((1,), float(e))})
    assert cm.latest_epoch() == 2
    np.testing.assert_allclose(cm.load_params()["w"].asnumpy(), [2.0])


def test_retention_protects_newest_committed_over_quota(tmp_path):
    """The newest COMMITTED manifest survives GC even when a newer (but
    uncommitted — files missing) manifest sits above it in the quota:
    the quota would retire epoch 0, but epoch 1 lost its params file, so
    0 is the last restorable state and must outrank the quota."""
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=0)  # GC off
    for e in range(3):
        cm.save(e, params={"w": mx.nd.full((1,), float(e))})
    os.remove(cm._params_path(1))  # epochs 1 and 2 now read uncommitted
    os.remove(cm._params_path(2))
    cm.max_keep = 1
    cm._retire_old()               # quota says keep only [2]
    assert cm.latest_epoch() == 0  # but 0 is the newest committed
    np.testing.assert_allclose(cm.load_params()["w"].asnumpy(), [0.0])


def test_latest_epoch_skips_manifest_with_missing_files(tmp_path):
    """Regression: a manifest whose referenced files vanished must read
    as uncommitted — resume anchors on the previous committed epoch."""
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save(0, params={"w": mx.nd.ones((2,))})
    cm.save(1, params={"w": mx.nd.zeros((2,))})
    os.remove(cm._params_path(1))
    assert cm.latest_epoch() == 0
    np.testing.assert_allclose(cm.load_params()["w"].asnumpy(), [1.0, 1.0])


def test_restart_budget_resets_on_progress(tmp_path):
    """Regression: a long run with occasional preemptions must not be
    killed by max_restarts accumulated across its lifetime — an attempt
    that commits a newer epoch resets the consecutive-failure budget."""
    cm = elastic.CheckpointManager(str(tmp_path))
    calls = {"n": 0}

    def train_fn(start_epoch, manager):
        manager.save(start_epoch, params={"w": mx.nd.ones((1,))})
        calls["n"] += 1
        if calls["n"] <= 5:  # 5 failures against a budget of 2 — but each
            raise RuntimeError("preempted %d" % calls["n"])  # made progress
        return "done"

    assert elastic.run_elastic(train_fn, cm, max_restarts=2,
                               restart_delay=0) == "done"
    assert calls["n"] == 6


def test_run_elastic_still_exhausts_without_progress(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    calls = {"n": 0}

    def train_fn(start_epoch, manager):
        calls["n"] += 1
        raise RuntimeError("no progress")

    with pytest.raises(RuntimeError, match="no progress"):
        elastic.run_elastic(train_fn, cm, max_restarts=2, restart_delay=0)
    assert calls["n"] == 3


# ---------------------------------------------------------------------------
# preemption watcher + step_boundary
# ---------------------------------------------------------------------------

def test_step_boundary_preemption_saves_then_exits(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    saved = []
    elastic.request_preemption()
    with pytest.raises(elastic.Preempted):
        elastic.step_boundary(manager=cm, save_fn=lambda: saved.append(True))
    assert saved == [True]
    from mxnet_tpu import telemetry

    assert telemetry.PREEMPTIONS.value() >= 1


def test_step_boundary_preemption_save_failure_is_best_effort():
    elastic.request_preemption()

    def bad_save():
        raise RuntimeError("disk full")

    with pytest.raises(elastic.Preempted):  # NOT the RuntimeError
        elastic.step_boundary(save_fn=bad_save)


def test_preemption_file_polled(tmp_path, monkeypatch):
    flag = tmp_path / "evict-notice"
    monkeypatch.setenv("MXNET_PREEMPTION_FILE", str(flag))
    assert elastic.preempt_requested() is False
    flag.write_text("")
    assert elastic.preempt_requested() is True


def test_run_elastic_preempted_does_not_consume_restart(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    calls = {"n": 0}

    def train_fn(start_epoch, manager):
        calls["n"] += 1
        manager.save(0, params={"w": mx.nd.ones((1,))}, async_save=True)
        elastic.request_preemption()
        elastic.step_boundary(manager=manager)

    with pytest.raises(elastic.Preempted):
        elastic.run_elastic(train_fn, cm, max_restarts=3, restart_delay=0)
    assert calls["n"] == 1        # no in-process restart: clean exit
    assert cm.latest_epoch() == 0  # the flush barrier joined the async save


def test_stall_watchdog_restarts(tmp_path):
    import threading

    cm = elastic.CheckpointManager(str(tmp_path))
    wedge = threading.Event()
    attempts = []

    def train_fn(start_epoch, manager):
        attempts.append(start_epoch)
        if len(attempts) == 1:
            wedge.wait(10)  # hung: no step_boundary, no commit
            return "late"
        return "ok"

    try:
        out = elastic.run_elastic(train_fn, cm, max_restarts=2,
                                  restart_delay=0, stall_timeout=0.3)
    finally:
        wedge.set()
    assert out == "ok"
    assert len(attempts) == 2
    from mxnet_tpu import telemetry

    assert telemetry.ELASTIC_RESTARTS.value(reason="stall") >= 1


# ---------------------------------------------------------------------------
# chaos schedule actions
# ---------------------------------------------------------------------------

def test_chaos_action_parse_and_kill():
    from mxnet_tpu.resilience import chaos

    with chaos.active("site=elastic.step,at=2,action=kill"):
        elastic.step_boundary()  # call 1: clean
        with pytest.raises(chaos.Killed):
            elastic.step_boundary()  # call 2: the kill
        elastic.step_boundary()  # call 3: clean again
    with pytest.raises(Exception):
        chaos.parse_spec("site=x,at=1,action=definitely-not-an-action")
    # Killed is NOT transient: the retry machinery must not "recover" it
    from mxnet_tpu.resilience import TransientError

    assert not issubclass(chaos.Killed, TransientError)


def test_kill_at_step_restarts_from_committed(tmp_path):
    from mxnet_tpu.resilience import chaos

    cm = elastic.CheckpointManager(str(tmp_path))
    trained = []

    def train_fn(start_epoch, manager):
        for step in range(start_epoch, 5):
            elastic.step_boundary(manager=manager)
            trained.append(step)
            manager.save(step, params={"w": mx.nd.full((1,), float(step))})
        return "ok"

    with chaos.active("site=elastic.step,at=3,action=kill"):
        assert elastic.run_elastic(train_fn, cm, max_restarts=2,
                                   restart_delay=0) == "ok"
    # killed entering step 2; resumed from the last committed epoch (1)
    assert trained == [0, 1, 2, 3, 4]
    assert cm.latest_epoch() == 4


# ---------------------------------------------------------------------------
# iterator + RNG resume state
# ---------------------------------------------------------------------------

def _seq_iter(n=10, batch_size=2):
    data = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    return mx.io.NDArrayIter(data, np.arange(n, dtype=np.float32),
                             batch_size=batch_size)


def test_ndarray_iter_state_roundtrip():
    it = _seq_iter()
    for _ in range(3):
        it.next()
    state = it.state_dict()
    want = it.next().data[0].asnumpy()
    it2 = _seq_iter()
    it2.set_state(state)
    np.testing.assert_array_equal(it2.next().data[0].asnumpy(), want)


def test_prefetching_iter_state_roundtrip():
    from mxnet_tpu.io import PrefetchingIter

    it = PrefetchingIter(_seq_iter())
    for _ in range(3):
        it.next()
    state = it.state_dict()
    assert state == {"delivered": 3}
    want = it.next().data[0].asnumpy()
    it2 = PrefetchingIter(_seq_iter())
    it2.set_state(state)
    np.testing.assert_array_equal(it2.next().data[0].asnumpy(), want)


def test_device_prefetch_iter_state_roundtrip():
    from mxnet_tpu.io import DevicePrefetchIter

    it = DevicePrefetchIter(_seq_iter())
    for _ in range(3):
        it.next()
    state = it.state_dict()
    want = it.next().data[0].asnumpy()
    it2 = DevicePrefetchIter(_seq_iter())
    it2.set_state(state)
    np.testing.assert_array_equal(it2.next().data[0].asnumpy(), want)
    # and the stream still ends where it should (no off-by-one)
    seen = 1
    try:
        while True:
            it2.next()
            seen += 1
    except StopIteration:
        pass
    assert seen == 10 // 2 - 3


def test_rng_state_roundtrip():
    from mxnet_tpu import _global

    mx.random.seed(11)
    state = mx.random.get_state()
    k1 = np.asarray(_global.next_key())
    h1 = mx.random.np_rng().rand(3)
    mx.random.set_state(state)
    np.testing.assert_array_equal(np.asarray(_global.next_key()), k1)
    np.testing.assert_array_equal(mx.random.np_rng().rand(3), h1)


def test_save_training_carries_iter_rng_and_extra(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    it = _seq_iter()
    for _ in range(2):
        it.next()
    mx.random.seed(13)
    cm.save_training(0, params={"w": mx.nd.ones((1,))}, train_iter=it,
                     extra={"mid_epoch": True, "note": "x"})
    want = it.next().data[0].asnumpy()
    want_key = np.asarray(__import__("mxnet_tpu")._global.next_key())

    it2 = _seq_iter()
    mx.random.seed(99)  # scrambled; restore must bring 13's stream back
    assert cm.restore_training(train_iter=it2) == 0
    assert cm.last_restored_extra == {"mid_epoch": True, "note": "x"}
    np.testing.assert_array_equal(it2.next().data[0].asnumpy(), want)
    from mxnet_tpu import _global

    np.testing.assert_array_equal(np.asarray(_global.next_key()), want_key)
