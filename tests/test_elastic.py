"""Elastic training tests: atomic checkpoints, resume-after-crash harness,
dead-node API surface.

The reference covers this at the ps-lite level (heartbeats/GetDeadNodes,
recovery flag); the TPU design's equivalent contract is checkpoint-commit
atomicity + automatic restart (SURVEY §5.3).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon.loss import L2Loss


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix="el_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(1))
    net.initialize()
    # materialize deferred shapes
    net(mx.nd.ones((2, 4)))
    return net


def test_checkpoint_save_restore(tmp_path):
    net = _make_net(1)
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=3)
    assert cm.latest_epoch() == -1
    cm.save(0, net=net, trainer=trainer, metadata={"note": "first"})
    assert cm.latest_epoch() == 0

    want = {k: p.data().asnumpy() for k, p in net.collect_params().items()}
    net2 = _make_net(2)  # different init
    trainer2 = Trainer(net2.collect_params(), "sgd", {"learning_rate": 0.1})
    assert cm.restore(net=net2, trainer=trainer2) == 0
    for k, p in net2.collect_params().items():
        np.testing.assert_allclose(p.data().asnumpy(), want[k], rtol=1e-6,
                                   err_msg=k)


def test_checkpoint_retention(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path), max_keep=2)
    for e in range(5):
        cm.save(e, params={"w": mx.nd.full((2,), float(e))})
    assert cm._epochs() == [3, 4]
    params = cm.load_params()
    np.testing.assert_allclose(params["w"].asnumpy(), [4.0, 4.0])


def test_torn_checkpoint_invisible(tmp_path):
    """A params file without its manifest must not be resumable — the
    manifest write is the commit point."""
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save(0, params={"w": mx.nd.ones((2,))})
    # simulate a crash mid-save of epoch 1: params written, no manifest
    from mxnet_tpu.ndarray import io_utils

    io_utils.save(cm._params_path(1), {"w": mx.nd.zeros((2,))})
    assert cm.latest_epoch() == 0
    np.testing.assert_allclose(cm.load_params()["w"].asnumpy(), [1.0, 1.0])


def test_run_elastic_resumes(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    crashed = {"done": False}
    trained_epochs = []

    def train_fn(start_epoch, manager):
        for epoch in range(start_epoch, 6):
            if epoch == 3 and not crashed["done"]:
                crashed["done"] = True
                raise RuntimeError("injected failure")
            trained_epochs.append(epoch)
            manager.save(epoch, params={"w": mx.nd.full((1,), float(epoch))})
        return "finished"

    # restart_delay=0: the backoff schedule has its own test
    # (test_resilience.test_run_elastic_backoff_schedule)
    assert elastic.run_elastic(train_fn, cm, max_restarts=2,
                               restart_delay=0) == "finished"
    # epochs 0-2 trained, crash, resume from 3 (last committed was 2)
    assert trained_epochs == [0, 1, 2, 3, 4, 5]
    assert cm.latest_epoch() == 5


def test_run_elastic_gives_up(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))

    def always_fail(start_epoch, manager):
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        elastic.run_elastic(always_fail, cm, max_restarts=2,
                            restart_delay=0)


def test_dead_nodes_single_process():
    # no distributed runtime: nothing to detect, API still answers
    assert elastic.get_dead_nodes() == []
    assert elastic.start_heartbeat() is False
    kv = mx.kvstore.create("dist_sync")
    assert kv.get_dead_nodes() == []


def test_manifest_metadata(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path))
    path = cm.save(2, params={"w": mx.nd.ones((1,))},
                   metadata={"lr": 0.01, "step": 1234})
    with open(path) as f:
        manifest = json.load(f)
    assert manifest["epoch"] == 2
    assert manifest["metadata"]["step"] == 1234
    assert os.path.isfile(os.path.join(str(tmp_path), manifest["files"]["params"]))


def test_async_save_ordered_and_joined(tmp_path):
    """async_save rides the host engine: writes stay ordered per manager,
    latest_epoch()/wait() join them, and the snapshot is taken at call time
    (later mutations don't leak into the checkpoint)."""
    cm = elastic.CheckpointManager(str(tmp_path))
    w = mx.nd.full((3,), 1.0)
    cm.save(0, params={"w": w}, async_save=True)
    w[:] = 999.0  # mutate AFTER the async save snapshotted
    for e in range(1, 4):
        cm.save(e, params={"w": mx.nd.full((3,), float(e))}, async_save=True)
    assert cm.latest_epoch() == 3  # joins all pending writes
    np.testing.assert_allclose(cm.load_params(0)["w"].asnumpy(), [1.0] * 3)
    np.testing.assert_allclose(cm.load_params(3)["w"].asnumpy(), [3.0] * 3)


def test_async_save_failure_surfaces_at_wait(tmp_path):
    cm = elastic.CheckpointManager(str(tmp_path / "sub"))
    import os
    import shutil

    shutil.rmtree(str(tmp_path / "sub"))  # make the write fail
    cm.save(0, params={"w": mx.nd.ones((2,))}, async_save=True)
    with pytest.raises(Exception):
        cm.wait()


def test_async_save_snapshots_trainer_state(tmp_path):
    """Optimizer state is serialized at save() time, not later on the
    engine thread — a post-save trainer.step must not leak in."""
    net = _make_net(3)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = L2Loss()
    X, Y = mx.nd.ones((4, 4)), mx.nd.ones((4, 1))
    with mx.autograd.record():
        loss = loss_fn(net(X), Y)
    loss.backward()
    trainer.step(4)  # momentum now nonzero
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save(0, net=net, trainer=trainer, async_save=True)
    # mutate AFTER the async save: another step changes momentum
    with mx.autograd.record():
        loss = loss_fn(net(X), Y)
    loss.backward()
    trainer.step(4)
    cm.wait()
    # restoring must reproduce the state AT save time: roll a fresh
    # net/trainer forward one step from the checkpoint and compare with
    # rolling the original from its post-save state — they must differ,
    # while double-restore determinism must hold
    net2 = _make_net(4)
    t2 = Trainer(net2.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        loss = loss_fn(net2(X), Y)
    loss.backward()
    t2.step(4)  # materialize updater states before load
    assert cm.restore(net=net2, trainer=t2) == 0
    states = t2._updaters[0].get_states(dump_optimizer=False)
    net3 = _make_net(5)
    t3 = Trainer(net3.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    with mx.autograd.record():
        loss = loss_fn(net3(X), Y)
    loss.backward()
    t3.step(4)
    cm.restore(net=net3, trainer=t3)
    assert t3._updaters[0].get_states(dump_optimizer=False) == states
