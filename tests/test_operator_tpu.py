"""Re-run the operator suite under the TPU context.

The reference's ``tests/python/gpu/test_operator_gpu.py`` imports the whole
CPU operator suite and re-executes it with a GPU default context — the
same-suite-multiple-backends pattern SURVEY §4.2 calls out as worth
copying. This module does exactly that for TPU: when a non-CPU jax device
is visible (real hardware; the CI mesh forces CPU and skips), every test
function from tests/test_operator.py runs again inside ``with mx.tpu():``.
"""
import inspect

import jax
import pytest

import mxnet_tpu as mx

_ACCEL = [d for d in jax.devices() if d.platform != "cpu"]

pytestmark = pytest.mark.skipif(
    not _ACCEL, reason="no TPU device visible (CPU test mesh)")


def _op_test_functions():
    from tests import test_operator as mod

    out = []
    for name in dir(mod):
        if not name.startswith("test_"):
            continue
        fn = getattr(mod, name)
        if callable(fn) and not inspect.signature(fn).parameters:
            out.append((name, fn))
    return out


try:
    _CASES = _op_test_functions()
except ImportError:  # tests not importable as a package: fall back
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "test_operator_cpu_suite",
        pathlib.Path(__file__).parent / "test_operator.py")
    _mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(_mod)
    _CASES = [(n, getattr(_mod, n)) for n in dir(_mod)
              if n.startswith("test_") and callable(getattr(_mod, n))
              and not inspect.signature(getattr(_mod, n)).parameters]


@pytest.mark.parametrize("name,fn", _CASES, ids=[n for n, _ in _CASES])
def test_operator_on_tpu(name, fn):
    with mx.tpu():
        assert mx.current_context().device_type in ("tpu", "gpu")
        fn()
