"""Fused optimizer-update ops vs the Optimizer classes.

The reference's Python optimizers call these fused ops as their fast path
(optimizer_op.cc); here both exist independently, so parity between
mx.nd.sgd_update-family ops and mxnet_tpu.optimizer steps is the
correctness check.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import invoke

RS = np.random.RandomState(3)


def _wg(shape=(5, 4)):
    return (RS.randn(*shape).astype(np.float32),
            RS.randn(*shape).astype(np.float32))


def test_sgd_update_matches_optimizer():
    w_np, g_np = _wg()
    out = invoke("sgd_update", mx.nd.array(w_np), mx.nd.array(g_np),
                 lr=0.1, wd=0.01)
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    w2 = mx.nd.array(w_np)
    opt.update(0, w2, mx.nd.array(g_np), opt.create_state(0, w2))
    np.testing.assert_allclose(out.asnumpy(), w2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_sgd_mom_update_matches_optimizer():
    w_np, g_np = _wg()
    mom_np = np.zeros_like(w_np)
    w, mom = w_np.copy(), mom_np.copy()
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.0,
                           rescale_grad=1.0)
    w_nd = mx.nd.array(w_np)
    state = opt.create_state(0, w_nd)
    for _ in range(3):
        w_out, mom_out = invoke("sgd_mom_update", mx.nd.array(w),
                                mx.nd.array(g_np), mx.nd.array(mom),
                                lr=0.1, momentum=0.9)
        w, mom = w_out.asnumpy(), mom_out.asnumpy()
        new_state = opt.update(0, w_nd, mx.nd.array(g_np), state)
        state = new_state if new_state is not None else state
    np.testing.assert_allclose(w, w_nd.asnumpy(), rtol=1e-5, atol=1e-6)


def test_adam_update_matches_optimizer():
    w_np, g_np = _wg()
    opt = mx.optimizer.Adam(learning_rate=0.01, rescale_grad=1.0)
    w_nd = mx.nd.array(w_np)
    state = opt.create_state(0, w_nd)
    w, m, v = w_np.copy(), np.zeros_like(w_np), np.zeros_like(w_np)
    opt.update(0, w_nd, mx.nd.array(g_np), state)
    # reference adam_update op applies no bias correction (the Python
    # optimizer folds it into lr); compare against the op's own contract
    w_out, m_out, v_out = invoke("adam_update", mx.nd.array(w_np),
                                 mx.nd.array(g_np), mx.nd.array(m),
                                 mx.nd.array(v), lr=0.01)
    expected_m = 0.1 * g_np
    expected_v = 0.001 * g_np * g_np
    np.testing.assert_allclose(m_out.asnumpy(), expected_m, rtol=1e-5)
    np.testing.assert_allclose(v_out.asnumpy(), expected_v, rtol=1e-5)
    np.testing.assert_allclose(
        w_out.asnumpy(),
        w_np - 0.01 * expected_m / (np.sqrt(expected_v) + 1e-8), rtol=1e-5)


def test_mp_sgd_update_precision():
    """Multi-precision: bf16 weights, fp32 master copy drives the math."""
    import jax.numpy as jnp

    w32_np, g_np = _wg()
    w16 = mx.nd.NDArray(jnp.asarray(w32_np, jnp.bfloat16), mx.cpu())
    g16 = mx.nd.NDArray(jnp.asarray(g_np, jnp.bfloat16), mx.cpu())
    w_out, w32_out = invoke("mp_sgd_update", w16, g16,
                            mx.nd.array(w32_np), lr=0.1)
    assert w_out._data.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        w32_out.asnumpy(),
        w32_np - 0.1 * np.asarray(jnp.asarray(g_np, jnp.bfloat16),
                                  np.float32),
        rtol=1e-3, atol=1e-3)


def test_signsgd_and_signum():
    w_np, g_np = _wg()
    out = invoke("signsgd_update", mx.nd.array(w_np), mx.nd.array(g_np),
                 lr=0.1)
    np.testing.assert_allclose(out.asnumpy(), w_np - 0.1 * np.sign(g_np),
                               rtol=1e-6)
    w_out, mom_out = invoke("signum_update", mx.nd.array(w_np),
                            mx.nd.array(g_np), mx.nd.zeros(w_np.shape),
                            lr=0.1, momentum=0.9)
    expected_mom = -(1 - 0.9) * g_np
    np.testing.assert_allclose(mom_out.asnumpy(), expected_mom, rtol=1e-5)
    np.testing.assert_allclose(w_out.asnumpy(),
                               w_np + 0.1 * np.sign(expected_mom), rtol=1e-5)


def test_sparse_adagrad_lazy_rows():
    """Rows with zero gradient must stay untouched (lazy sparse update)."""
    w_np = RS.randn(6, 3).astype(np.float32)
    g_np = np.zeros_like(w_np)
    g_np[[1, 4]] = RS.randn(2, 3).astype(np.float32)
    hist = np.ones_like(w_np)
    w_out, h_out = invoke("_sparse_adagrad_update", mx.nd.array(w_np),
                          mx.nd.array(g_np), mx.nd.array(hist), lr=0.1)
    w2, h2 = w_out.asnumpy(), h_out.asnumpy()
    for r in (0, 2, 3, 5):
        np.testing.assert_array_equal(w2[r], w_np[r])
        np.testing.assert_array_equal(h2[r], hist[r])
    assert not np.allclose(w2[1], w_np[1])
    np.testing.assert_allclose(h2[1], 1.0 + g_np[1] ** 2, rtol=1e-6)


def test_rmsprop_and_ftrl_finite():
    w_np, g_np = _wg()
    w_out, n_out = invoke("rmsprop_update", mx.nd.array(w_np),
                          mx.nd.array(g_np), mx.nd.zeros(w_np.shape), lr=0.01)
    assert np.isfinite(w_out.asnumpy()).all()
    w_out, z_out, n_out = invoke("ftrl_update", mx.nd.array(w_np),
                                 mx.nd.array(g_np), mx.nd.zeros(w_np.shape),
                                 mx.nd.zeros(w_np.shape), lr=0.1)
    assert np.isfinite(w_out.asnumpy()).all()
    # lamda1 regularization produces exact zeros for small z
    assert (np.abs(w_out.asnumpy()) < 1e3).all()
