"""Fused optimizer-update ops vs the Optimizer classes.

The reference's Python optimizers call these fused ops as their fast path
(optimizer_op.cc); here both exist independently, so parity between
mx.nd.sgd_update-family ops and mxnet_tpu.optimizer steps is the
correctness check.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import invoke

RS = np.random.RandomState(3)


def _wg(shape=(5, 4)):
    return (RS.randn(*shape).astype(np.float32),
            RS.randn(*shape).astype(np.float32))


def test_sgd_update_matches_optimizer():
    w_np, g_np = _wg()
    out = invoke("sgd_update", mx.nd.array(w_np), mx.nd.array(g_np),
                 lr=0.1, wd=0.01)
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    w2 = mx.nd.array(w_np)
    opt.update(0, w2, mx.nd.array(g_np), opt.create_state(0, w2))
    np.testing.assert_allclose(out.asnumpy(), w2.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_sgd_mom_update_matches_optimizer():
    w_np, g_np = _wg()
    mom_np = np.zeros_like(w_np)
    w, mom = w_np.copy(), mom_np.copy()
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.0,
                           rescale_grad=1.0)
    w_nd = mx.nd.array(w_np)
    state = opt.create_state(0, w_nd)
    for _ in range(3):
        w_out, mom_out = invoke("sgd_mom_update", mx.nd.array(w),
                                mx.nd.array(g_np), mx.nd.array(mom),
                                lr=0.1, momentum=0.9)
        w, mom = w_out.asnumpy(), mom_out.asnumpy()
        new_state = opt.update(0, w_nd, mx.nd.array(g_np), state)
        state = new_state if new_state is not None else state
    np.testing.assert_allclose(w, w_nd.asnumpy(), rtol=1e-5, atol=1e-6)


def test_adam_update_matches_optimizer():
    w_np, g_np = _wg()
    opt = mx.optimizer.Adam(learning_rate=0.01, rescale_grad=1.0)
    w_nd = mx.nd.array(w_np)
    state = opt.create_state(0, w_nd)
    w, m, v = w_np.copy(), np.zeros_like(w_np), np.zeros_like(w_np)
    opt.update(0, w_nd, mx.nd.array(g_np), state)
    # reference adam_update op applies no bias correction (the Python
    # optimizer folds it into lr); compare against the op's own contract
    w_out, m_out, v_out = invoke("adam_update", mx.nd.array(w_np),
                                 mx.nd.array(g_np), mx.nd.array(m),
                                 mx.nd.array(v), lr=0.01)
    expected_m = 0.1 * g_np
    expected_v = 0.001 * g_np * g_np
    np.testing.assert_allclose(m_out.asnumpy(), expected_m, rtol=1e-5)
    np.testing.assert_allclose(v_out.asnumpy(), expected_v, rtol=1e-5)
    np.testing.assert_allclose(
        w_out.asnumpy(),
        w_np - 0.01 * expected_m / (np.sqrt(expected_v) + 1e-8), rtol=1e-5)


def test_mp_sgd_update_precision():
    """Multi-precision: bf16 weights, fp32 master copy drives the math."""
    import jax.numpy as jnp

    w32_np, g_np = _wg()
    w16 = mx.nd.NDArray(jnp.asarray(w32_np, jnp.bfloat16), mx.cpu())
    g16 = mx.nd.NDArray(jnp.asarray(g_np, jnp.bfloat16), mx.cpu())
    w_out, w32_out = invoke("mp_sgd_update", w16, g16,
                            mx.nd.array(w32_np), lr=0.1)
    assert w_out._data.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        w32_out.asnumpy(),
        w32_np - 0.1 * np.asarray(jnp.asarray(g_np, jnp.bfloat16),
                                  np.float32),
        rtol=1e-3, atol=1e-3)


def test_signsgd_and_signum():
    w_np, g_np = _wg()
    out = invoke("signsgd_update", mx.nd.array(w_np), mx.nd.array(g_np),
                 lr=0.1)
    np.testing.assert_allclose(out.asnumpy(), w_np - 0.1 * np.sign(g_np),
                               rtol=1e-6)
    w_out, mom_out = invoke("signum_update", mx.nd.array(w_np),
                            mx.nd.array(g_np), mx.nd.zeros(w_np.shape),
                            lr=0.1, momentum=0.9)
    expected_mom = -(1 - 0.9) * g_np
    np.testing.assert_allclose(mom_out.asnumpy(), expected_mom, rtol=1e-5)
    np.testing.assert_allclose(w_out.asnumpy(),
                               w_np + 0.1 * np.sign(expected_mom), rtol=1e-5)


def test_sparse_adagrad_lazy_rows():
    """Rows with zero gradient must stay untouched (lazy sparse update)."""
    w_np = RS.randn(6, 3).astype(np.float32)
    g_np = np.zeros_like(w_np)
    g_np[[1, 4]] = RS.randn(2, 3).astype(np.float32)
    hist = np.ones_like(w_np)
    w_out, h_out = invoke("_sparse_adagrad_update", mx.nd.array(w_np),
                          mx.nd.array(g_np), mx.nd.array(hist), lr=0.1)
    w2, h2 = w_out.asnumpy(), h_out.asnumpy()
    for r in (0, 2, 3, 5):
        np.testing.assert_array_equal(w2[r], w_np[r])
        np.testing.assert_array_equal(h2[r], hist[r])
    assert not np.allclose(w2[1], w_np[1])
    np.testing.assert_allclose(h2[1], 1.0 + g_np[1] ** 2, rtol=1e-6)


def test_rmsprop_and_ftrl_finite():
    w_np, g_np = _wg()
    w_out, n_out = invoke("rmsprop_update", mx.nd.array(w_np),
                          mx.nd.array(g_np), mx.nd.zeros(w_np.shape), lr=0.01)
    assert np.isfinite(w_out.asnumpy()).all()
    w_out, z_out, n_out = invoke("ftrl_update", mx.nd.array(w_np),
                                 mx.nd.array(g_np), mx.nd.zeros(w_np.shape),
                                 mx.nd.zeros(w_np.shape), lr=0.1)
    assert np.isfinite(w_out.asnumpy()).all()
    # lamda1 regularization produces exact zeros for small z
    assert (np.abs(w_out.asnumpy()) < 1e3).all()


def test_clip_wd_ordering():
    """adam/ftml/rmsprop/rmspropalex clip AFTER folding in wd*weight
    (reference optimizer_op-inl.h AdamUpdate ~:858, FTMLKernel :761,
    RMSProp kernels ~:1157-1260); the sgd family clips the bare gradient.
    With clip small and wd*|w| large the two orderings differ measurably."""
    w_np = np.full((3, 2), 10.0, np.float32)
    g_np = np.full((3, 2), 0.5, np.float32)
    clip, wd, lr = 0.1, 1.0, 0.01

    # adam: g = clip(grad + wd*w) = clip(0.5 + 10) = 0.1 everywhere
    g_eff = np.clip(g_np + wd * w_np, -clip, clip)
    m = (1 - 0.9) * g_eff
    v = (1 - 0.999) * g_eff * g_eff
    expect = w_np - lr * m / (np.sqrt(v) + 1e-8)
    w_out, m_out, v_out = invoke(
        "adam_update", mx.nd.array(w_np), mx.nd.array(g_np),
        mx.nd.zeros(w_np.shape), mx.nd.zeros(w_np.shape),
        lr=lr, wd=wd, clip_gradient=clip)
    np.testing.assert_allclose(w_out.asnumpy(), expect, rtol=1e-6)
    np.testing.assert_allclose(m_out.asnumpy(), m, rtol=1e-6)

    # rmsprop: same prologue
    n = (1 - 0.95) * g_eff * g_eff
    expect = w_np - lr * g_eff / np.sqrt(n + 1e-8)
    w_out, _ = invoke("rmsprop_update", mx.nd.array(w_np), mx.nd.array(g_np),
                      mx.nd.zeros(w_np.shape), lr=lr, wd=wd,
                      clip_gradient=clip)
    np.testing.assert_allclose(w_out.asnumpy(), expect, rtol=1e-5)

    # sgd clips the bare grad, wd applied outside: g=clip(0.5)=0.1,
    # step = lr*(0.1 + wd*10)
    expect = w_np - lr * (np.clip(g_np, -clip, clip) + wd * w_np)
    out = invoke("sgd_update", mx.nd.array(w_np), mx.nd.array(g_np),
                 lr=lr, wd=wd, clip_gradient=clip)
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)


def test_optimizer_class_clip_wd_ordering():
    """The Optimizer classes mirror the kernel ordering: Adam folds wd
    before clip; AdaGrad/AdaDelta keep wd out of the gradient statistics
    entirely (reference optimizer.py :1105-1108, AdaDelta update)."""
    from mxnet_tpu import optimizer as opt
    w_np = np.full((4,), 10.0, np.float32)
    g_np = np.full((4,), 0.5, np.float32)
    clip, wd, lr = 0.1, 1.0, 0.01

    adam = opt.Adam(learning_rate=lr, wd=wd, clip_gradient=clip)
    w = mx.nd.array(w_np)
    st = adam.create_state(0, w)
    st = adam.update(0, w, mx.nd.array(g_np), st)
    g_eff = np.clip(g_np + wd * w_np, -clip, clip)   # = 0.1
    m = 0.1 * g_eff
    v = 0.001 * g_eff * g_eff
    lr_t = lr * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = w_np - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-6)

    # AdaGrad: history uses clip(bare grad); wd applied at the update
    ada = opt.AdaGrad(learning_rate=lr, wd=wd, clip_gradient=clip)
    w = mx.nd.array(w_np)
    st = ada.create_state(0, w)
    st = ada.update(0, w, mx.nd.array(g_np), st)
    g_eff = np.clip(g_np, -clip, clip)
    h = g_eff * g_eff
    expect = w_np - lr * (g_eff / np.sqrt(h + 1e-7) + wd * w_np)
    np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st), h, rtol=1e-6)


def test_remaining_update_ops_finite_and_consistent():
    """ftml_update / rmspropalex_update / mp_sgd_mom_update: one step each,
    finite outputs and hand-computed first-step values."""
    w_np, g_np = _wg()
    z = np.zeros_like(w_np)

    w1, d1, v1, z1 = invoke("ftml_update", mx.nd.array(w_np),
                            mx.nd.array(g_np), mx.nd.array(z),
                            mx.nd.array(z), mx.nd.array(z), lr=0.1, t=1)
    for o in (w1, d1, v1, z1):
        assert np.isfinite(o.asnumpy()).all()
    # first step: v = (1-b2) g^2; d = (1-b1)/lr (sqrt(v/(1-b2)) + eps)
    v_e = (1 - 0.999) * g_np ** 2
    d_e = (1 - 0.6) / 0.1 * (np.sqrt(v_e / (1 - 0.999)) + 1e-8)
    np.testing.assert_allclose(v1.asnumpy(), v_e, rtol=1e-5)
    np.testing.assert_allclose(d1.asnumpy(), d_e, rtol=1e-5)

    w1, n1, g1, dl1 = invoke("rmspropalex_update", mx.nd.array(w_np),
                             mx.nd.array(g_np), mx.nd.array(z),
                             mx.nd.array(z), mx.nd.array(z), lr=0.1)
    n_e = (1 - 0.95) * g_np ** 2
    g_e = (1 - 0.95) * g_np
    dl_e = -0.1 * g_np / np.sqrt(n_e - g_e ** 2 + 1e-8)
    np.testing.assert_allclose(n1.asnumpy(), n_e, rtol=1e-5)
    np.testing.assert_allclose(w1.asnumpy(), w_np + dl_e, rtol=1e-4)

    w16 = w_np.astype(np.float16)
    w1, m1, w32 = invoke("mp_sgd_mom_update", mx.nd.array(w16),
                         mx.nd.array(g_np.astype(np.float16)),
                         mx.nd.array(z), mx.nd.array(w_np),
                         lr=0.1, momentum=0.9)
    np.testing.assert_allclose(w32.asnumpy(), w_np - 0.1 * g_np, rtol=1e-3)
    assert w1.asnumpy().dtype == np.float16
