"""Operator correctness tests vs numpy + finite-difference gradients
(modeled on reference tests/python/unittest/test_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def _rnd(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


# ---------------------------------------------------------------- elemwise
def test_unary_math_vs_numpy():
    x = np.random.uniform(0.1, 2.0, (3, 4)).astype(np.float32)
    nd = mx.nd.array(x)
    for name, npf in [
        ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt), ("square", np.square),
        ("abs", np.abs), ("floor", np.floor), ("ceil", np.ceil),
        ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
        ("log1p", np.log1p), ("expm1", np.expm1), ("rsqrt", lambda v: 1 / np.sqrt(v)),
        ("reciprocal", lambda v: 1 / v), ("cbrt", np.cbrt),
    ]:
        assert_almost_equal(getattr(mx.nd, name)(nd), npf(x), rtol=1e-4, atol=1e-5, names=(name, "np"))


def test_activation_ops():
    x = _rnd(4, 5)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.relu(nd), np.maximum(x, 0))
    assert_almost_equal(mx.nd.Activation(nd, act_type="relu"), np.maximum(x, 0))
    assert_almost_equal(mx.nd.Activation(nd, act_type="sigmoid"), 1 / (1 + np.exp(-x)), rtol=1e-5, atol=1e-6)
    assert_almost_equal(mx.nd.LeakyReLU(nd, act_type="leaky", slope=0.1), np.where(x > 0, x, 0.1 * x))
    elu = mx.nd.LeakyReLU(nd, act_type="elu", slope=1.0)
    assert_almost_equal(elu, np.where(x > 0, x, np.expm1(x)), rtol=1e-5, atol=1e-6)


def test_fully_connected():
    x, w, b = _rnd(5, 3), _rnd(4, 3), _rnd(4)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b), num_hidden=4)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4, atol=1e-5)
    out2 = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), num_hidden=4, no_bias=True)
    assert_almost_equal(out2, x @ w.T, rtol=1e-4, atol=1e-5)
    # 4D input flattens
    x4 = _rnd(2, 3, 2, 2)
    w4 = _rnd(4, 12)
    out3 = mx.nd.FullyConnected(mx.nd.array(x4), mx.nd.array(w4), num_hidden=4, no_bias=True)
    assert_almost_equal(out3, x4.reshape(2, -1) @ w4.T, rtol=1e-4, atol=1e-5)
    check_numeric_gradient(
        lambda a, ww: mx.nd.FullyConnected(a, ww, num_hidden=4, no_bias=True),
        [_rnd(3, 3), _rnd(4, 3)],
    )


def test_convolution_vs_naive():
    # compare against explicit correlation
    x = _rnd(1, 2, 5, 5)
    w = _rnd(3, 2, 3, 3)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3), num_filter=3, no_bias=True)
    ref = np.zeros((1, 3, 3, 3), dtype=np.float32)
    for o in range(3):
        for i in range(3):
            for j in range(3):
                ref[0, o, i, j] = np.sum(x[0, :, i:i + 3, j:j + 3] * w[o])
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_convolution_grad():
    check_numeric_gradient(
        lambda d, w: mx.nd.Convolution(d, w, kernel=(3, 3), num_filter=2, pad=(1, 1), no_bias=True),
        [_rnd(1, 2, 4, 4), _rnd(2, 2, 3, 3)],
        rtol=2e-2, atol=1e-3,
    )


def test_conv_stride_pad_group():
    x = _rnd(2, 4, 8, 8)
    w = _rnd(6, 2, 3, 3)
    out = mx.nd.Convolution(
        mx.nd.array(x), mx.nd.array(w), kernel=(3, 3), num_filter=6, stride=(2, 2), pad=(1, 1), num_group=2, no_bias=True
    )
    assert out.shape == (2, 6, 4, 4)


def test_deconvolution():
    x = _rnd(1, 2, 4, 4)
    w = _rnd(2, 3, 3, 3)  # (in, out, kh, kw)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3), num_filter=3, stride=(2, 2))
    assert out.shape == (1, 3, 9, 9)
    # deconv is adjoint of conv: <conv(y), x> == <deconv(x), y>.
    # The deconv weight (in=2, out=3, kh, kw) is exactly the weight of the
    # adjoint conv (1,3,9,9)->(1,2,4,4) whose layout is (out=2, in=3, kh, kw).
    y = _rnd(1, 3, 9, 9)
    conv = mx.nd.Convolution(mx.nd.array(y), mx.nd.array(w),
                             kernel=(3, 3), num_filter=2, stride=(2, 2), no_bias=True)
    lhs = float((conv.asnumpy() * x).sum())
    rhs = float((out.asnumpy() * y).sum())
    assert lhs == pytest.approx(rhs, rel=1e-3)


def test_pooling():
    x = _rnd(2, 3, 6, 6)
    mxp = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert mxp.shape == (2, 3, 3, 3)
    ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(mxp, ref)
    avg = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert_almost_equal(avg, x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5)), rtol=1e-5, atol=1e-6)
    gp = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="max")
    assert gp.shape == (2, 3, 1, 1)
    assert_almost_equal(gp.asnumpy().reshape(2, 3), x.max(axis=(2, 3)))


def test_batchnorm_train_eval():
    x = _rnd(4, 3, 5, 5)
    gamma, beta = np.ones(3, np.float32), np.zeros(3, np.float32)
    mm, mv = np.zeros(3, np.float32), np.ones(3, np.float32)
    args = [mx.nd.array(v) for v in (x, gamma, beta, mm, mv)]
    with mx.autograd.record():  # train mode: use batch stats
        out = mx.nd.BatchNorm(*args, fix_gamma=False, eps=1e-5)[0]
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-5)
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)
    # eval mode: use moving stats
    out_eval = mx.nd.BatchNorm(*args, fix_gamma=False, eps=1e-5)[0]
    assert_almost_equal(out_eval, x / np.sqrt(1 + 1e-5), rtol=1e-4, atol=1e-5)


def test_layernorm():
    x = _rnd(4, 10)
    g, b = np.random.rand(10).astype(np.float32), _rnd(10)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b), eps=1e-5)[0]
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) * g + b
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_softmax_family():
    x = _rnd(3, 5)
    nd = mx.nd.array(x)
    sm = mx.nd.softmax(nd).asnumpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    assert_almost_equal(sm, e / e.sum(-1, keepdims=True), rtol=1e-5, atol=1e-6)
    assert_almost_equal(mx.nd.log_softmax(nd), np.log(e / e.sum(-1, keepdims=True)), rtol=1e-4, atol=1e-5)
    assert_almost_equal(mx.nd.softmin(nd).asnumpy().sum(-1), np.ones(3), rtol=1e-5, atol=1e-6)


def test_softmax_output_grad():
    x = _rnd(4, 5)
    label = np.array([0, 2, 1, 4], dtype=np.float32)
    nd = mx.nd.array(x)
    nd.attach_grad()
    with mx.autograd.record():
        out = mx.nd.SoftmaxOutput(nd, mx.nd.array(label))
    out.backward()
    e = np.exp(x - x.max(-1, keepdims=True))
    prob = e / e.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    assert_almost_equal(nd.grad, prob - onehot, rtol=1e-4, atol=1e-5)


def test_dropout():
    x = mx.nd.ones((100, 100))
    with mx.autograd.record():
        y = mx.nd.Dropout(x, p=0.5)
    kept = (y.asnumpy() != 0).mean()
    assert 0.4 < kept < 0.6
    assert_almost_equal(y.asnumpy()[y.asnumpy() != 0], 2.0 * np.ones((y.asnumpy() != 0).sum()))
    # eval mode: identity
    y_eval = mx.nd.Dropout(x, p=0.5)
    assert_almost_equal(y_eval, x.asnumpy())


def test_reshape_special_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert mx.nd.Reshape(a, shape=(0, -1)).shape == (2, 12)
    assert mx.nd.Reshape(a, shape=(-2,)).shape == (2, 3, 4)
    assert mx.nd.Reshape(a, shape=(-3, 0)).shape == (6, 4)
    assert mx.nd.Reshape(a, shape=(0, 0, -4, 2, 2)).shape == (2, 3, 2, 2)


def test_embedding_grad_dense():
    w = _rnd(10, 4)
    idx = np.array([1, 3, 1], dtype=np.float32)
    wnd = mx.nd.array(w)
    wnd.attach_grad()
    with mx.autograd.record():
        out = mx.nd.Embedding(mx.nd.array(idx), wnd, input_dim=10, output_dim=4)
        loss = out.sum()
    loss.backward()
    ref = np.zeros_like(w)
    for i in idx.astype(int):
        ref[i] += 1
    assert_almost_equal(wnd.grad, ref)


def test_rnn_lstm_shapes():
    T, B, I, H, L = 5, 3, 4, 6, 2
    from mxnet_tpu.ops.nn import rnn_param_size

    psize = rnn_param_size("lstm", I, H, L)
    params = mx.nd.array(np.random.uniform(-0.1, 0.1, (psize,)).astype(np.float32))
    data = mx.nd.array(_rnd(T, B, I))
    h0 = mx.nd.zeros((L, B, H))
    c0 = mx.nd.zeros((L, B, H))
    out = mx.nd.RNN(data, params, h0, c0, state_size=H, num_layers=L, mode="lstm", state_outputs=True)
    assert out[0].shape == (T, B, H)
    assert out[1].shape == (L, B, H)
    assert out[2].shape == (L, B, H)


def test_rnn_gru_bidirectional():
    T, B, I, H = 4, 2, 3, 5
    from mxnet_tpu.ops.nn import rnn_param_size

    psize = rnn_param_size("gru", I, H, 1, bidirectional=True)
    params = mx.nd.array(np.random.uniform(-0.1, 0.1, (psize,)).astype(np.float32))
    out = mx.nd.RNN(
        mx.nd.array(_rnd(T, B, I)), params, mx.nd.zeros((2, B, H)),
        state_size=H, num_layers=1, mode="gru", bidirectional=True,
    )
    assert out.shape == (T, B, 2 * H)


def test_sequence_ops():
    data = mx.nd.array(np.arange(24, dtype=np.float32).reshape(4, 2, 3))
    seq_len = mx.nd.array([2, 4])
    masked = mx.nd.SequenceMask(data, seq_len, use_sequence_length=True, value=-1.0)
    mn = masked.asnumpy()
    assert (mn[2:, 0] == -1).all() and (mn[:, 1] != -1).all()
    last = mx.nd.SequenceLast(data, seq_len, use_sequence_length=True)
    assert_almost_equal(last, data.asnumpy()[[1, 3], [0, 1]])
    rev = mx.nd.SequenceReverse(data, seq_len, use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], data.asnumpy()[1, 0])


def test_linalg_ops():
    a = _rnd(3, 4)
    b = _rnd(4, 5)
    c = _rnd(3, 5)
    out = mx.nd.linalg_gemm(mx.nd.array(a), mx.nd.array(b), mx.nd.array(c), alpha=2.0, beta=0.5)
    assert_almost_equal(out, 2 * (a @ b) + 0.5 * c, rtol=1e-4, atol=1e-5)
    spd = np.eye(4, dtype=np.float32) * 3 + 0.1
    L = mx.nd.linalg_potrf(mx.nd.array(spd))
    assert_almost_equal(L.asnumpy() @ L.asnumpy().T, spd, rtol=1e-4, atol=1e-4)
    sld = mx.nd.linalg_sumlogdiag(mx.nd.array(np.eye(3, dtype=np.float32) * 2))
    assert float(sld.asscalar()) == pytest.approx(3 * np.log(2), rel=1e-4)


def test_regression_outputs():
    x = _rnd(4, 3)
    label = _rnd(4, 3)
    nd = mx.nd.array(x)
    nd.attach_grad()
    with mx.autograd.record():
        out = mx.nd.LinearRegressionOutput(nd, mx.nd.array(label))
    out.backward()
    assert_almost_equal(out, x)
    assert_almost_equal(nd.grad, (x - label) / 3, rtol=1e-4, atol=1e-5)


def test_grad_of_grad_ops():
    # numeric gradient checks across a sample of op families
    check_numeric_gradient(lambda x: mx.nd.softmax(x), [_rnd(3, 4)])
    check_numeric_gradient(lambda x: mx.nd.LayerNorm(x, mx.nd.ones((4,)), mx.nd.zeros((4,)))[0], [_rnd(3, 4)], rtol=2e-2, atol=1e-3)
    check_numeric_gradient(lambda x: mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg"), [_rnd(1, 2, 4, 4)])
    check_numeric_gradient(lambda x: mx.nd.sum(x, axis=1), [_rnd(3, 4)])
    mult = mx.nd.array(_rnd(1, 4))
    check_numeric_gradient(lambda x: mx.nd.broadcast_mul(x, mult), [_rnd(3, 4)])


def test_random_samplers():
    g = mx.nd.random.gamma(2.0, 2.0, shape=(2000,)).asnumpy()
    assert g.mean() == pytest.approx(4.0, rel=0.2)
    p = mx.nd.random.poisson(3.0, shape=(2000,)).asnumpy()
    assert p.mean() == pytest.approx(3.0, rel=0.2)
    m = mx.nd.random.multinomial(mx.nd.array([[0.0, 0.0, 1.0]]), shape=(50,)).asnumpy()
    assert (m == 2).all()
    s = mx.nd.random.shuffle(mx.nd.arange(0, 10))
    assert sorted(s.asnumpy().tolist()) == list(range(10))


def test_dot_ndim_and_transpose():
    a = mx.nd.ones((3, 4, 5))
    b = mx.nd.ones((5, 6))
    assert mx.nd.dot(a, b).shape == (3, 4, 6)
    x = _rnd(4, 3)
    y = _rnd(4, 5)
    assert_almost_equal(mx.nd.dot(mx.nd.array(x), mx.nd.array(y), transpose_a=True),
                        x.T @ y, rtol=1e-4, atol=1e-5)


def test_softmax_output_label_smoothing():
    x = _rnd(2, 4)
    label = np.array([1, 3], dtype=np.float32)
    nd = mx.nd.array(x)
    nd.attach_grad()
    with mx.autograd.record():
        out = mx.nd.SoftmaxOutput(nd, mx.nd.array(label), smooth_alpha=0.1)
    out.backward()
    e = np.exp(x - x.max(-1, keepdims=True))
    prob = e / e.sum(-1, keepdims=True)
    onehot = np.eye(4, dtype=np.float32)[label.astype(int)]
    target = onehot * 0.9 + (1 - onehot) * (0.1 / 3)
    assert_almost_equal(nd.grad, prob - target, rtol=1e-4, atol=1e-5)


def test_svm_output_grad():
    x = np.array([[0.5, -2.0, 3.0]], dtype=np.float32)
    label = np.array([0], dtype=np.float32)
    nd = mx.nd.array(x)
    nd.attach_grad()
    with mx.autograd.record():
        out = mx.nd.SVMOutput(nd, mx.nd.array(label), margin=1.0, use_linear=True)
    out.backward()
    # L1 SVM: target col 0: -(1 > 0.5) = -1; col1: (1 > 2.0)=0; col2: (1 > -3)=1
    assert_almost_equal(nd.grad, [[-1.0, 0.0, 1.0]])
    nd.grad[:] = 0
    with mx.autograd.record():
        out = mx.nd.SVMOutput(nd, mx.nd.array(label), margin=1.0)
    out.backward()
    # L2: col0: -2*max(0,1-0.5)=-1; col1: 2*max(0,1-2)=0; col2: 2*max(0,1+3)=8
    assert_almost_equal(nd.grad, [[-1.0, 0.0, 8.0]])


def test_makediag_offset():
    x = mx.nd.array([1.0, 2.0, 3.0])
    d = mx.nd.linalg_makediag(x, offset=1)
    assert d.shape == (4, 4)
    assert_almost_equal(mx.nd.linalg_extractdiag(d, offset=1), [1, 2, 3])
    d0 = mx.nd.linalg_makediag(x)
    assert_almost_equal(d0, np.diag([1.0, 2.0, 3.0]))


def test_random_ctx_honored():
    u = mx.nd.random.uniform(0, 1, shape=(2,), ctx=mx.cpu())
    assert u.context.device_type == "cpu"


def test_registry_tail_ops():
    """Round-5 registry tail (misc_tail.py): div_sqrt_dim, quadratic,
    slice_assign, scatter-scalar storage preservation, image ops, aliases."""
    from mxnet_tpu.ndarray import sparse as mxs
    from mxnet_tpu.ndarray.ndarray import invoke
    from mxnet_tpu.ops.registry import OP_REGISTRY

    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 8).astype(np.float32)
    assert_almost_equal(invoke("_contrib_div_sqrt_dim", mx.nd.array(x)),
                        x / np.sqrt(8), rtol=1e-6)
    assert_almost_equal(
        invoke("_contrib_quadratic", mx.nd.array(x), a=2.0, b=-1.0, c=0.5),
        2 * x * x - x + 0.5, rtol=1e-5)

    a = rs.randn(5, 4).astype(np.float32)
    r = rs.randn(2, 4).astype(np.float32)
    e = a.copy()
    e[1:3] = r
    assert_almost_equal(invoke("_slice_assign", mx.nd.array(a),
                               mx.nd.array(r), begin=(1,), end=(3,)), e)
    e = a.copy()
    e[0:2] = 7
    assert_almost_equal(invoke("_slice_assign_scalar", mx.nd.array(a),
                               scalar=7.0, begin=(0,), end=(2,)), e)

    s0 = np.zeros((6, 2), np.float32)
    s0[[1, 4]] = rs.randn(2, 2)
    rsp = mxs.cast_storage(mx.nd.array(s0), "row_sparse")
    out = invoke("_scatter_plus_scalar", rsp, scalar=5.0)
    assert out.stype == "row_sparse"
    e = s0.copy()
    e[[1, 4]] += 5.0
    assert_almost_equal(out, e)
    out = invoke("_scatter_minus_scalar", rsp, scalar=5.0)
    assert out.stype == "row_sparse"
    e = s0.copy()
    e[[1, 4]] -= 5.0
    assert_almost_equal(out, e)
    div = invoke("_scatter_elemwise_div", rsp,
                 mx.nd.array(np.full((6, 2), 2.0, np.float32)))
    assert div.stype == "row_sparse"
    assert_almost_equal(div, s0 / 2.0)

    img = (rs.rand(10, 12, 3) * 255).astype(np.uint8)
    t = invoke("_image_to_tensor", mx.nd.array(img))
    assert t.shape == (3, 10, 12)
    norm = invoke("_image_normalize", t, mean=(0.4, 0.5, 0.6),
                  std=(0.2, 0.2, 0.2))
    e = (t.asnumpy() - np.array([0.4, 0.5, 0.6],
                                np.float32).reshape(3, 1, 1)) / 0.2
    assert_almost_equal(norm, e, rtol=1e-4, atol=1e-6)
    rz = invoke("_cvimresize", mx.nd.array(img), w=6, h=5)
    assert rz.shape == (5, 6, 3) and rz.asnumpy().dtype == np.uint8
    pad = invoke("_cvcopyMakeBorder", mx.nd.array(img), top=1, bot=2,
                 left=3, right=4)
    assert pad.shape == (13, 19, 3)

    from mxnet_tpu import image as im
    jpg = im.imencode(img)
    dec = invoke("_cvimdecode",
                 mx.nd.array(np.frombuffer(jpg, np.uint8).copy()))
    assert dec.shape[2] == 3 and dec.asnumpy().dtype == np.uint8

    # _cvimread: file-based decode with its reference signature
    import tempfile

    fn = tempfile.mktemp(suffix=".jpg")
    with open(fn, "wb") as f:
        f.write(jpg)
    rd = invoke("_cvimread", filename=fn)
    assert rd.shape[2] == 3 and rd.asnumpy().dtype == np.uint8

    # reflect border + step mismatch error
    bordered = invoke("_cvcopyMakeBorder", mx.nd.array(img), top=2, bot=0,
                      left=0, right=0, type=2)
    np.testing.assert_array_equal(bordered.asnumpy()[0], img[1])
    import pytest as _pytest
    from mxnet_tpu.base import MXNetError as _Err
    with _pytest.raises(_Err, match="lengths differ"):
        invoke("_slice_assign_scalar", mx.nd.array(a), scalar=1.0,
               begin=(1, 0), end=(3, 2), step=(1,))

    # dense lhs / sparse rhs divisor densifies (all rows stored => finite)
    dens = np.full((6, 2), 2.0, np.float32)
    sp_div = mxs.cast_storage(mx.nd.array(dens), "row_sparse")
    dl = invoke("_scatter_elemwise_div",
                mx.nd.array(np.ones((6, 2), np.float32)), sp_div)
    assert_almost_equal(dl, np.full((6, 2), 0.5, np.float32))

    for n in ("_copyto", "_CrossDeviceCopy", "_default_subgraph_op",
              "_cvimread"):
        assert n in OP_REGISTRY, n
