"""Seeded oom-masking bugs — fixture source for the tpulint pass tests.

``tests/test_tpulint.py::test_oom_masking_*`` lints this file under a
``mxnet_tpu/`` pseudo-path. Two seeded masks (a logged-and-defaulted
dispatch catch, an XlaRuntimeError retry loop) must fire; the routed,
re-raising and narrow handlers below them must not. Not imported at
runtime — pure fixture source.
"""
import logging

from mxnet_tpu import telemetry
from mxnet_tpu.resilience import hbm
from mxnet_tpu.serving.utils import fetch_host

_LOG = logging.getLogger(__name__)


# -- bug 1: dispatch OOM logged and defaulted --------------------------------
# the handler "handles" the failure locally: the governor never learns,
# admission re-runs at the size that just blew up.

def masked_step(fn, params, batch):
    try:
        return telemetry.jit_call("train.step", fn, params, batch)
    except Exception as exc:  # BUG: OOM masked — no classify, no re-raise
        _LOG.warning("step failed: %r", exc)
        return None


# -- bug 2: XlaRuntimeError swallowed around a transfer ----------------------

def masked_fetch(arrays, XlaRuntimeError):
    try:
        return fetch_host(arrays)
    except XlaRuntimeError:  # BUG: RESOURCE_EXHAUSTED retried blindly
        return fetch_host(arrays)


# -- clean: handler routes through the survival plane ------------------------

def surviving_step(fn, params, batch):
    try:
        return telemetry.jit_call("train.step", fn, params, batch)
    except Exception as exc:
        if not hbm.oom_survival("train.step", exc):
            raise
        return None


# -- clean: handler re-raises (an outer guarded layer classifies) ------------

def reraising_step(fn, params, batch):
    try:
        return telemetry.jit_call("train.step", fn, params, batch)
    except Exception as exc:
        _LOG.warning("step failed: %r", exc)
        raise


# -- clean: narrow catch cannot see an OOM -----------------------------------

def narrow_step(fn, params, batch):
    try:
        return telemetry.jit_call("train.step", fn, params, batch)
    except KeyError:
        return None
