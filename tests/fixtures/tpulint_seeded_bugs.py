"""Seeded synthetic bugs for the tpulint whole-program engine — one per
interprocedural pass, each invisible to every file-local pass.

``tests/test_tpulint.py::test_seeded_bugs_*`` lints this file under a
``mxnet_tpu/`` pseudo-path and asserts each pass catches EXACTLY its
seeded bug (and nothing else fires): the regression gate proving the
engine still sees through call indirection, donation windows and thread
boundaries. Not imported at runtime — pure fixture source.
"""
import threading

import numpy as np


# -- bug 1: traced host-sync, two calls below the traced entry point --------
# `_leaf_step` is a traced seed (every fused/graph-plane jit traces it);
# the float() sync hides two frames down, where the file-local host-sync
# pass (no loop, no same-file jit wrap) cannot see it.

def _leaf_step(w, g, state):
    return _apply_update(w, g, state)


def _apply_update(w, g, state):
    return _normalize(w - g), state


def _normalize(x):
    return x / float(x.sum())  # BUG: trace-time device sync, frozen scalar


# -- bug 2: read-after-donate ----------------------------------------------
# `weights` is donated through fused_apply; the return still reads it.

def fused_apply(optimizer, indices, grads, weights, states):
    raise NotImplementedError  # stand-in for the fastpath entry point


def apply_and_peek(optimizer, indices, grads, weights, states):
    new_w, new_s = fused_apply(optimizer, indices, grads, weights, states)
    return weights[0], new_w, new_s  # BUG: stale handle over a donated buffer


# -- bug 3: unlocked cross-thread write ------------------------------------
# the worker mutates `_count` off-thread; `snapshot` reads it from the
# caller; neither side holds the (existing!) lock.

class SeededWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self._count += 1  # BUG: unlocked write on the worker thread

    def snapshot(self):
        return np.int64(self._count)  # unlocked read from the caller
