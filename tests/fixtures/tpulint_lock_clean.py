"""Clean concurrency idioms for the v4 lock/lifecycle passes.

Every class here is a distilled version of a pattern the serving plane
actually uses, written the RIGHT way — the suite asserts ZERO findings
across ALL passes, so any false positive on these idioms is a
regression:

- ``Engine``: the tick-boundary CV discipline — the condition variable
  guards bookkeeping only; the batch is swapped out under the lock and
  every device fetch happens outside it; waits are timed, looped, and
  observe the shutdown flag; notifies hold the CV.
- ``Admitter``: the catch-all evict-then-free caller-protection idiom
  plus the subscript-store ownership transfer (``self._slots[slot] =
  req`` is the consuming last touch), and guard-polarity token charges
  settled in the handler.
- ``Copier``: the lifecycle-synchronized hand-off — ``_skip`` is
  written only while the worker is quiescent (before ``start()``), so
  the happens-before edge is ``Thread.start()``, not a lock.

NOT imported at runtime — pure lint fixture.
"""
import threading

from mxnet_tpu.base import fetch_host


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._pending = []
        self._closed = False
        self._t = threading.Thread(target=self._worker, daemon=True)

    def submit(self, item):
        with self._cv:
            self._pending.append(item)
            self._cv.notify_all()

    def close(self, timeout=None):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._t.join(timeout)

    def _worker(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait(0.1)
                if self._closed and not self._pending:
                    return
                batch, self._pending = self._pending, []
            self._step(batch)

    def _step(self, batch):
        out = fetch_host(batch)
        return ", ".join(str(x) for x in out)


class Admitter:
    def __init__(self, cache, tenant):
        self._cache = cache
        self._tenant = tenant
        self._slots = {}

    def admit(self, req, slot, pages, tokens):
        if not self._tenant.take_tokens(tokens):
            return False
        try:
            self._prefill(req, slot, pages)
        except Exception:
            self._release(slot)
            self._tenant.refund_tokens(tokens)
            raise
        return True

    def _prefill(self, req, slot, pages):
        self._cache.reserve(slot, pages)
        self._tenant.charge_pages(pages)
        self._slots[slot] = req

    def _release(self, slot):
        self._slots.pop(slot, None)
        self._cache.free(slot)
        self._tenant.release_pages(1)


class Copier:
    def __init__(self):
        self._skip = 0
        self._done = []
        self._t = threading.Thread(target=self._run, daemon=True)

    def configure(self, skip):
        self._skip = skip  # worker not started yet: start() publishes it

    def start(self):
        self._t.start()

    def _run(self):
        for i in range(self._skip, 8):
            self._done.append(i)

    def finish(self):
        self._t.join()
        return list(self._done)
