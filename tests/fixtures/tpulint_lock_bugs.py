"""Seeded concurrency bugs for the v4 lock/lifecycle passes.

Linted under a ``mxnet_tpu/`` pseudo-path by ``tests/test_tpulint.py``;
each class plants exactly ONE bug for exactly ONE pass, so the suite can
assert per-pass exactness (a pass that fires twice here has a precision
regression; one that fires zero times has a recall regression).

NOT imported at runtime — pure lint fixture.
"""
import threading

from mxnet_tpu.base import fetch_host


class PoolA:
    """BUG 1 (lock-order-cycle), forward half: A -> B.

    ``peer`` is typed through a string annotation on purpose — the
    analyzer must resolve ``self.peer.poke()`` through the attr-type
    layer, not the call graph's symbol table."""

    def __init__(self, peer: "PoolB"):
        self._lock = threading.Lock()
        self.peer = peer

    def forward(self):
        with self._lock:
            return self.peer.poke()

    def poke(self):
        with self._lock:
            return 1


class PoolB:
    """BUG 1, reverse half: B -> A closes the cycle — two threads
    running ``forward`` and ``backward`` deadlock on first interleave."""

    def __init__(self, peer: PoolA):
        self._lock = threading.Lock()
        self.peer = peer

    def backward(self):
        with self._lock:
            return self.peer.poke()

    def poke(self):
        with self._lock:
            return 2


class Sampler:
    """BUG 2 (blocking-under-lock): a device->host fetch inside the
    critical section — every thread waiting on ``_lock`` stalls for the
    full round trip."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last = None

    def snapshot(self, batch):
        with self._lock:
            self._last = fetch_host([batch])[0]
            return self._last


class Waiter:
    """BUG 3 (cv-protocol): single-shot wait — a spurious wakeup or a
    notify landing before the wait returns with the predicate false."""

    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def await_ready(self):
        with self._cv:
            self._cv.wait()
            return self._ready


class Prefiller:
    """BUG 4 (resource-lifecycle): pages reserved, a fallible call, then
    the free — if ``_run_model`` raises, the reservation leaks (no
    ``finally``, no owner transfer, no caller-side handler)."""

    def __init__(self, cache):
        self._cache = cache

    def admit(self, slot, pages):
        self._cache.reserve(slot, pages)
        self._run_model(slot)
        self._cache.free(slot)

    def _run_model(self, slot):
        return slot
