"""Sanctioned shape/sharding idioms — the tpulint v3 false-positive suite.

Every pattern here is the framework's *blessed* way of keeping shapes
static: knob-sized pools, padded bucket ladders, warmup pre-compilation
over the rungs, tile-aligned Pallas blocks with scalar prefetch, and
PartitionSpecs over axes a Mesh actually defines. The tests assert the
three new passes (recompile-risk, pallas-kernel-check, sharding-flow)
report ZERO findings on this file: the abstract domain must classify
knob reads and ladder rungs as bounded — clean by construction — or the
static gate would drown the real hazards in noise. Not imported at
runtime — pure fixture source.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry  # dispatches ride jit_call: attributed idiom
from ..base import get_env
from ..serving.buckets import select_bucket

LANES = 128
_SUBLANES = 8


# -- the serving/prefill bucket-ladder idiom ---------------------------------
# a prompt of any length pads up to a rung; one compile per rung, all
# pre-compiled by warmup() — recompile-risk must stay silent.

class CleanEngine:
    def __init__(self, model_fn, prefill_buckets=None):
        self.num_slots = get_env("MXNET_DECODE_SLOTS", 8, int, cache=False)
        self.max_seq_len = get_env("MXNET_DECODE_MAX_SEQ_LEN", 256, int,
                                   cache=False)
        self._ladder = self._prefill_ladder(prefill_buckets)
        self._step = jax.jit(model_fn, donate_argnums=(1,))
        self._prefill_jit = jax.jit(model_fn)

    def _prefill_ladder(self, buckets):
        if buckets is None:
            raw = get_env("MXNET_DECODE_PREFILL_BUCKETS", "16,64", str,
                          cache=False)
            buckets = [int(t) for t in str(raw).split(",") if t.strip()]
        ladder = sorted({int(b) for b in buckets if int(b) > 0})
        ladder = [b for b in ladder if b < self.max_seq_len]
        ladder.append(self.max_seq_len)
        return tuple(ladder)

    def warmup(self):
        # the warmed decode step: knob-shaped packed operands
        s = self.num_slots
        packed = np.zeros((5, s), np.int32)
        telemetry.jit_call("fixture.decode_step", self._step,
                           jnp.asarray(packed), None)
        # one pre-compile per rung: bounded, never ⊤
        for rung in self._ladder:
            pre = np.zeros((3, rung), np.int32)
            telemetry.jit_call("fixture.prefill", self._prefill_jit,
                               jnp.asarray(pre), None)

    def prefill(self, prompt):
        p = int(np.asarray(prompt, np.int32).size)
        rung = select_bucket(p, self._ladder)
        pre = np.zeros((3, rung), np.int32)  # padded to the rung
        return telemetry.jit_call("fixture.prefill", self._prefill_jit,
                                  jnp.asarray(pre),
                                  jnp.asarray(p, jnp.int32))


# -- a tile-aligned Pallas kernel with scalar prefetch -----------------------
# (8, 128) float32 blocks, grid↔index_map arity consistent with one
# scalar-prefetch ref, VMEM footprint far under the ceiling.

def _scale_kernel(tbl_ref, x_ref, o_ref, acc_ref):
    o_ref[...] = x_ref[...] * 2.0
    acc_ref[...] = x_ref[...]


def clean_pallas(x, table):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4, 2),
        in_specs=[
            pl.BlockSpec((_SUBLANES, LANES),
                         lambda i, j, tbl: (tbl[i], j)),
        ],
        out_specs=pl.BlockSpec((_SUBLANES, LANES),
                               lambda i, j, tbl: (i, j)),
        scratch_shapes=[pltpu.VMEM((_SUBLANES, LANES), jnp.float32)],
    )
    kernel = pl.pallas_call(
        _scale_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((32, 256), jnp.float32),
    )
    return telemetry.jit_call("fixture.clean_pallas", kernel, table, x)


# -- sharding over axes the mesh defines -------------------------------------

def make_mesh(devices):
    return Mesh(np.asarray(devices), ("dp", "mp"))


def shard_batch(devices, batch, params):
    mesh = make_mesh(devices)
    sharded = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    step = jax.jit(lambda b, p: (b, p),
                   in_shardings=(sharded, repl),
                   out_shardings=(sharded, repl),
                   donate_argnums=(0,))  # donated layout matches an output
    with mesh:
        return telemetry.jit_call("fixture.shard_step", step,
                                  jax.device_put(batch, sharded),
                                  jax.device_put(params, repl))
