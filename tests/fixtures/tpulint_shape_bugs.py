"""Seeded synthetic bugs for the tpulint v3 shape/sharding interpreter —
one per new pass, each invisible to every other pass.

``tests/test_tpulint.py::test_shape_seeded_bug_*`` lints this file under
a ``mxnet_tpu/`` pseudo-path and asserts each pass catches EXACTLY its
seeded bug (and nothing else fires): the regression gate proving the
abstract interpreter still derives ⊤ through host-data flow, the pallas
checker still folds block constants, and the sharding checker still
cross-references the project's mesh axes. Not imported at runtime —
pure fixture source.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry  # dispatches ride jit_call: attributed idiom


# -- bug 1: ⊤-shaped operand into a jit dispatch -----------------------------
# `rows` accumulates host data in a python loop; np.stack gives the batch
# a data-dependent leading dim, and `_STEP(batch)` compiles one executable
# per distinct row count — a steady-state recompile storm the runtime
# gauge would only see on a chip.

def _step_impl(x):
    return x * 2


_STEP = jax.jit(_step_impl)


def collate_and_step(host_batches):
    rows = []
    for b in host_batches:
        rows.append(np.asarray(b, np.float32))
    batch = np.stack(rows)
    # BUG: ⊤ leading dim — recompile per batch size (attribution via
    # jit_call does not absolve the data-dependent shape).
    return telemetry.jit_call("fixture.collate_step", _STEP, batch)


# -- bug 2: off-tile Pallas block --------------------------------------------
# the (8, 100) input block violates the (8, 128) float32 lane tile; it
# runs fine in interpret mode (the CPU tier-1 path) and only Mosaic on
# real hardware rejects — or silently relayouts — it.

def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def off_tile_copy(x):
    kernel = pl.pallas_call(
        _copy_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],  # BUG: 100 lanes
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
    )
    return telemetry.jit_call("fixture.off_tile_copy", kernel, x)


# -- bug 3: undefined mesh axis ----------------------------------------------
# the project defines only the "dp" axis; constraining over "tp" raises
# on the real mesh (or silently replicates under a permissive lowering).

def shard_hidden(devices, x):
    mesh = Mesh(np.asarray(devices), ("dp",))
    with mesh:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("tp")))  # BUG: no mesh defines "tp"
