"""HBM pressure governor + OOM survival plane (ISSUE-19 acceptance).

Tier-1, CPU, deterministic: the chaos ``action=oom`` schedules are
seeded, so every "5% OOM" soak here either always passes or always
fails. Covers the governor's hysteresis ladder and red latch, OOM
classification (injected/host/device), the retry policy's refuse-to-
retry-OOM guard, the kvcache shed/reclaim accounting behind the yellow
rung, the orange rung's defer-batch-never-interactive contract, the
decode OOM-survival soak (every request oracle-exact or cleanly
errored, worker alive, red latched + green recovered, zero steady-state
recompiles), the /healthz 503 + ``pressure`` field, the ``hbm``
/debug/state view, and the trainplane OOM path (structured diagnostic
in a flight-recorder dump BEFORE the controlled eager fallback).
"""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel, serving, telemetry, trainplane
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import FaultInjected, RetryPolicy, chaos, hbm
from mxnet_tpu.serving.kvcache import PagedKVCache
from mxnet_tpu.telemetry import flightrec


@pytest.fixture(autouse=True)
def _clean_state():
    """Chaos off, fresh governor, fresh metrics + flight ring per test."""
    chaos.disable()
    hbm.reset()
    telemetry.REGISTRY.clear_data()
    flightrec.clear()
    yield
    chaos.disable()
    hbm.reset()
    telemetry.REGISTRY.clear_data()
    flightrec.clear()


# ---------------------------------------------------------------------------
# the governor: ladder, hysteresis, latch
# ---------------------------------------------------------------------------

def _gov(**kw):
    kw.setdefault("capacity_bytes", 100)
    kw.setdefault("yellow", 0.70)
    kw.setdefault("orange", 0.85)
    kw.setdefault("red", 0.95)
    kw.setdefault("hysteresis", 0.05)
    kw.setdefault("red_hold", 2)
    return hbm.PressureGovernor(**kw)


def test_ladder_tiers_up_and_hysteresis_down():
    gov = _gov()
    load = {"b": 0}
    gov.register_bound("plane", lambda: load["b"])
    assert gov.observe() == "green"
    load["b"] = 75
    assert gov.observe() == "yellow"
    load["b"] = 90
    assert gov.observe() == "orange"
    load["b"] = 96
    assert gov.observe() == "red"
    # 0.92 is below red's entry (0.95) but not by the hysteresis margin:
    # a ratio oscillating on the boundary must not flap the tier
    load["b"] = 92
    assert gov.observe() == "red"
    # clears 0.95 - 0.05: releases exactly ONE tier per observation
    load["b"] = 0
    assert gov.observe() == "orange"
    assert gov.observe() == "yellow"
    assert gov.observe() == "green"
    assert gov.tiers_seen() == ["yellow", "orange", "red",
                                "orange", "yellow", "green"]


def test_pressure_is_max_of_device_and_bounds():
    gov = _gov()
    gov.register_bound("kv", 40)
    gov.register_bound("zero", 35)
    assert gov.observe() == "yellow"          # bounds sum to 75
    gov.observe_device({0: (96, 96)})          # device watermark wins
    assert gov.tier() == "red"


def test_unknown_capacity_means_no_tier_pressure():
    gov = _gov(capacity_bytes=0)
    gov.register_bound("kv", 1 << 40)
    assert gov.observe() == "green"            # only classified OOMs act


def test_red_latch_outranks_pressure_then_releases():
    gov = _gov(capacity_bytes=0, red_hold=2)
    assert gov.latch_red("oom:test") == "green"
    assert gov.tier() == "red" and gov.latched
    # the hold: pressure (0.0 — stat-less backend) may not speak yet
    assert gov.observe() == "red"
    # hold expired, pressure 0.0 -> green: the CPU CI recovery path
    assert gov.observe() == "green"
    assert not gov.latched
    assert gov.healthz_view()["latch_reason"] is None


def test_broken_callable_bound_reads_zero():
    gov = _gov()

    def boom():
        raise RuntimeError("probe died")

    gov.register_bound("bad", boom)
    gov.register_bound("good", 75)
    assert gov.observe() == "yellow"           # bad bound isolated to 0
    assert gov.oom_report()["bounds_bytes"] == {"bad": 0, "good": 75}


def test_oom_report_and_debug_view_are_json():
    gov = _gov()
    gov.register_bound("kv", lambda: 90)
    gov.observe(source="test")
    gov.latch_red("oom:test")
    gov.note_shed(3, "decode")
    rep = gov.oom_report()
    assert rep["tier"] == "red" and rep["latched"]
    assert rep["capacity_bytes"] == 100
    assert rep["watermarks"][-1]["source"] in ("test", "latch")
    view = gov.debug_view()
    assert view["transitions"][-1]["to"] == "red"
    assert view["last_shed"]["pages"] == 3
    assert view["thresholds"]["red"] == 0.95
    json.dumps(rep)
    json.dumps(view)


def test_governed_admit_default_and_knob(monkeypatch):
    gov = _gov()
    assert gov.governed_admit(8) == 4          # half the in-flight count
    assert gov.governed_admit(1) == 1          # floor 1
    monkeypatch.setenv("MXNET_HBM_RED_ADMIT", "3")
    assert gov.governed_admit(8) == 3


# ---------------------------------------------------------------------------
# classification + the chaos action and retry guard
# ---------------------------------------------------------------------------

def test_classify_kinds():
    assert hbm.classify(MemoryError("host heap")) == "host"
    assert hbm.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "17179869184 bytes")) == "device"
    assert hbm.classify(RuntimeError("failed to allocate request")) \
        == "device"
    assert hbm.classify(RuntimeError("device OOM during fusion")) \
        == "device"
    assert hbm.classify(ValueError("shape mismatch")) is None
    # the bare acronym matches as a whole word only: an unrelated
    # message containing "zoom"/"room" must not read as an OOM
    assert hbm.classify(ValueError("zoom level out of range")) is None
    assert hbm.classify(None) is None


def test_chaos_action_oom_injects_classifiable_oom():
    chaos.configure("seed=1,site=x.alloc,p=1.0,max=1,action=oom")
    with pytest.raises(chaos.OOMInjected) as ei:
        chaos.maybe_fail("x.alloc")
    exc = ei.value
    # the issue contract: a FaultInjected by inheritance, carrying the
    # literal status text a real XLA OOM would
    assert isinstance(exc, FaultInjected)
    assert "RESOURCE_EXHAUSTED" in str(exc)
    assert hbm.classify(exc) == "injected"
    chaos.maybe_fail("x.alloc")                # max=1: fires exactly once


def test_retry_policy_refuses_to_retry_oom():
    calls = {"n": 0}

    def alloc():
        calls["n"] += 1
        raise chaos.OOMInjected("t.site", calls["n"])

    p = RetryPolicy(max_attempts=5, base_delay_ms=0.0, jitter=0.0)
    with pytest.raises(chaos.OOMInjected):
        p.call(alloc, site="t.site")
    assert calls["n"] == 1                     # surfaced immediately
    from mxnet_tpu.resilience.policies import retries_counter

    assert retries_counter().value(site="t.site", outcome="oom") == 1


def test_oom_survival_ignores_non_oom():
    assert not hbm.oom_survival("any.plane",
                                ValueError("not a memory failure"))
    assert hbm.governor().tier() == "green"


def test_oom_survival_latches_counts_and_records():
    gov = hbm.governor()
    gov.register_bound("kv", 123)
    assert hbm.oom_survival("test.plane",
                            MemoryError("boom"), dump=False)
    assert gov.tier() == "red" and gov.latched
    events = [e for e in flightrec.tail() if e["kind"] == "hbm.oom"]
    assert events and events[-1]["plane"] == "test.plane"
    assert events[-1]["oom_kind"] == "host"
    assert events[-1]["report"]["bounds_bytes"]["kv"] == 123
    assert hbm._T_OOMS.value(plane="test.plane") == 1


# ---------------------------------------------------------------------------
# kvcache: reclaimable accounting + the yellow shed rung
# ---------------------------------------------------------------------------

def _cached_cache():
    """A pool with 5 usable pages, 2 of them parked in the cached-LRU."""
    c = PagedKVCache(num_slots=2, max_seq_len=32, num_layers=1,
                     num_kv_heads=1, head_dim=4, page_size=4, num_pages=6,
                     prefix_cache=True, name="shed%d" % np.random.randint(
                         1 << 30))
    c.reserve(0, 8)
    c.insert_prefix(0, np.arange(1, 9, dtype=np.int32))
    c.free(0)                                  # 2 indexed pages -> cached
    assert c.pages_free == 3 and c.pages_cached == 2
    return c


def test_admission_counts_reclaimable_cached_pages():
    c = _cached_cache()
    # the regression: 5 pages needed, only 3 on the free list — the
    # admission check must count the 2 reclaimable cached pages or every
    # warm cache reads as pressure and admission deadlocks at the head
    assert c.pages_available == 5
    assert c.can_admit(5 * 4)
    c.reserve(1, 5 * 4)                        # demand-reclaims the LRU
    assert c.pages_cached == 0 and c.pages_free == 0
    c.free(1)


def test_shed_cached_reclaims_ref0_only():
    c = _cached_cache()
    c.reserve(1, 4)                            # 1 live page, untouchable
    shed = c.shed_cached()
    assert shed == 2 and c.pages_cached == 0
    assert c.pages_in_use == 1                 # the live mapping survived
    assert c.pressure_sheds == 2
    assert c.shed_cached() == 0                # idempotent when drained
    c.free(1)
    from mxnet_tpu.serving.kvcache import _T_PRESSURE_SHEDS

    assert _T_PRESSURE_SHEDS.value(cache=c.name) == 2


# ---------------------------------------------------------------------------
# decode plane: ladder rungs + the OOM-survival acceptance soak
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    model = serving.TinyDecoder(vocab_size=32, num_layers=2, num_heads=4,
                                head_dim=8, num_kv_heads=2)
    return model, model.init_params(0)


def _engine(tiny, **kw):
    model, params = tiny
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("timeout_ms", 0)
    kw.setdefault("name", "h%d" % np.random.randint(1 << 30))
    return serving.DecodeEngine(model, params, **kw)


def test_orange_defers_batch_never_interactive(tiny):
    """The defer-vs-shed boundary: under orange, a batch-class head is
    DEFERRED (stays queued, admits when the tier recedes) while
    interactive heads keep flowing — degradation never inverts
    priority, and deferral is not a shed."""
    gov = hbm.governor()
    bound = 1 << 20
    gov.register_bound("test.synthetic", bound)
    with _engine(tiny) as eng:
        gold = eng.tenants.register(
            "gold", priority=serving.PRIORITY_CLASSES["interactive"])
        bulk = eng.tenants.register(
            "bulk", priority=serving.PRIORITY_CLASSES["batch"])
        eng.warmup()
        gov.set_capacity(int(bound / 0.87))    # pressure ~0.87: orange
        bulk_futs = [eng.submit([1, 2, 3], 4, tenant="bulk")
                     for _ in range(2)]
        gold_futs = [eng.submit([4, 5, 6], 4, tenant="gold")
                     for _ in range(2)]
        for f in gold_futs:                    # interactive flows
            f.result(timeout=120)
        deadline = time.time() + 60
        while not bulk.stats.snapshot()["deferred_pressure"] \
                and time.time() < deadline:
            time.sleep(0.01)
        assert bulk.stats.snapshot()["deferred_pressure"] > 0
        assert gold.stats.snapshot()["deferred_pressure"] == 0
        # not a shed: recede to green and the deferred heads admit
        gov.set_capacity(bound * 4)
        for f in bulk_futs:
            f.result(timeout=120)
        assert bulk.stats.snapshot()["shed"] == 0
    assert "orange" in gov.tiers_seen()


def test_decode_oom_survival_soak(tiny):
    """ISSUE-19 acceptance: chaos action=oom at p=0.05 on BOTH the
    decode step and prefill sites. Every request is oracle-exact or
    cleanly errored, the worker survives every injection, the governor
    latches red and recovers green once chaos stops, and governed
    re-admission never changes slot shapes (zero steady-state
    recompiles)."""
    model, params = tiny
    gov = hbm.governor()
    chaos.configure("seed=5,site=serving.decode,p=0.05,action=oom;"
                    "seed=5,site=serving.decode.prefill,p=0.05,"
                    "action=oom")
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(1, 32, int(rng.randint(2, 12))).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(18)]
    with _engine(tiny) as eng:
        eng.warmup()
        futs = [eng.submit(p, m) for p, m in reqs]
        errored = 0
        for (p, m), f in zip(reqs, futs):
            try:
                got = f.result(timeout=180)
            except Exception:  # noqa: BLE001 - a surfaced error IS the
                errored += 1   # clean outcome under injected OOM
                continue
            np.testing.assert_array_equal(
                got, model.reference_generate(params, p, m))
        # the schedule must actually have fired (else the soak proved
        # nothing) — and an injection means the governor latched red
        assert "red" in gov.tiers_seen()
        stats = eng.stats()
        assert stats["hbm"]["oom_count"] > 0
        # recovery: chaos off, the latch releases within red_hold
        # admission passes (stat-less backend -> pressure 0.0) and a
        # second wave completes oracle-exact
        chaos.disable()
        futs2 = [eng.submit(p, m) for p, m in reqs[:6]]
        for (p, m), f in zip(reqs[:6], futs2):
            np.testing.assert_array_equal(
                f.result(timeout=180),
                model.reference_generate(params, p, m))
        assert eng._thread.is_alive()          # zero worker deaths
        stats = eng.stats()
    assert gov.tier() == "green" and not gov.latched
    assert stats["steady_state_recompiles"] == 0
    assert stats["hbm"]["governed_limit"] is None  # cleared on green
    text = telemetry.render_prometheus()
    assert "mxnet_hbm_oom_total" in text


def test_decode_oom_mid_prefill_isolated_and_governed(tiny):
    """A single deterministic prefill OOM: the victim request errors (or
    restarts clean), the survival path arms governed re-admission, and
    the engine keeps serving afterwards."""
    model, params = tiny
    with _engine(tiny) as eng:
        eng.warmup()
        chaos.configure(
            "seed=2,site=serving.decode.prefill,p=1.0,max=1,action=oom")
        victim = eng.submit([1, 2, 3], 4)
        with pytest.raises(Exception):
            victim.result(timeout=120)
        assert hbm.governor().latched or \
            "red" in hbm.governor().tiers_seen()
        # engine alive and exact after the full eviction + re-admission
        out = eng.submit([7, 8, 9], 5).result(timeout=120)
        np.testing.assert_array_equal(
            out, model.reference_generate(
                params, np.asarray([7, 8, 9], np.int32), 5))
        events = [e for e in flightrec.tail() if e["kind"] == "hbm.oom"]
        assert events and events[-1]["plane"] == "serving.decode.prefill"


# ---------------------------------------------------------------------------
# /healthz 503 + the hbm debug view
# ---------------------------------------------------------------------------

def test_healthz_degrades_while_red():
    from mxnet_tpu.telemetry.httpd import _Handler

    doc = _Handler._healthz()
    assert doc["status"] == "ok"
    assert doc["pressure"]["tier"] == "green"
    hbm.governor().latch_red("oom:test")
    doc = _Handler._healthz()
    assert doc["status"] == "degraded"
    assert doc["pressure"]["tier"] == "red"
    assert doc["pressure"]["latched"]
    assert doc["pressure"]["latch_reason"] == "oom:test"


def test_healthz_503_over_http():
    from urllib.error import HTTPError
    from urllib.request import urlopen

    from mxnet_tpu.telemetry import httpd as _httpd

    hbm.governor().latch_red("oom:test")
    srv = _httpd.start_httpd(port=0)
    try:
        host, port = srv.server_address[:2]
        with pytest.raises(HTTPError) as ei:
            urlopen("http://%s:%d/healthz" % (host, port), timeout=10)
        assert ei.value.code == 503
        doc = json.loads(ei.value.read().decode())
        assert doc["status"] == "degraded"
        assert doc["pressure"]["latched"]
    finally:
        _httpd.stop_httpd()


def test_debug_state_grows_hbm_view():
    from mxnet_tpu.telemetry import httpd as _httpd

    gov = hbm.governor()                       # registration side effect
    gov.register_bound("kv", 42)
    views = _httpd._debug_views()
    assert "hbm" in views
    view = views["hbm"]
    assert view["tier"] in hbm.TIERS
    assert view["bounds_bytes"]["kv"] == 42
    assert "transitions" in view and "thresholds" in view


def test_decode_stats_carry_hbm_view(tiny):
    with _engine(tiny) as eng:
        hv = eng.stats()["hbm"]
    assert hv["tier"] in hbm.TIERS
    assert "governed_limit" in hv and "pressure_sheds" in hv


# ---------------------------------------------------------------------------
# trainplane: structured diagnostic BEFORE the controlled fallback
# ---------------------------------------------------------------------------

B = 8


def _mlp_plane(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    monkeypatch.setenv("MXNET_FLIGHTREC_PATH", str(tmp_path / "box.json"))
    rs = np.random.RandomState(3)
    xs = rs.rand(4 * B, 6).astype(np.float32)
    ys = rs.randint(0, 8, (4 * B,))
    net = nn.HybridSequential(prefix="hbmoom_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(8))
    net.initialize()
    with mx.autograd.pause():
        net(nd.array(xs[:B]))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
        mesh=parallel.device_mesh(1))
    return plane, xs, ys


def test_trainplane_oom_dumps_diagnostic_then_falls_back(monkeypatch,
                                                         tmp_path):
    plane, xs, ys = _mlp_plane(monkeypatch, tmp_path)
    loss = plane.step(nd.array(xs[:B]), nd.array(ys[:B]))
    assert plane.plane == "graph"
    assert np.isfinite(float(np.asarray(loss._data).mean()))
    # one injected OOM at the step's jit dispatch: the step must still
    # RETURN (eager fallback), with the post-mortem already on disk
    chaos.configure("seed=1,site=jit.compile,p=1.0,max=1,action=oom")
    loss = plane.step(nd.array(xs[B:2 * B]), nd.array(ys[B:2 * B]))
    assert np.isfinite(float(np.asarray(loss._data).mean()))
    assert plane.plane == "eager"              # controlled demotion
    assert hbm.governor().latched              # red latched
    path = flightrec.last_dump_path()
    assert path == str(tmp_path / "box.json") and os.path.exists(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"].startswith("hbm oom at trainplane.step")
    ooms = [e for e in doc["events"] if e["kind"] == "hbm.oom"]
    assert ooms
    ev = ooms[-1]
    assert ev["plane"] == "trainplane.step"
    assert ev["oom_kind"] == "injected"
    # the structured diagnostic: per-plane breakdown + watermark history
    assert "bounds_bytes" in ev["report"]
    assert "watermarks" in ev["report"]
    assert ev["report"]["latched"] or ev["report"]["oom_count"] >= 1
    # training continues on the eager plane after the survival
    chaos.disable()
    loss = plane.step(nd.array(xs[2 * B:3 * B]), nd.array(ys[2 * B:3 * B]))
    assert np.isfinite(float(np.asarray(loss._data).mean()))


def test_trainplane_non_oom_still_propagates(monkeypatch, tmp_path):
    plane, xs, ys = _mlp_plane(monkeypatch, tmp_path)
    plane.step(nd.array(xs[:B]), nd.array(ys[:B]))
    assert plane.plane == "graph"
    # a plain injected fault is NOT an OOM: no hidden fallback — the
    # never-a-crash discipline is scoped to classified OOMs only
    chaos.configure("seed=1,site=jit.compile,p=1.0,max=1,action=fault")
    with pytest.raises(FaultInjected):
        plane.step(nd.array(xs[B:2 * B]), nd.array(ys[B:2 * B]))
    assert plane.plane == "graph"
    assert not hbm.governor().latched
