"""Tests for tools/ (im2rec, diagnose, flakiness_checker normalization).

The reference ships its dataset packer and launch utilities in tools/
(tools/im2rec.py, tools/launch.py, tools/diagnose.py); launch.py is covered
by test_dist_launch.py.
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def _write_images(root):
    from mxnet_tpu import image

    for cls in ("cats", "dogs"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            img = (np.random.RandomState(i).rand(24, 30, 3) * 255).astype(np.uint8)
            (root / cls / ("img%d.png" % i)).write_bytes(image.imencode(img, ".png"))


def test_im2rec_list_and_pack(tmp_path):
    import im2rec

    from mxnet_tpu import recordio

    _write_images(tmp_path / "imgs")
    prefix = str(tmp_path / "data")
    assert im2rec.main(["--list", "--recursive", prefix, str(tmp_path / "imgs")]) == 0
    lst = Path(prefix + ".lst").read_text().strip().splitlines()
    assert len(lst) == 6
    labels = {line.split("\t")[1] for line in lst}
    assert labels == {"0.000000", "1.000000"}  # two classes

    assert im2rec.main(["--resize", "16", "--encoding", ".png",
                        prefix, str(tmp_path / "imgs")]) == 0
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(r.keys) == 6
    seen_labels = set()
    for k in r.keys:
        h, img = recordio.unpack_img(r.read_idx(k))
        assert min(img.shape[:2]) == 16
        seen_labels.add(float(h.label))
    assert seen_labels == {0.0, 1.0}
    r.close()


def test_im2rec_pass_through(tmp_path):
    import im2rec

    from mxnet_tpu import recordio

    _write_images(tmp_path / "imgs")
    prefix = str(tmp_path / "data")
    im2rec.main(["--list", "--recursive", prefix, str(tmp_path / "imgs")])
    im2rec.main(["--pass-through", prefix, str(tmp_path / "imgs")])
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    h, payload = recordio.unpack(r.read_idx(r.keys[0]))
    assert payload[:8].startswith(b"\x89PNG")  # raw bytes, not re-encoded
    r.close()


def test_flakiness_checker_target_normalization():
    import flakiness_checker

    assert flakiness_checker.normalize_target(
        "tests/test_operator.py::test_x") == "tests/test_operator.py::test_x"
    assert flakiness_checker.normalize_target(
        "test_operator.test_x") == os.path.join("tests", "test_operator.py") + "::test_x"


def test_diagnose_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # never dial the accelerator relay from a diagnostics subprocess — a
    # wedged tunnel would hang the import (see .claude/skills/verify)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run([sys.executable, str(REPO / "tools" / "diagnose.py")],
                         capture_output=True, text=True, timeout=180,
                         env=env)
    assert out.returncode == 0
    assert "mxnet_tpu Info" in out.stdout and "JAX Info" in out.stdout


def test_im2rec_multithread(tmp_path):
    """--num-thread packs via the host engine with serialized writes."""
    import im2rec

    from mxnet_tpu import recordio

    _write_images(tmp_path / "imgs")
    prefix = str(tmp_path / "data")
    im2rec.main(["--list", "--recursive", prefix, str(tmp_path / "imgs")])
    assert im2rec.main(["--resize", "16", "--encoding", ".png",
                        "--num-thread", "4", prefix,
                        str(tmp_path / "imgs")]) == 0
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert sorted(r.keys) == list(range(6))
    for k in r.keys:
        h, img = recordio.unpack_img(r.read_idx(k))
        assert min(img.shape[:2]) == 16
    r.close()
