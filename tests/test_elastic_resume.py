"""Elastic v2 acceptance suite: sharded checkpoints and bitwise
kill-and-resume.

The PR-4 chaos discipline applied to the whole save→kill→resume cycle:

* a ZeRO-partitioned updater checkpoints each dp shard DIRECTLY — zero
  all-gathers, asserted via the ``mxnet_zero_materializations_total``
  counter (telemetry accounting, not assumption) — and restore re-buckets
  exactly onto a different dp size;
* torn-write and drop-one-shard chaos against a committed epoch fall back
  to the previous committed epoch, never a crash;
* a kill-at-step preemption resumed through ``run_elastic`` is BITWISE
  identical to the uninterrupted run — final params, optimizer state,
  data cursor and step counters — for SGD and Adam at ``MXNET_ZERO=0``
  and ``1`` on a 2-device CPU mesh through the trainplane graph path.

Runs on the conftest 8-virtual-device CPU backend.
"""
import os

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import elastic, gluon, nd, parallel, telemetry, trainplane
from mxnet_tpu.fastpath import zero
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import chaos

B = 8
STEPS = 6
CKPT_EVERY = 2


@pytest.fixture(autouse=True)
def _clear_preemption():
    elastic.clear_preemption()
    yield
    elastic.clear_preemption()


def _make(prefix, opt_name, opt_params):
    mx.random.seed(7)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize()
    with mx.autograd.pause():
        net(nd.ones((B, 6)))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), opt_name,
                            dict(opt_params))
    return net, trainer


def _data(seed=3):
    rs = np.random.RandomState(seed)
    return (rs.rand(STEPS * B, 6).astype(np.float32),
            rs.randint(0, 8, (STEPS * B,)).astype(np.float32))


def _materialized_states(trainer):
    upd = trainer._updaters[0]
    zero.materialize_updater(upd)
    return {k: [np.asarray(x) for x in jax.tree_util.tree_leaves(v)]
            for k, v in upd.states.items()}


def _params_of(net):
    # key by the prefix-free tail ("dense0_weight") so runs built under
    # different name prefixes compare parameter-for-parameter
    return {n[n.index("dense"):]: np.asarray(p.data()._data)
            for n, p in net.collect_params().items()}


# ---------------------------------------------------------------------------
# sharded checkpoints
# ---------------------------------------------------------------------------


def _train_sharded(tag, opt_name, opt_params, steps=3):
    """Eager fastpath training with the ZeRO plane attached; returns the
    live (net, trainer) with sharded updater state."""
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net, trainer = _make(tag, opt_name, opt_params)
    for s in range(steps):
        with mx.autograd.record():
            loss = loss_fn(net(nd.array(X[s * B:(s + 1) * B])),
                           nd.array(Y[s * B:(s + 1) * B]))
        loss.backward()
        trainer.step(B)
    return net, trainer


def test_sharded_save_performs_zero_allgathers(tmp_path, monkeypatch):
    """The sharded save reads per-rank device shards directly: the
    materialization counter must not move, the state stays sharded, and
    the per-shard files + hashed manifest land committed-last."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    monkeypatch.setenv("MXNET_ZERO", "1")
    monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    net, trainer = _train_sharded("zsg_", "adam", {"learning_rate": 0.01})
    upd = trainer._updaters[0]
    assert zero.plane_of(upd) is not None

    cm = elastic.CheckpointManager(str(tmp_path))
    m0 = zero.MATERIALIZATIONS.value()
    t0 = telemetry.TRANSFER_BYTES.value(path="ckpt.shard")
    cm.save_training(0, net=net, trainer=trainer)
    assert zero.MATERIALIZATIONS.value() == m0  # NO all-gather
    assert telemetry.TRANSFER_BYTES.value(path="ckpt.shard") > t0
    assert all(zero.is_sharded(s) for s in upd.states.values())
    names = sorted(os.listdir(tmp_path))
    assert any(".shard0-of-2" in n for n in names)
    assert any(".shard1-of-2" in n for n in names)
    assert any(".zmeta" in n for n in names)

    import json

    manifest = json.load(open(cm._manifest_path(0)))
    assert manifest["sharded"] == {"dp": 2, "level": 1,
                                   "mesh_shape": {"dp": 2}}
    assert len(manifest["shards"]) == 2
    assert all(s["sha256"] for s in manifest["shards"])


@pytest.mark.parametrize("opt_name,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_sharded_restore_roundtrip_exact(tmp_path, monkeypatch, opt_name,
                                         opt_params):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    monkeypatch.setenv("MXNET_ZERO", "1")
    monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    net, trainer = _train_sharded("zrt%s_" % opt_name, opt_name, opt_params)
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save_training(0, net=net, trainer=trainer)

    net2, trainer2 = _make("zrr%s_" % opt_name, opt_name, opt_params)
    assert cm.restore_training(net=net2, trainer=trainer2) == 0
    want_states = _materialized_states(trainer)
    got_states = _materialized_states(trainer2)
    assert set(want_states) == set(got_states)
    for k in want_states:
        for a, b in zip(want_states[k], got_states[k]):
            np.testing.assert_array_equal(a, b, err_msg=str(k))
    want_p, got_p = _params_of(net), _params_of(net2)
    for k in want_p:
        np.testing.assert_array_equal(want_p[k], got_p[k], err_msg=k)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update
    assert trainer2._optimizer._index_update_count == \
        trainer._optimizer._index_update_count


def test_sharded_save_replicated_masters_roundtrip(tmp_path, monkeypatch):
    """bf16 weights + fp32 masters at level 1: the masters are classic-
    ZeRO-1 replicated, land once in the .repl file, and the whole state
    (masters + sharded base) round-trips bitwise."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_ZERO", "1")
    monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt_params = {"learning_rate": 0.1, "momentum": 0.9,
                  "multi_precision": True}
    net, trainer = _make("zmp_", "sgd", opt_params)
    net.cast("bfloat16")
    for s in range(2):
        x = mx.nd.NDArray(jnp.asarray(X[s * B:(s + 1) * B], jnp.bfloat16),
                          mx.cpu())
        with mx.autograd.record():
            loss = loss_fn(net(x), nd.array(Y[s * B:(s + 1) * B]))
        loss.backward()
        trainer.step(B)
    assert zero.plane_of(trainer._updaters[0]) is not None

    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save_training(0, net=net, trainer=trainer)
    assert any(n.endswith(".repl") for n in os.listdir(tmp_path))

    net2, trainer2 = _make("zmq_", "sgd", opt_params)
    net2.cast("bfloat16")
    assert cm.restore_training(net=net2, trainer=trainer2) == 0
    want = _materialized_states(trainer)
    got = _materialized_states(trainer2)
    assert set(want) == set(got)
    for k in want:
        for a, b in zip(want[k], got[k]):
            np.testing.assert_array_equal(a, b, err_msg=str(k))


def test_sharded_restore_onto_different_dp(tmp_path, monkeypatch):
    """Save at dp=2, resume at dp=4: the flat-plan re-bucketing makes the
    layout change invisible — materialized state is bitwise the dp=2
    run's, and the next sharded step adopts at the new width."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    monkeypatch.setenv("MXNET_ZERO", "1")
    monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    net, trainer = _train_sharded("zdp_", "adam", {"learning_rate": 0.01})
    cm = elastic.CheckpointManager(str(tmp_path))
    cm.save_training(0, net=net, trainer=trainer)

    monkeypatch.setenv("MXNET_ZERO_DEVICES", "4")
    net2, trainer2 = _make("zdq_", "adam", {"learning_rate": 0.01})
    assert cm.restore_training(net=net2, trainer=trainer2) == 0
    want = _materialized_states(trainer)
    got = _materialized_states(trainer2)
    for k in want:
        for a, b in zip(want[k], got[k]):
            np.testing.assert_array_equal(a, b, err_msg=str(k))
    # one more step adopts the restored state onto the dp=4 mesh
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with mx.autograd.record():
        loss = loss_fn(net2(nd.array(X[:B])), nd.array(Y[:B]))
    loss.backward()
    trainer2.step(B)
    plane = zero.plane_of(trainer2._updaters[0])
    assert plane is not None and plane.dp == 4


def _corruption_case(tmp_path, monkeypatch, spec):
    """Two committed sharded epochs; the second saved under a chaos spec
    that corrupts/loses a shard. Returns (manager, trainer-at-epoch-0
    snapshot states, restored trainer, restored epoch, corruption delta)."""
    monkeypatch.setenv("MXNET_ZERO", "1")
    monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net, trainer = _make("zcc_", "adam", {"learning_rate": 0.01})
    cm = elastic.CheckpointManager(str(tmp_path))

    def step(s):
        with mx.autograd.record():
            loss = loss_fn(net(nd.array(X[s * B:(s + 1) * B])),
                           nd.array(Y[s * B:(s + 1) * B]))
        loss.backward()
        trainer.step(B)

    step(0)
    step(1)
    cm.save_training(0, net=net, trainer=trainer)
    want = _materialized_states(trainer)  # snapshot AT epoch 0
    # (materialize detached the plane; the next step re-adopts)
    step(2)
    with chaos.active(spec):
        cm.save_training(1, net=net, trainer=trainer)
    c0 = telemetry.CKPT_CORRUPTION.value()
    net2, trainer2 = _make("zcd_", "adam", {"learning_rate": 0.01})
    epoch = cm.restore_training(net=net2, trainer=trainer2)
    return cm, want, trainer2, epoch, telemetry.CKPT_CORRUPTION.value() - c0


def test_torn_write_falls_back_to_previous_epoch(tmp_path, monkeypatch):
    """A committed-looking epoch whose shard bytes tore (hash mismatch)
    restores the PREVIOUS committed epoch — counted, never a crash."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    cm, want, trainer2, epoch, corrupt = _corruption_case(
        tmp_path, monkeypatch, "site=ckpt.shard,at=1,action=torn-write")
    assert cm.latest_epoch() == 1      # files all exist: LOOKS committed
    assert epoch == 0                  # ...but restore detected the tear
    assert corrupt >= 1
    got = _materialized_states(trainer2)
    for k in want:
        for a, b in zip(want[k], got[k]):
            np.testing.assert_array_equal(a, b, err_msg=str(k))


def test_drop_one_shard_falls_back_to_previous_epoch(tmp_path, monkeypatch):
    """A lost shard file makes the epoch read UNCOMMITTED everywhere:
    latest_epoch skips it and restore lands on the previous epoch."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    cm, want, trainer2, epoch, _corrupt = _corruption_case(
        tmp_path, monkeypatch, "site=ckpt.shard,at=1,action=drop-shard")
    assert cm.latest_epoch() == 0      # missing file == uncommitted
    assert epoch == 0
    got = _materialized_states(trainer2)
    for k in want:
        for a, b in zip(want[k], got[k]):
            np.testing.assert_array_equal(a, b, err_msg=str(k))


# ---------------------------------------------------------------------------
# bitwise kill-and-resume through the trainplane graph path
# ---------------------------------------------------------------------------


def _elastic_run(tmpdir, tag, opt_name, opt_params, kill_spec=None):
    """Train STEPS steps through TrainPlane on a 2-device mesh under
    run_elastic, checkpointing (async, sharded-aware) every CKPT_EVERY
    steps; returns the final state fingerprint."""
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    cm = elastic.CheckpointManager(str(tmpdir))
    final = {}

    def train_fn(start_epoch, manager):
        net, trainer = _make(tag, opt_name, opt_params)
        plane = trainplane.TrainPlane(net, loss_fn, trainer,
                                      mesh=parallel.device_mesh(2))
        it = mx.io.NDArrayIter(X, Y, batch_size=B)
        last = manager.restore_training(net=net, trainer=trainer,
                                        train_iter=it)
        for step in range(last + 1, STEPS):
            elastic.step_boundary(manager=manager)
            batch = it.next()
            plane.step(batch.data[0], batch.label[0])
            if (step + 1) % CKPT_EVERY == 0:
                manager.save_training(step, net=net, trainer=trainer,
                                      train_iter=it, async_save=True)
        manager.wait()
        final["net"], final["trainer"], final["it"] = net, trainer, it
        final["plane"] = plane
        return "done"

    if kill_spec:
        with chaos.active(kill_spec):
            assert elastic.run_elastic(train_fn, cm, max_restarts=3,
                                       restart_delay=0) == "done"
    else:
        assert elastic.run_elastic(train_fn, cm, max_restarts=0,
                                   restart_delay=0) == "done"
    net, trainer, it = final["net"], final["trainer"], final["it"]
    assert final["plane"].plane == "graph"  # the acceptance path
    return {
        "params": _params_of(net),
        "states": _materialized_states(trainer),
        "cursor": int(it.cursor),
        "num_update": trainer._optimizer.num_update,
        "index_counts": dict(trainer._optimizer._index_update_count),
    }


@pytest.mark.parametrize("opt_name,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
@pytest.mark.parametrize("zero_level", [0, 1])
def test_kill_at_step_resume_bitwise(tmp_path, monkeypatch, opt_name,
                                     opt_params, zero_level):
    """ACCEPTANCE: kill-at-step → resume is bitwise identical to the
    uninterrupted run — final params, optimizer state, data cursor and
    step counters — for SGD/Adam at MXNET_ZERO=0 and 1 on a 2-device CPU
    mesh through the trainplane graph path."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    if zero_level:
        monkeypatch.setenv("MXNET_ZERO", "1")
        monkeypatch.setenv("MXNET_ZERO_DEVICES", "2")
    else:
        monkeypatch.delenv("MXNET_ZERO", raising=False)
    tag = "kr%s%d_" % (opt_name, zero_level)

    ref = _elastic_run(tmp_path / "ref", tag + "a_", opt_name, opt_params)
    # the 4th step boundary = entering step 3: steps 2 (unsaved) and 3
    # are killed mid-window and must replay from the epoch-1 checkpoint
    got = _elastic_run(tmp_path / "kill", tag + "b_", opt_name, opt_params,
                       kill_spec="site=elastic.step,at=4,action=kill")

    assert got["cursor"] == ref["cursor"]
    assert got["num_update"] == ref["num_update"]
    assert got["index_counts"] == ref["index_counts"]
    assert set(got["params"]) == set(ref["params"])
    for k in ref["params"]:
        np.testing.assert_array_equal(got["params"][k], ref["params"][k],
                                      err_msg="param %s" % k)
    assert set(got["states"]) == set(ref["states"])
    for k in ref["states"]:
        assert len(got["states"][k]) == len(ref["states"][k])
        for a, b in zip(ref["states"][k], got["states"][k]):
            np.testing.assert_array_equal(b, a, err_msg="state %s" % str(k))


def test_trainplane_fit_checkpoint_resume(tmp_path, monkeypatch):
    """trainplane.fit(checkpoint=...) under a kill: run_elastic restarts
    it and fit resumes from the committed epoch; the run completes with
    every epoch's checkpoint committed."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    monkeypatch.delenv("MXNET_ZERO", raising=False)
    X, Y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    cm = elastic.CheckpointManager(str(tmp_path))

    def train_fn(start_epoch, manager):
        net, trainer = _make("fitck_", "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
        trainplane.fit(net, loss_fn, trainer,
                       mx.io.NDArrayIter(X, Y, batch_size=B),
                       epochs=3, mesh=parallel.device_mesh(2),
                       checkpoint=manager)
        return "ok"

    # 3 epochs x 6 batches: kill at the 8th step boundary (epoch 1)
    with chaos.active("site=elastic.step,at=8,action=kill"):
        assert elastic.run_elastic(train_fn, cm, max_restarts=2,
                                   restart_delay=0) == "ok"
    assert cm.latest_epoch() == 2
    assert cm.restore_training() == 2
    assert (cm.last_restored_extra or {}).get("mid_epoch") is False
