"""Tests for the deformable/PS-ROI/count-sketch op tail and the
SyncBatchNorm sharding contract.

Reference models: tests/python/unittest/test_operator.py
(test_deformable_convolution — zero offsets must equal plain convolution),
test_psroipooling, count_sketch tests, and the sync_batch_norm cross-device
statistics check (tests/python/gpu/test_operator_gpu.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops.registry import get_op


def test_deformable_conv_zero_offset_equals_conv():
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(2, 4, 9, 9).astype(np.float32))
    w = mx.nd.array(rs.randn(6, 4, 3, 3).astype(np.float32))
    b = mx.nd.array(rs.randn(6).astype(np.float32))
    offset = mx.nd.zeros((2, 2 * 9, 7, 7))
    out_d = invoke("_contrib_DeformableConvolution", x, offset, w, b,
                   kernel=(3, 3), num_filter=6)
    out_c = invoke("Convolution", x, w, b, kernel=(3, 3), num_filter=6)
    np.testing.assert_allclose(out_d.asnumpy(), out_c.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_shift():
    """A constant integer offset of (0, 1) equals convolving the input
    shifted left by one pixel (interior pixels)."""
    rs = np.random.RandomState(1)
    x_np = rs.randn(1, 2, 8, 8).astype(np.float32)
    w = mx.nd.array(rs.randn(3, 2, 3, 3).astype(np.float32))
    x = mx.nd.array(x_np)
    off = np.zeros((1, 18, 6, 6), np.float32)
    off[:, 1::2] = 1.0  # dx = +1 for every tap
    out_d = invoke("_contrib_DeformableConvolution", x, mx.nd.array(off), w,
                   kernel=(3, 3), num_filter=3, no_bias=True)
    shifted = np.zeros_like(x_np)
    shifted[..., :-1] = x_np[..., 1:]
    out_c = invoke("Convolution", mx.nd.array(shifted), w,
                   kernel=(3, 3), num_filter=3, no_bias=True)
    # columns whose +1-shifted taps stay in bounds match exactly
    np.testing.assert_allclose(out_d.asnumpy()[..., :5],
                               out_c.asnumpy()[..., :5], rtol=1e-4, atol=1e-4)


def test_psroi_pooling_group_selection():
    p, odim = 2, 3
    c = odim * p * p
    data = np.zeros((1, c, 8, 8), np.float32)
    for ch in range(c):
        data[0, ch] = ch  # each score map is a distinct constant
    rois = mx.nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    out = invoke("_contrib_PSROIPooling", mx.nd.array(data), rois,
                 spatial_scale=1.0, output_dim=odim, pooled_size=p)
    got = out.asnumpy()
    assert got.shape == (1, odim, p, p)
    for ci in range(odim):
        for py in range(p):
            for px in range(p):
                expected = ci * p * p + py * p + px
                assert got[0, ci, py, px] == pytest.approx(expected), \
                    (ci, py, px)


def test_count_sketch():
    data = mx.nd.array(np.array([[1.0, 2.0, 3.0, 4.0]], np.float32))
    h = mx.nd.array(np.array([[0, 1, 0, 2]], np.float32))
    s = mx.nd.array(np.array([[1, -1, 1, 1]], np.float32))
    out = invoke("_contrib_count_sketch", data, h, s, out_dim=3)
    np.testing.assert_allclose(out.asnumpy(), [[4.0, -2.0, 4.0]])


def test_legacy_aliases_resolve():
    for name in ("BatchNorm_v1", "Convolution_v1", "Pooling_v1", "fft",
                 "ifft", "_contrib_SyncBatchNorm"):
        get_op(name)


def test_sync_batch_norm_global_stats_under_sharding():
    """The SyncBatchNorm contract (reference sync_batch_norm-inl.h): batch
    statistics span ALL devices. Under GSPMD a batch-sharded BatchNorm
    already reduces over the full logical batch; verify the sharded output
    equals the full-batch single-device result and differs from the
    per-shard one."""
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    opdef = get_op("BatchNorm")
    attrs = opdef.parse_attrs({"fix_gamma": "False", "eps": "1e-3"})
    rs = np.random.RandomState(0)
    # make shard means differ so per-shard BN is distinguishable
    x = rs.randn(16, 4, 3, 3).astype(np.float32)
    x += np.repeat(np.arange(8, dtype=np.float32)[:, None, None, None] * 3.0,
                   2, axis=0)
    gamma = np.ones(4, np.float32)
    beta = np.zeros(4, np.float32)
    mmean = np.zeros(4, np.float32)
    mvar = np.ones(4, np.float32)

    from mxnet_tpu import _global

    def bn(data):
        # batch statistics (not moving averages) — train-mode BN
        with _global.train_mode_scope(True):
            out, _, _ = opdef.fcompute(attrs, data, gamma, beta, mmean, mvar)
        return out

    ref = bn(jnp.asarray(x))  # full batch, one device

    mesh = Mesh(np.asarray(devices[:8]), ("dp",))
    sharded = jax.device_put(x, NamedSharding(mesh, P("dp")))
    out_sharded = jax.jit(bn)(sharded)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    # per-shard BN (what an unsynchronized implementation would compute)
    per_shard = np.concatenate([np.asarray(bn(jnp.asarray(x[i:i + 2])))
                                for i in range(0, 16, 2)])
    assert not np.allclose(per_shard, np.asarray(ref), atol=1e-2)


def test_deformable_psroi_pooling():
    """reference src/operator/contrib/deformable_psroi_pooling.cc: with
    zero offsets each output bin pools its own position-sensitive score
    map; a positive x-offset on a horizontal ramp increases the sample."""
    import numpy as np

    from mxnet_tpu.ndarray.ndarray import invoke

    p, group, odim = 2, 2, 2
    c = odim * group * group  # 8 channels
    # channel k is constant k -> bin value must equal mapped channel index
    data = np.zeros((1, c, 8, 8), np.float32)
    for k in range(c):
        data[0, k] = k
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    trans = np.zeros((1, 2, p, p), np.float32)
    out = invoke("_contrib_DeformablePSROIPooling",
                 mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
                 spatial_scale=1.0, output_dim=odim, group_size=group,
                 pooled_size=p, trans_std=0.1, sample_per_part=2)
    assert out.shape == (1, odim, p, p)
    got = out.asnumpy()[0]
    for ch in range(odim):
        for py in range(p):
            for px in range(p):
                expect = ch * group * group + py * group + px
                np.testing.assert_allclose(got[ch, py, px], expect,
                                           rtol=1e-5)

    # horizontal ramp: a positive x offset must increase the pooled value
    ramp = np.tile(np.arange(8, dtype=np.float32), (8, 1))
    data2 = np.broadcast_to(ramp, (1, c, 8, 8)).copy()
    t0 = invoke("_contrib_DeformablePSROIPooling",
                mx.nd.array(data2), mx.nd.array(rois), mx.nd.array(trans),
                spatial_scale=1.0, output_dim=odim, group_size=group,
                pooled_size=p, trans_std=0.1, sample_per_part=2).asnumpy()
    trans_px = trans.copy()
    trans_px[0, 0] = 1.0  # dx = trans_std * rw = 0.8 pixels
    t1 = invoke("_contrib_DeformablePSROIPooling",
                mx.nd.array(data2), mx.nd.array(rois),
                mx.nd.array(trans_px),
                spatial_scale=1.0, output_dim=odim, group_size=group,
                pooled_size=p, trans_std=0.1, sample_per_part=2).asnumpy()
    assert (t1 > t0 + 0.4).all(), (t0, t1)

    # no_trans mode drops the trans input entirely
    nt = invoke("_contrib_DeformablePSROIPooling",
                mx.nd.array(data), mx.nd.array(rois),
                spatial_scale=1.0, output_dim=odim, group_size=group,
                pooled_size=p, no_trans=True, sample_per_part=2)
    np.testing.assert_allclose(nt.asnumpy(), got[None], rtol=1e-5)
