"""Profiler tests (reference tests/python/unittest/test_profiler.py):
chrome-trace dump, aggregate tables, pause/resume, user objects."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


@pytest.fixture(autouse=True)
def _reset_profiler(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "profile.json"),
                           aggregate_stats=True, profile_symbolic=False,
                           profile_all=False)
    yield
    mx.profiler.set_state("stop")
    mx.profiler._events.clear()
    mx.profiler._agg.clear()


def test_eager_ops_recorded_and_dumped(tmp_path):
    mx.profiler.set_state("run")
    a = nd.ones((8, 8))
    b = nd.dot(a, a)
    (b + 1).asnumpy()
    mx.profiler.set_state("stop")
    fname = str(tmp_path / "profile.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.dump()
    doc = json.load(open(fname))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "dot" in names
    assert all(e["ph"] in ("X", "C", "i") for e in doc["traceEvents"])


def test_executor_events_and_aggregate_table():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    mx.profiler.set_state("run")
    ex.forward(is_train=True)
    ex.backward()
    mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "executor::forward" in table
    assert "executor::backward" in table
    assert "Calls" in table and "Avg(ms)" in table


def test_pause_resume():
    x = nd.ones((2, 2))
    mx.profiler.set_state("run")
    mx.profiler.pause()
    nd.relu(x).asnumpy()
    assert not any("relu" in k for k in mx.profiler._agg)
    mx.profiler.resume()
    nd.relu(x).asnumpy()
    assert any("relu" in k for k in mx.profiler._agg)


def test_redundant_run_is_noop_and_warns():
    mx.profiler.set_state("run")
    nd.relu(nd.ones((2, 2))).asnumpy()
    assert any("relu" in k for k in mx.profiler._agg)
    with pytest.warns(UserWarning, match="no-op"):
        mx.profiler.set_state("run")
    # the session continued: the redundant run did NOT clear the buffers
    assert any("relu" in k for k in mx.profiler._agg)
    mx.profiler.set_state("stop")


def test_pause_resume_threaded_against_set_state():
    """pause/resume from worker threads while the main thread cycles
    set_state: the final state must be consistent (both now mutate under
    _lock), i.e. a stopped profiler is never left ENABLED."""
    import threading

    stop = threading.Event()

    def churn():
        while not stop.is_set():
            mx.profiler.pause()
            mx.profiler.resume()

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(200):
        mx.profiler.set_state("run")
        mx.profiler.set_state("stop")
    stop.set()
    for t in threads:
        t.join()
    # profiler is stopped; a straggling resume() must not re-enable it
    mx.profiler.resume()
    assert mx.profiler.ENABLED is False


def test_profiler_off_means_no_events():
    nd.ones((2, 2)).asnumpy()
    assert not mx.profiler._events


def test_user_objects():
    mx.profiler.set_state("run")
    dom = mx.profiler.Domain("app")
    with dom.new_task("work"):
        pass
    frame = dom.new_frame("frame0")
    frame.start()
    frame.stop()
    counter = dom.new_counter("ctr", 5)
    counter.increment(2)
    dom.new_marker("here").mark()
    mx.profiler.set_state("stop")
    cats = [e["cat"] for e in mx.profiler._events]
    assert "task" in cats and "frame" in cats
    assert "counter" in cats and "marker" in cats
    with pytest.raises(mx.MXNetError):
        dom.new_task("bad").stop()


def test_set_config_rejects_unknown():
    with pytest.raises(mx.MXNetError):
        mx.profiler.set_config(bogus=True)


def test_resnet_step_trace(tmp_path):
    """Trace + summary from a (small) model-zoo ResNet step — the VERDICT
    round-3 acceptance for the profiler MVP."""
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10)
    net.initialize()
    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        parallel.device_mesh(1),
        optimizer_params={"learning_rate": 0.1})
    x = nd.array(np.random.rand(2, 3, 32, 32).astype(np.float32))
    y = nd.array(np.zeros(2, np.float32))
    step(x, y)  # compile outside the profiled region
    mx.profiler.set_state("run")
    with mx.profiler.Domain("train").new_task("step"):
        step(x, y).wait_to_read()
    mx.profiler.set_state("stop")
    fname = str(tmp_path / "rn.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.dump()
    doc = json.load(open(fname))
    assert any(e["name"] == "train::step" for e in doc["traceEvents"])
    assert "train::step" in mx.profiler.dumps()
