"""mxnet_tpu.serving.fleet — FleetRouter behind the single-engine surface
(CPU; split across the tier-1 and slow tiers, see below).

Covers the PR-17 acceptance surface: oracle parity through the router,
prefix-affinity placement (fleet hit ratio vs a single replica),
rendezvous + spillover routing, replica drain/rolling-swap with zero
drops, failure containment (kill + chaos site → exactly-once re-routing,
breaker isolation, index tombstones, restart), SLO-driven autoscaling up
and down, the /debug/state fleet view, and the fleet-wide tenant
snapshot merge.

Tiering: every multi-replica warmup costs ~10 jit compiles on a 1-core
CI box, so the soak-shaped tests ride the ``slow`` tier (the tier-1
budget is already nearly spent by the rest of the suite); tier-1 keeps
the surface smoke (oracle parity through a cold 2-replica fleet),
submit validation, and the pure snapshot-merge unit. The BENCH_FLEET
soak re-proves the slow tier's gates end to end on every bench run."""
import time

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving.fleet import FleetRouter, fleet_debug_state
from mxnet_tpu.serving.tenancy import aggregate_snapshots
from mxnet_tpu.telemetry import httpd as _httpd
from mxnet_tpu.telemetry import slo as _slo
from mxnet_tpu.telemetry import tracing as _tracing


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.disable()
    yield
    chaos.disable()


@pytest.fixture(scope="module")
def tiny():
    model = serving.TinyDecoder(vocab_size=32, num_layers=2, num_heads=4,
                                head_dim=8, num_kv_heads=2)
    return model, model.init_params(0)


def _factory(tiny, **kw):
    model, params = tiny
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("timeout_ms", 0)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)

    def make(name):
        return serving.DecodeEngine(model, params, name=name, **kw)

    return make


def _fname():
    return "fl%d" % np.random.randint(1 << 30)


def _routed(fl):
    fam = telemetry.REGISTRY.get("mxnet_fleet_routed_total")
    return {d: fam.value(fleet=fl.name, decision=d)
            for d in ("affine", "rendezvous", "spill")}


# ---------------------------------------------------------------------------
# single-engine surface: oracle parity, stats, close
# ---------------------------------------------------------------------------

def test_fleet_matches_oracle_through_router(tiny):
    # tier-1 smoke: a cold fleet (no warmup — lazy compiles, ONE prefill
    # rung) still answers oracle-exact through the router; the
    # zero-recompile contract is proven by the slow rolling-swap test
    # and the BENCH_FLEET gate, which do pay for warmup
    model, params = tiny
    rng = np.random.RandomState(7)
    reqs = [(rng.randint(1, 32, int(rng.randint(9, 14))).astype(np.int32),
             int(rng.randint(1, 5))) for _ in range(9)]
    with FleetRouter(_factory(tiny, prefill_buckets=(16,), max_seq_len=32),
                     replicas=2, name=_fname()) as fl:
        futs = [fl.submit(p, m) for p, m in reqs]
        for f, (p, m) in zip(futs, reqs):
            np.testing.assert_array_equal(
                f.result(timeout=120), model.reference_generate(params, p, m))
        s = fl.stats()
        assert s["replicas_live"] == 2
        assert s["router"]["submitted"] == 9
        assert s["router"]["completed"] == 9
        assert s["router"]["failed"] == 0
        assert len(s["replicas"]) == 2
        # the two replicas split the traffic (router-side bookkeeping)
        assert sum(s["replicas"][r]["completed"]
                   for r in s["replicas"]) == 9
        assert "default" in s["tenants"]
        assert s["tenants"]["default"]["completed"] == 9
    assert fl.closed
    assert fl.close() == 0  # idempotent
    with pytest.raises(serving.ServerClosedError):
        fl.submit([1, 2, 3], 2)


def test_fleet_submit_validation_propagates(tiny):
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        with pytest.raises(MXNetError):
            fl.submit([], 4)
        with pytest.raises(MXNetError):
            fl.submit([1] * 40, 40)  # exceeds max_seq_len on EVERY replica
        assert fl.stats()["router"]["failed"] == 1  # door-reject, no spin


# ---------------------------------------------------------------------------
# placement: affinity, rendezvous, spillover
# ---------------------------------------------------------------------------

def _prefix_workload(rng, n, prefix_len=16, tail=4, max_new=4):
    prefix = rng.randint(1, 32, prefix_len).astype(np.int32)
    return [(np.concatenate([prefix, rng.randint(1, 32, tail)
                             .astype(np.int32)]), max_new)
            for _ in range(n)]


@pytest.mark.slow
def test_prefix_affinity_pins_shared_prefix_to_one_replica(tiny):
    model, params = tiny
    rng = np.random.RandomState(3)
    reqs = _prefix_workload(rng, 8)
    with FleetRouter(_factory(tiny), replicas=3, name=_fname()) as fl:
        fl.warmup()
        for p, m in reqs:
            np.testing.assert_array_equal(
                fl.generate(p, m, timeout=120),
                model.reference_generate(params, p, m))
        counts = [row["routed"]
                  for row in fl.debug_state()["replicas"].values()]
        # every request shares the 2-page prefix: after the first lands,
        # the index pins the rest to the same replica
        assert max(counts) == len(reqs)
        routed = _routed(fl)
        assert routed["affine"] == len(reqs) - 1
        assert fl.stats()["prefix_hit_ratio"] > 0.5


@pytest.mark.slow
def test_fleet_hit_ratio_matches_single_replica(tiny):
    # the acceptance metric: a fleet of 3 keeps >= 0.9x the prefix-hit
    # ratio of a single replica on a shared-prefix workload
    rng = np.random.RandomState(11)
    reqs = _prefix_workload(rng, 10)
    ratios = []
    for n in (1, 3):
        with FleetRouter(_factory(tiny), replicas=n, name=_fname()) as fl:
            fl.warmup()
            for p, m in reqs:
                fl.generate(p, m, timeout=120)
            ratios.append(fl.stats()["prefix_hit_ratio"])
    single, fleet = ratios
    assert single > 0
    assert fleet >= 0.9 * single


@pytest.mark.slow
def test_cold_placement_is_rendezvous_then_affine(tiny):
    rng = np.random.RandomState(5)
    p = rng.randint(1, 32, 12).astype(np.int32)
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        fl.generate(p, 3, timeout=120)
        first = _routed(fl)
        assert first["rendezvous"] == 1 and first["affine"] == 0
        fl.generate(p, 3, timeout=120)
        second = _routed(fl)
        assert second["affine"] == 1  # the index remembers the placement


@pytest.mark.slow
def test_spillover_when_affine_replica_is_loaded(tiny):
    model, params = tiny
    rng = np.random.RandomState(9)
    # every request shares a prefix -> all affine to ONE replica; with
    # 1 slot and a deep backlog the router must spill past it once the
    # affine target carries >= MXNET_FLEET_SPILL_DEPTH in flight
    reqs = _prefix_workload(rng, 8, max_new=6)
    with FleetRouter(_factory(tiny, num_slots=1, queue_depth=16),
                     replicas=2, name=_fname()) as fl:
        fl.warmup()
        futs = [fl.submit(p, m) for p, m in reqs]
        for f, (p, m) in zip(futs, reqs):
            np.testing.assert_array_equal(
                f.result(timeout=120), model.reference_generate(params, p, m))
        counts = [row["routed"]
                  for row in fl.debug_state()["replicas"].values()]
        assert min(counts) > 0, "spillover never engaged: %r" % counts
        assert _routed(fl)["spill"] > 0


@pytest.mark.slow
def test_spillover_on_door_reject(tiny, monkeypatch):
    # disarm the proactive spill so the exception path carries: the
    # affine replica sheds at its door (queue full) and the router walks
    # to the next live replica instead of failing the caller
    monkeypatch.setenv("MXNET_FLEET_SPILL_DEPTH", "1000")
    model, params = tiny
    rng = np.random.RandomState(13)
    reqs = _prefix_workload(rng, 4, max_new=8)
    with FleetRouter(_factory(tiny, num_slots=1, queue_depth=2),
                     replicas=2, name=_fname()) as fl:
        fl.warmup()
        futs = [fl.submit(p, m, tenant="gold" if i % 2 else "bronze")
                for i, (p, m) in enumerate(reqs)]
        for f, (p, m) in zip(futs, reqs):
            np.testing.assert_array_equal(
                f.result(timeout=120), model.reference_generate(params, p, m))
        counts = [row["routed"]
                  for row in fl.debug_state()["replicas"].values()]
        assert min(counts) > 0, "door-reject spill never engaged: %r" % counts
        # fleet-wide tenant merge sees both tenants' traffic
        tens = fl.stats()["tenants"]
        assert tens["gold"]["completed"] == 2
        assert tens["bronze"]["completed"] == 2


# ---------------------------------------------------------------------------
# lifecycle: drain, add, rolling swap
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_drain_replica_zero_drop_and_counted(tiny):
    model, params = tiny
    rng = np.random.RandomState(17)
    reqs = _prefix_workload(rng, 5, max_new=5)  # all pin to one replica
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        futs = [fl.submit(p, m) for p, m in reqs]
        target = max(fl.debug_state()["replicas"].items(),
                     key=lambda kv: kv[1]["routed"])[0]
        drained = fl.drain_replica(target)
        for f, (p, m) in zip(futs, reqs):
            np.testing.assert_array_equal(
                f.result(timeout=120), model.reference_generate(params, p, m))
        assert fl.stats()["replicas_live"] == 1
        assert target not in fl.debug_state()["replicas"]
        # the return value IS the metric (the zero-drop receipt)
        fam = telemetry.REGISTRY.get("mxnet_serving_drain_completed_total")
        assert fam.value(server=target) == drained
        # nothing lost: every request completed exactly once somewhere
        assert fl.stats()["router"]["completed"] == len(reqs)


@pytest.mark.slow
def test_add_replica_takes_traffic(tiny):
    model, params = tiny
    rng = np.random.RandomState(19)
    with FleetRouter(_factory(tiny), replicas=1, name=_fname()) as fl:
        fl.warmup()
        added = fl.add_replica()
        assert fl.stats()["replicas_live"] == 2
        assert added in fl.debug_state()["replicas"]
        # cold prompts rendezvous over BOTH replicas now
        seen = set()
        for i in range(12):
            p = rng.randint(1, 32, 12).astype(np.int32)
            fl.generate(p, 2, timeout=120)
            for name, row in fl.debug_state()["replicas"].items():
                if row["routed"]:
                    seen.add(name)
        assert len(seen) == 2


@pytest.mark.slow
def test_rolling_swap_zero_drop_zero_recompiles(tiny):
    model, params = tiny
    params_b = model.init_params(1)
    rng = np.random.RandomState(23)
    reqs = [(rng.randint(1, 32, 10).astype(np.int32), 5) for _ in range(6)]
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        fl.register_variant("v2", params_b)
        futs = [fl.submit(p, m) for p, m in reqs]  # in flight across swap
        assert fl.rolling_swap(variant="v2", timeout=60) == 2
        for f in futs:
            assert f.result(timeout=120) is not None  # zero dropped
        p = rng.randint(1, 32, 9).astype(np.int32)
        np.testing.assert_array_equal(  # post-swap traffic runs v2
            fl.generate(p, 4, timeout=120),
            model.reference_generate(params_b, p, 4))
        s = fl.stats()
        assert s["steady_state_recompiles"] == 0
        for row in s["replicas"].values():
            assert row["active_variant"] == "v2"


# ---------------------------------------------------------------------------
# failure containment: kill, chaos, exactly-once
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_kill_replica_reroutes_exactly_once(tiny, monkeypatch):
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "1.0")
    model, params = tiny
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(1, 32, 10).astype(np.int32), 6) for _ in range(12)]
    with FleetRouter(_factory(tiny), replicas=3, name=_fname()) as fl:
        fl.warmup()
        futs = [fl.submit(p, m) for p, m in reqs]
        victim = fl.debug_state()["replicas"]  # kill the busiest
        victim = max(victim.items(), key=lambda kv: kv[1]["inflight"])[0]
        fl.kill_replica(victim)
        for f, (p, m) in zip(futs, reqs):
            np.testing.assert_array_equal(
                f.result(timeout=120), model.reference_generate(params, p, m))
        s = fl.stats()["router"]
        assert s["resubmitted"] >= 1
        assert s["completed"] == len(reqs)
        # exactly-once, proven on the trace terminal contract: every
        # fleet trace carries AT MOST one terminal hop
        terminals = ("complete", "error", "shed", "timeout", "rejected")
        fleet_traces = 0
        for tid in _tracing.trace_ids():
            tr = _tracing.get_trace(tid)
            if not tr or tr.get("plane") != "fleet":
                continue
            fleet_traces += 1
            terms = [e for e in tr["events"] if e["kind"] in terminals]
            assert len(terms) <= 1, (tid, terms)
        assert fleet_traces >= len(reqs)
        # the dead replica restarts and rejoins (daemon rebuild)
        for _ in range(300):
            if fl.debug_state()["replicas"][victim]["state"] == "live":
                break
            time.sleep(0.05)
        row = fl.debug_state()["replicas"][victim]
        assert row["state"] == "live" and row["deaths"] == 1
        assert row["breaker"] == "closed"  # restart probe closed it
        p = rng.randint(1, 32, 8).astype(np.int32)
        np.testing.assert_array_equal(  # the rebuilt replica serves
            fl.generate(p, 3, timeout=120),
            model.reference_generate(params, p, 3))


@pytest.mark.slow
def test_kill_without_restart_isolates_via_breaker(tiny):
    model, params = tiny
    rng = np.random.RandomState(29)
    reqs = _prefix_workload(rng, 4)
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        for p, m in reqs[:2]:
            fl.generate(p, m, timeout=120)
        victim = max(fl.debug_state()["replicas"].items(),
                     key=lambda kv: kv[1]["routed"])[0]
        before = fl.debug_state()["replicas"][victim]["routed"]
        fl.kill_replica(victim, restart=False)
        row = fl.debug_state()["replicas"][victim]
        assert row["state"] == "dead" and row["breaker"] == "open"
        assert fl.stats()["router"]["index_entries"] == 0  # tombstoned
        for p, m in reqs[2:]:  # same prefix now re-routes elsewhere
            np.testing.assert_array_equal(
                fl.generate(p, m, timeout=120),
                model.reference_generate(params, p, m))
        assert fl.debug_state()["replicas"][victim]["routed"] == before


@pytest.mark.slow
def test_chaos_site_kills_replica_at_routing(tiny):
    model, params = tiny
    rng = np.random.RandomState(31)
    p = rng.randint(1, 32, 10).astype(np.int32)
    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        # probe where this prompt lands, then arm the fault at exactly
        # that replica: the affine re-submit MUST walk into it
        fl.generate(p, 3, timeout=120)
        victim = max(fl.debug_state()["replicas"].items(),
                     key=lambda kv: kv[1]["routed"])[0]
        idx = int(victim.rsplit(".r", 1)[1])
        with chaos.active("seed=1,site=serving.fleet.replica.%d,at=1" % idx):
            # the route hits the fault: the router contains the death
            # and re-routes before the caller ever sees it
            np.testing.assert_array_equal(
                fl.generate(p, 3, timeout=120),
                model.reference_generate(params, p, 3))
        assert chaos.injected_counts() == {}  # disabled again outside
        assert fl.debug_state()["replicas"][victim]["deaths"] == 1
        assert fl.stats()["router"]["completed"] == 2


# ---------------------------------------------------------------------------
# autoscaling
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_autoscaler_scales_up_on_queue_depth_burn(tiny):
    _slo.reset()
    with FleetRouter(_factory(tiny), replicas=1, name=_fname(),
                     max_replicas=2) as fl:
        fl.warmup()
        rep = next(iter(fl.debug_state()["replicas"]))
        # synthetic QueueDepthBurn on the replica: mean depth/bound > 0.9
        _slo.note_bound("queue_depth", rep, 10)
        g = telemetry.gauge("mxnet_serving_queue_depth", labels=("server",))
        g.set(9.5, server=rep)
        event = fl.autoscale_tick()
        assert event is not None and event["action"] == "up"
        assert event["reason"] == "QueueDepthBurn"
        assert fl.stats()["replicas_live"] == 2
        assert fl.stats()["router"]["last_scale"]["action"] == "up"
        fam = telemetry.REGISTRY.get("mxnet_fleet_scale_events_total")
        assert fam.value(fleet=fl.name, action="up") == 1
        # cooldown gates the next decision
        assert fl.autoscale_tick() is None
        # the cap holds: even under burn, never past max_replicas
        g.set(9.5, server=rep)
        assert fl.autoscale_tick(now=time.monotonic() + 3600) is None \
            or fl.stats()["replicas_live"] <= 2
        g.set(0.0, server=rep)
    _slo.reset()


@pytest.mark.slow
def test_autoscaler_drains_coldest_on_occupancy_collapse(tiny):
    _slo.reset()
    with FleetRouter(_factory(tiny), replicas=2, name=_fname(),
                     min_replicas=1) as fl:
        fl.warmup()
        g = telemetry.gauge("mxnet_decode_slot_occupancy",
                            labels=("server",))
        for rep in fl.debug_state()["replicas"]:
            g.set(0.0, server=rep)
        event = fl.autoscale_tick()
        assert event is not None and event["action"] == "down"
        assert event["reason"] == "occupancy_collapse"
        assert fl.stats()["replicas_live"] == 1
        # never below min_replicas
        assert fl.autoscale_tick(now=time.monotonic() + 3600) is None
        assert fl.stats()["replicas_live"] == 1
    _slo.reset()


# ---------------------------------------------------------------------------
# observation: /debug/state fleet view, snapshot merge
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_debug_state_view_over_httpd(tiny):
    import json
    from urllib.request import urlopen

    with FleetRouter(_factory(tiny), replicas=2, name=_fname()) as fl:
        fl.warmup()
        fl.generate([1, 2, 3, 4], 2, timeout=120)
        view = fleet_debug_state()
        assert fl.name in view
        row = view[fl.name]
        assert set(row["replicas"]) == set(fl.debug_state()["replicas"])
        for rep in row["replicas"].values():
            assert {"state", "breaker", "inflight", "routed",
                    "deaths"} <= set(rep)
        srv = _httpd.start_httpd(port=0)
        try:
            host, port = srv.server_address[:2]
            with urlopen("http://%s:%d/debug/state" % (host, port),
                         timeout=10) as resp:
                doc = json.loads(resp.read())
            assert fl.name in doc["fleet"]
            rep0 = next(iter(doc["fleet"][fl.name]["replicas"].values()))
            assert rep0["state"] == "live"
            assert "queue_depth" in rep0 and "pages_in_use" in rep0
        finally:
            _httpd.stop_httpd()


def test_aggregate_snapshots_merges_per_tenant():
    a = {"gold": {"submitted": 3, "completed": 2, "queue_ms_p99_ms": 5.0,
                  "queue_ms_count": 2, "breaker": "closed",
                  "weight": 3.0},
         "bronze": {"submitted": 1, "completed": 1, "breaker": "open"}}
    b = {"gold": {"submitted": 4, "completed": 4, "queue_ms_p99_ms": 9.0,
                  "queue_ms_count": 4, "breaker": "half_open",
                  "weight": 3.0}}
    out = aggregate_snapshots([a, b])
    assert out["gold"]["submitted"] == 7
    assert out["gold"]["completed"] == 6
    assert out["gold"]["queue_ms_count"] == 6
    assert out["gold"]["queue_ms_p99_ms"] == 9.0  # worst replica wins
    assert out["gold"]["breaker"] == "half_open"  # severity order
    assert out["gold"]["weight"] == 3.0
    assert out["bronze"]["breaker"] == "open"
    assert aggregate_snapshots([]) == {}
