"""AOT no-Python deployment (VERDICT r4 item 7).

export_model → (a) portable jax.export StableHLO artifact round-trips and
matches the live net; (b) the TF-SavedModel form runs from a pure C++
binary (cpp-package/predict_aot_demo.cc) linked against the TensorFlow C
API with **no libpython**, matching the Python forward bit-for-bit-ish.
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    from mxnet_tpu import aot, gluon, nd

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    rs = np.random.RandomState(0)
    x = rs.randn(2, 8).astype(np.float32)
    net(nd.array(x))  # materialize params
    out_dir = str(tmp_path_factory.mktemp("aot"))
    manifest = aot.export_model(net, (2, 8), out_dir)
    expect = net(nd.array(x)).asnumpy()
    return out_dir, manifest, x, expect


def test_stablehlo_roundtrip(exported):
    from mxnet_tpu import aot

    out_dir, manifest, x, expect = exported
    assert os.path.exists(os.path.join(out_dir, "model.stablehlo"))
    got = aot.predict_stablehlo(out_dir, x)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    assert manifest["output_shape"] == [2, 4]


def test_c_runner_no_python(exported, tmp_path):
    out_dir, manifest, x, expect = exported
    tf_dir = None
    for p in sys.path:
        cand = Path(p) / "tensorflow"
        if (cand / "libtensorflow_cc.so.2").exists():
            tf_dir = cand
            break
    if tf_dir is None:
        pytest.skip("tensorflow C libraries not available")

    binary = tmp_path / "predict_aot_demo"
    compile_cmd = [
        "g++", "-std=c++17", "-O1",
        str(REPO / "cpp-package" / "predict_aot_demo.cc"),
        "-I", str(tf_dir / "include"),
        str(tf_dir / "libtensorflow_cc.so.2"),
        str(tf_dir / "libtensorflow_framework.so.2"),
        "-Wl,-rpath," + str(tf_dir),
        "-o", str(binary),
    ]
    out = subprocess.run(compile_cmd, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr[-3000:]

    # the whole point: the runner must not link libpython
    ldd = subprocess.run(["ldd", str(binary)], capture_output=True,
                         text=True, timeout=60)
    assert "libpython" not in ldd.stdout, ldd.stdout

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    run = subprocess.run(
        [str(binary), out_dir, manifest["tf_input_tensor"],
         manifest["tf_output_tensor"], str(x.size)],
        input=x.tobytes(), capture_output=True, timeout=300, env=env)
    assert run.returncode == 0, run.stderr[-2000:].decode(errors="replace")
    got = np.frombuffer(run.stdout, np.float32).reshape(expect.shape)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
