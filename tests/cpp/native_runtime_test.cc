/*
 * C++ unit tests for the native runtime — the counterpart of the
 * reference's tests/cpp/{engine,storage} googletest suites
 * (threaded_engine_test.cc dependency stress, storage_test.cc allocator
 * reuse), written against the public C ABI with plain asserts since
 * googletest is not part of this toolchain.
 *
 * Built + run by tests/test_native.py::test_cpp_unit_suite:
 *   g++ -std=c++17 -O2 tests/cpp/native_runtime_test.cc -Isrc -Lsrc/build \
 *       -lmxtpu -Wl,-rpath,src/build -o /tmp/native_runtime_test
 */
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include "mxtpu.h"

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAILED %s:%d: %s (last error: %s)\n",         \
                   __FILE__, __LINE__, #cond, MXTPUGetLastError());       \
      return 1;                                                           \
    }                                                                     \
  } while (0)

static int TestStoragePool() {
  void *a = nullptr;
  CHECK(MXTPUStorageAlloc(3000, &a) == 0);
  std::memset(a, 7, 3000);
  CHECK(MXTPUStorageFree(a) == 0);
  void *b = nullptr;
  CHECK(MXTPUStorageAlloc(2500, &b) == 0);  // same 4096 bucket
  CHECK(b == a);                            // pool reuse
  uint64_t in_use, pooled, peak, nalloc, nhit;
  CHECK(MXTPUStorageStats(&in_use, &pooled, &peak, &nalloc, &nhit) == 0);
  CHECK(nhit >= 1);
  CHECK(MXTPUStorageDirectFree(b) == 0);
  CHECK(MXTPUStorageFree(b) != 0);  // double free detected
  std::printf("storage pool OK\n");
  return 0;
}

struct Counter {
  std::vector<int> *counters;
  int idx;
};

static int BumpNonAtomic(void *arg) {
  auto *c = static_cast<Counter *>(arg);
  int cur = (*c->counters)[c->idx];
  std::this_thread::sleep_for(std::chrono::microseconds(200));
  (*c->counters)[c->idx] = cur + 1;  // engine must serialize writers
  return 0;
}

static int TestEngineStress() {
  std::mt19937 rng(42);
  const int kVars = 5, kOps = 200;
  std::vector<MXTPUVarHandle> vars(kVars);
  for (auto &v : vars) CHECK(MXTPUEngineNewVar(&v) == 0);
  std::vector<int> counters(kVars, 0);
  std::vector<int> expected(kVars, 0);
  std::vector<Counter> args;
  args.reserve(kOps);
  for (int i = 0; i < kOps; ++i) {
    int vi = (int)(rng() % kVars);
    expected[vi]++;
    args.push_back(Counter{&counters, vi});
    uint64_t id;
    // random extra read deps exercise the grant bookkeeping
    MXTPUVarHandle cv = vars[(vi + 1) % kVars];
    int nc = (rng() % 2) ? 1 : 0;
    CHECK(MXTPUEnginePushAsync(BumpNonAtomic, &args.back(), nc ? &cv : nullptr,
                               nc, &vars[vi], 1, 0, &id) == 0);
  }
  CHECK(MXTPUEngineWaitForAll() == 0);
  for (int i = 0; i < kVars; ++i) CHECK(counters[i] == expected[i]);
  for (auto v : vars) CHECK(MXTPUEngineDeleteVar(v) == 0);
  std::printf("engine stress OK (%d ops)\n", kOps);
  return 0;
}

static int FailingOp(void *) { return 1; }

static int TestEngineErrorPropagation() {
  MXTPUVarHandle v;
  CHECK(MXTPUEngineNewVar(&v) == 0);
  uint64_t id;
  CHECK(MXTPUEnginePushAsync(FailingOp, nullptr, nullptr, 0, &v, 1, 0, &id) == 0);
  CHECK(MXTPUEngineWaitForVar(v) != 0);        // failure surfaces
  CHECK(MXTPUEngineWaitForVar(v) == 0);        // rethrow-once
  CHECK(MXTPUEngineDeleteVar(v) == 0);
  std::printf("engine error propagation OK\n");
  return 0;
}

static int TestRecordIO() {
  const char *path = "/tmp/mxtpu_cpp_test.rec";
  void *w = nullptr;
  CHECK(MXTPURecordIOWriterCreate(path, &w) == 0);
  // payload embedding the magic word must survive the split/rejoin
  uint32_t magic = 0xced7230a;
  std::vector<char> payload(64, 'x');
  std::memcpy(payload.data() + 10, &magic, 4);
  uint64_t pos;
  CHECK(MXTPURecordIOWriterWrite(w, payload.data(), payload.size(), &pos) == 0);
  CHECK(MXTPURecordIOWriterWrite(w, "", 0, &pos) == 0);  // empty record
  CHECK(MXTPURecordIOWriterClose(w) == 0);

  void *r = nullptr;
  CHECK(MXTPURecordIOReaderCreate(path, &r) == 0);
  const char *rec;
  size_t n;
  CHECK(MXTPURecordIOReaderNext(r, &rec, &n) == 0);
  CHECK(n == payload.size() && std::memcmp(rec, payload.data(), n) == 0);
  CHECK(MXTPURecordIOReaderNext(r, &rec, &n) == 0);
  CHECK(rec != nullptr && n == 0);  // empty record, not EOF
  CHECK(MXTPURecordIOReaderNext(r, &rec, &n) == 0);
  CHECK(rec == nullptr);            // EOF
  CHECK(MXTPURecordIOReaderClose(r) == 0);
  std::printf("recordio OK\n");
  return 0;
}

int main() {
  int version;
  CHECK(MXTPUGetVersion(&version) == 0);
  if (TestStoragePool()) return 1;
  if (TestEngineStress()) return 1;
  if (TestEngineErrorPropagation()) return 1;
  if (TestRecordIO()) return 1;
  std::printf("ALL C++ TESTS PASSED\n");
  return 0;
}
