"""Telemetry tests: registry semantics + thread safety, span double-sink,
recompile/transfer accounting, exporters, disabled-path freedom, and the
serving/training smoke the acceptance criteria are stated against."""
import json
import re
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.telemetry import registry as reg_mod


@pytest.fixture(autouse=True)
def _reset_registry():
    prev = telemetry.set_enabled(True)
    telemetry.REGISTRY.clear_data()
    yield
    telemetry.REGISTRY.clear_data()
    telemetry.set_enabled(prev)


# ---------------------------------------------------------------------------
# registry core
# ---------------------------------------------------------------------------

def test_counter_gauge_basics():
    c = telemetry.counter("mxnet_t_basic_total", "help", labels=("k",))
    c.inc(k="a")
    c.inc(2.5, k="a")
    c.inc(k="b")
    assert c.value(k="a") == 3.5
    assert c.value(k="b") == 1.0
    assert c.value(k="never") == 0.0
    with pytest.raises(mx.MXNetError):
        c.inc(-1, k="a")  # counters are monotonic
    g = telemetry.gauge("mxnet_t_basic_gauge")
    g.set(5)
    g.dec(2)
    assert g.value() == 3.0


def test_get_or_create_and_kind_mismatch():
    a = telemetry.counter("mxnet_t_shared_total", labels=("x",))
    b = telemetry.counter("mxnet_t_shared_total", labels=("x",))
    assert a is b  # instrumentation points in different modules share series
    with pytest.raises(mx.MXNetError):
        telemetry.gauge("mxnet_t_shared_total", labels=("x",))
    with pytest.raises(mx.MXNetError):
        telemetry.counter("mxnet_t_shared_total", labels=("y",))


def test_label_validation():
    with pytest.raises(mx.MXNetError):
        telemetry.counter("bad name")
    with pytest.raises(mx.MXNetError):
        telemetry.counter("mxnet_t_badlabel_total", labels=("bad-label",))
    c = telemetry.counter("mxnet_t_labels_total", labels=("a", "b"))
    with pytest.raises(mx.MXNetError):
        c.inc(a="1")  # missing label
    with pytest.raises(mx.MXNetError):
        c.inc(a="1", b="2", c="3")  # extra label


def test_registry_thread_safety_concurrent_increments():
    c = telemetry.counter("mxnet_t_race_total", labels=("who",))
    h = telemetry.histogram("mxnet_t_race_ms", labels=())
    n_threads, n_iter = 8, 1000

    def worker(i):
        for _ in range(n_iter):
            c.inc(who="t%d" % (i % 2))
            h.observe(1.0)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = c.value(who="t0") + c.value(who="t1")
    assert total == n_threads * n_iter  # no lost read-modify-write updates
    assert h.count() == n_threads * n_iter


def test_histogram_percentile_sanity():
    h = telemetry.histogram("mxnet_t_pct_ms", labels=("s",), reservoir=4096)
    for v in range(1, 1001):  # 1..1000
        h.observe(float(v), s="w")
    assert h.count(s="w") == 1000
    assert abs(h.percentile(50, s="w") - 500) <= 10
    assert abs(h.percentile(99, s="w") - 990) <= 10
    (row,) = h.series()
    assert row["sum"] == sum(range(1, 1001))
    assert row["p50"] <= row["p90"] <= row["p99"]


def test_histogram_reservoir_bounded():
    h = telemetry.histogram("mxnet_t_bounded_ms", reservoir=64)
    for v in range(10000):
        h.observe(float(v))
    (row,) = h.series()
    assert row["count"] == 10000      # exact totals survive the window
    assert row["window"] == 64        # ...but memory stays bounded
    assert row["p50"] >= 9000         # window holds only recent values


def test_clear_data_keeps_handles_working():
    c = telemetry.counter("mxnet_t_clear_total")
    c.inc()
    telemetry.REGISTRY.clear_data()
    assert c.value() == 0.0
    c.inc()
    assert c.value() == 1.0


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

class _PoisonLock:
    """Lock stand-in that fails the test if anything acquires it."""

    def __enter__(self):
        raise AssertionError("disabled telemetry path acquired a lock")

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **kw):
        raise AssertionError("disabled telemetry path acquired a lock")

    release = acquire


def test_disabled_path_does_no_locking():
    c = telemetry.counter("mxnet_t_off_total", labels=("k",))
    g = telemetry.gauge("mxnet_t_off_gauge")
    h = telemetry.histogram("mxnet_t_off_ms")
    telemetry.set_enabled(False)
    try:
        c._lock = g._lock = h._lock = _PoisonLock()
        c.inc(k="a")
        g.set(1)
        h.observe(2.0)
        with telemetry.span("off-region"):
            pass
        telemetry.record_transfer("asnumpy", (np.zeros(4),))
    finally:
        c._lock, g._lock, h._lock = (threading.Lock(), threading.Lock(),
                                     threading.Lock())
        telemetry.set_enabled(True)
    assert c.value(k="a") == 0.0  # nothing was recorded while off


def test_disabled_jit_call_passthrough():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    telemetry.set_enabled(False)
    out = telemetry.jit_call("t.off_site", f, jnp.ones(2))
    assert float(np.asarray(out)[0]) == 2.0
    telemetry.set_enabled(True)
    assert telemetry.RECOMPILES.value(site="t.off_site") == 0.0


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------

def test_recompile_counter_fires_exactly_once_for_same_shape():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2)
    for _ in range(5):
        telemetry.jit_call("t.same_shape", f, jnp.ones((3,)))
    assert telemetry.RECOMPILES.value(site="t.same_shape") == 1.0
    assert telemetry.COMPILE_SECONDS.value(site="t.same_shape") > 0.0
    # a new shape is a real recompile and must be counted
    telemetry.jit_call("t.same_shape", f, jnp.ones((4,)))
    assert telemetry.RECOMPILES.value(site="t.same_shape") == 2.0


def test_executor_recompile_accounting():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    for _ in range(3):
        ex.forward(is_train=False)
    assert telemetry.RECOMPILES.value(site="executor.forward") == 1.0


def test_transfer_accounting_fetch_host_and_asnumpy():
    from mxnet_tpu.base import fetch_host

    arrs = [nd.ones((4, 4)), nd.ones((2,))]
    out = fetch_host(arrs)
    assert telemetry.TRANSFERS.value(path="fetch_host") == 1.0  # ONE batched
    expect = sum(int(a.nbytes) for a in out)
    assert telemetry.TRANSFER_BYTES.value(path="fetch_host") == expect

    nd.ones((8, 8)).asnumpy()
    assert telemetry.TRANSFERS.value(path="asnumpy") == 1.0
    assert telemetry.TRANSFER_BYTES.value(path="asnumpy") == 8 * 8 * 4


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_feeds_registry_and_profiler():
    mx.profiler.set_state("run")
    try:
        with telemetry.span("t_region", category="t_cat"):
            pass
    finally:
        mx.profiler.set_state("stop")
    assert telemetry.spans.SPAN_MS.count(category="t_cat",
                                         span="t_region") == 1
    assert any(e["name"] == "t_region" and e["cat"] == "t_cat"
               for e in mx.profiler._events)
    mx.profiler._events.clear()


def test_span_as_decorator():
    calls = []

    @telemetry.span("t_deco")
    def work(x):
        calls.append(x)
        return x + 1

    assert work(1) == 2
    assert calls == [1]
    assert telemetry.spans.SPAN_MS.count(category="span", span="t_deco") == 1


def test_profiler_counter_bridged_to_gauge():
    ctr = mx.profiler.Domain("t_dom").new_counter("t_ctr", 3)
    ctr.increment(4)
    assert telemetry.PROFILER_COUNTER.value(domain="t_dom",
                                            counter="t_ctr") == 7.0


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_output_format():
    c = telemetry.counter("mxnet_t_prom_total", "counter help", labels=("k",))
    c.inc(3, k='va"l\\ue')  # escaping-hostile label value
    h = telemetry.histogram("mxnet_t_prom_ms", "hist help", labels=())
    h.observe(1.0)
    h.observe(2.0)
    text = telemetry.render_prometheus()
    assert "# HELP mxnet_t_prom_total counter help" in text
    assert "# TYPE mxnet_t_prom_total counter" in text
    assert 'mxnet_t_prom_total{k="va\\"l\\\\ue"} 3' in text
    assert "# TYPE mxnet_t_prom_ms summary" in text
    assert 'mxnet_t_prom_ms{quantile="0.5"}' in text
    assert "mxnet_t_prom_ms_sum 3" in text
    assert "mxnet_t_prom_ms_count 2" in text
    # every sample line is NAME{labels} VALUE parseable
    sample = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? -?[0-9.e+-]+$")
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert sample.match(line), line


def test_snapshot_shape_and_json_round_trip():
    telemetry.counter("mxnet_t_snap_total", labels=("k",)).inc(k="a")
    snap = telemetry.snapshot()
    assert snap["enabled"] is True
    m = snap["metrics"]["mxnet_t_snap_total"]
    assert m["type"] == "counter"
    assert m["series"] == [{"labels": {"k": "a"}, "value": 1.0}]
    json.dumps(snap)  # JSONL-emitter requirement: always serializable


def test_emitter_appends_jsonl(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    telemetry.counter("mxnet_t_emit_total").inc()
    em = telemetry.Emitter(60.0, path)
    assert em.emit_once()
    assert em.emit_once()
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    doc = json.loads(lines[0])
    assert "mxnet_t_emit_total" in doc["metrics"]


def test_start_emitter_disabled_by_default():
    assert telemetry.start_emitter() is None  # MXNET_TELEMETRY_EMIT_SECS=0


def test_start_emitter_runs_and_stops(tmp_path):
    path = str(tmp_path / "bg.jsonl")
    em = telemetry.start_emitter(0.2, path)
    try:
        assert em is not None and em.is_alive()
        assert telemetry.start_emitter(0.2, path) is em  # idempotent
    finally:
        telemetry.stop_emitter()
    assert not em.is_alive()


def test_emitter_atexit_flushes_short_lived_process(tmp_path):
    """Regression (ISSUE-15 satellite): a run that dies BETWEEN emit
    intervals must still leave its final snapshot — start_emitter
    registers an atexit flush, so a short-lived subprocess whose
    interval (1h) never elapses still writes its tail line."""
    import os
    import pathlib
    import subprocess
    import sys

    path = str(tmp_path / "tail.jsonl")
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_TELEMETRY_EMIT_SECS="3600",
               MXNET_TELEMETRY_EMIT_PATH=path,
               PYTHONPATH=repo)
    code = ("from mxnet_tpu import telemetry\n"
            "telemetry.counter('mxnet_atexit_probe_total').inc()\n")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          timeout=180, capture_output=True)
    assert proc.returncode == 0, proc.stderr[-800:]
    with open(path) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    # the interval never elapsed: every line on disk came from the
    # atexit flush, and it carries the counter bumped mid-run
    assert lines, "atexit flush wrote nothing"
    assert "mxnet_atexit_probe_total" in lines[-1]["metrics"]


# ---------------------------------------------------------------------------
# acceptance smoke: serving + training publish >= 15 distinct series
# ---------------------------------------------------------------------------

def test_serving_plus_training_smoke_series():
    from mxnet_tpu import gluon, serving

    # training: one executor fwd/bwd (recompile + span series)
    data = mx.sym.var("data")
    net_s = mx.sym.FullyConnected(data=data, num_hidden=4, name="fct")
    ex = net_s.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    ex.forward(is_train=True)
    ex.backward()
    ex.outputs[0].asnumpy()

    # serving: tiny MLP behind a Server (request/latency/bucket series)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.float32)))
    srv = serving.serve_block(net, sample_shape=(4,), buckets=(1, 4),
                              max_delay_ms=1.0, name="t_smoke")
    try:
        srv.warmup()
        futs = [srv.submit(np.random.rand(4).astype(np.float32))
                for _ in range(8)]
        for f in futs:
            f.result(timeout=30)
        stats = srv.stats()
    finally:
        srv.close()
    assert stats["steady_state_recompiles"] == 0
    assert telemetry.STEADY_STATE_RECOMPILES.value(
        site="serving.t_smoke") == 0.0

    text = telemetry.render_prometheus()
    samples = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert len(samples) >= 15, text
    for required in ("mxnet_recompiles_total",
                     "mxnet_host_transfer_bytes_total",
                     "mxnet_serving_latency_ms"):
        assert any(s.startswith(required) for s in samples), required
    # serving latency exports the p50/p99 summary the criteria name
    assert any('quantile="0.5"' in s for s in samples
               if s.startswith("mxnet_serving_latency_ms"))
    assert any('quantile="0.99"' in s for s in samples
               if s.startswith("mxnet_serving_latency_ms"))
