"""regress + benchwatch — the bench-regression sentinel.

Covers the ISSUE-18 sentinel surface: config signatures keep apples
with apples, direction inference, the median+MAD verdict math (noise
absorption, the zero-MAD relative floor, warm-up exclusion, dead-round
``no_value``), history ingestion across all three committed file
shapes, the acceptance replay (a seeded slowdown is flagged; an
unchanged rerun of the committed history produces zero false
verdicts), stamp_line/recent_verdicts, and the benchwatch CLI's exit
codes.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu.telemetry import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    regress.reset()
    yield
    regress.reset()


def _line(value, metric="decode tokens/s", unit="tok/s", extra=None,
          error=None):
    doc = {"metric": metric, "value": value, "unit": unit}
    if extra is not None:
        doc["extra"] = extra
    if error is not None:
        doc["error"] = error
    return doc


def _seed(store, values, **kw):
    for v in values:
        store.add(_line(v, **kw))


# ---------------------------------------------------------------------------
# keys and direction
# ---------------------------------------------------------------------------

def test_config_signature_ignores_measurements_keeps_config():
    a = _line(100.0, extra={"batch": 8, "infer_img_s": 52.9})
    b = _line(900.0, extra={"batch": 8, "infer_img_s": 11.1})
    assert regress.config_signature(a) == regress.config_signature(b)
    c = _line(100.0, extra={"batch": 16, "infer_img_s": 52.9})
    assert regress.config_signature(a) != regress.config_signature(c)
    # unit/metric are part of the key too
    assert regress.config_signature(_line(1, unit="ms")) != \
        regress.config_signature(_line(1, unit="tok/s"))


def test_direction_inference():
    assert regress.direction(_line(1, unit="tok/s")) == "higher"
    assert regress.direction(_line(1, unit="img/s")) == "higher"
    assert regress.direction(_line(1, unit="ms")) == "lower"
    assert regress.direction(_line(1, unit="seconds")) == "lower"
    assert regress.direction(
        _line(1, metric="decode p99 latency", unit="x")) == "lower"
    assert regress.direction(
        _line(1, metric="devprof overhead", unit="frac")) == "lower"


# ---------------------------------------------------------------------------
# verdict math
# ---------------------------------------------------------------------------

def test_insufficient_history_never_confirms():
    store = regress.TrajectoryStore()
    v = store.verdict(_line(100.0))
    assert v["verdict"] == "no_history" and not v["confirmed"]
    _seed(store, [100.0, 101.0])
    v = store.verdict(_line(1.0))  # a 99% drop — but only 2 points
    assert v["verdict"] == "insufficient_history" and not v["confirmed"]


def test_regression_beyond_noise_confirms():
    store = regress.TrajectoryStore()
    _seed(store, [100.0, 102.0, 98.0, 101.0, 99.0])
    v = store.verdict(_line(80.0))  # 20% down, noise is ~1.5
    assert v["verdict"] == "regression" and v["confirmed"]
    assert v["direction"] == "higher" and v["delta"] < 0
    # same magnitude UP is an improvement, not a regression
    v = store.verdict(_line(120.0))
    assert v["verdict"] == "improvement" and not v["confirmed"]


def test_latency_regresses_upward():
    store = regress.TrajectoryStore()
    _seed(store, [10.0, 10.2, 9.8, 10.1], metric="decode p50", unit="ms")
    v = store.verdict(_line(14.0, metric="decode p50", unit="ms"))
    assert v["verdict"] == "regression" and v["confirmed"]
    v = store.verdict(_line(7.0, metric="decode p50", unit="ms"))
    assert v["verdict"] == "improvement"


def test_zero_mad_history_uses_relative_floor():
    # identical repeated values: MAD = 0, so the sigma term is 0 — the
    # 5% relative floor must keep a 1% wobble from flagging
    store = regress.TrajectoryStore()
    _seed(store, [100.0, 100.0, 100.0, 100.0])
    assert store.verdict(_line(99.0))["verdict"] == "ok"
    assert store.verdict(_line(94.0))["verdict"] == "regression"


def test_noise_absorption_within_sigma():
    store = regress.TrajectoryStore()
    _seed(store, [100.0, 110.0, 90.0, 105.0, 95.0])  # MAD 5 -> sigma ~7.4
    assert store.verdict(_line(85.0))["verdict"] == "ok"  # within 4 sigma


def test_warmup_points_are_not_history():
    store = regress.TrajectoryStore()
    for _ in range(5):
        store.add(_line(10.0, extra={"warmup": True}))
    key = store.key(_line(10.0, extra={"warmup": True}))
    assert store.history(key) == []
    # explicit flag works too
    store.add(_line(10.0), warmup=True)
    assert store.history(store.key(_line(10.0))) == []


def test_dead_round_is_no_value_with_error():
    store = regress.TrajectoryStore()
    _seed(store, [100.0, 101.0, 99.0])
    v = store.verdict(_line(None, error="backend init timed out"))
    assert v["verdict"] == "no_value" and not v["confirmed"]
    assert "backend init" in v["error"]
    # and the null point never pollutes history
    store.add(_line(None, error="backend init timed out"))
    assert store.history(store.key(_line(1.0))) == [100.0, 101.0, 99.0]


def test_history_is_bounded():
    store = regress.TrajectoryStore(max_points=4)
    _seed(store, [float(i) for i in range(10)])
    assert store.history(store.key(_line(1.0))) == [6.0, 7.0, 8.0, 9.0]


# ---------------------------------------------------------------------------
# ingestion: the three committed shapes
# ---------------------------------------------------------------------------

def test_iter_bench_lines_raw_wrapper_jsonl(tmp_path):
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(_line(15.31, metric="resnet quick")))
    wrapper = tmp_path / "wrap.json"
    wrapper.write_text(json.dumps(
        {"n": 4, "rc": 1, "parsed": _line(52.63, metric="resnet train"),
         "tail": "noise"}))
    dead = tmp_path / "dead.json"
    dead.write_text(json.dumps(
        {"n": 5, "rc": 1, "parsed": None,
         "tail": "Traceback...\n" + json.dumps(_line(9.9, metric="embedded"))
         + "\nmore noise"}))
    jsonl = tmp_path / "emit.jsonl"
    jsonl.write_text(json.dumps(_line(1.0, metric="a")) + "\n"
                     + "not json\n"
                     + json.dumps(_line(2.0, metric="b")) + "\n")
    got = {m["metric"]: m for p in (raw, wrapper, dead, jsonl)
           for m in regress.iter_bench_lines(str(p))}
    assert set(got) == {"resnet quick", "resnet train", "embedded",
                        "a", "b"}


def test_iter_bench_lines_snapshot_rows(tmp_path):
    snap = {"ts": 1.0, "enabled": True, "metrics": {
        "mxnet_device_time_ms": {"type": "histogram", "series": [
            {"labels": {"site": "serving.decode_step"},
             "p50": 1.25, "p99": 3.0, "sum": 10.0, "count": 8}]},
        "mxnet_tokens_per_device_second": {"type": "gauge", "series": [
            {"labels": {"server": "srv"}, "value": 5000.0}]}}}
    p = tmp_path / "telemetry.jsonl"
    p.write_text(json.dumps(snap) + "\n")
    rows = list(regress.iter_bench_lines(str(p)))
    mets = {r["metric"]: r["value"] for r in rows}
    assert mets["devprof p50 device ms [serving.decode_step]"] == 1.25
    assert mets["devprof tokens/device-s [srv]"] == 5000.0


def test_iter_bench_lines_never_raises(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{truncated")
    assert list(regress.iter_bench_lines(str(bad))) == []
    assert list(regress.iter_bench_lines(str(tmp_path / "missing"))) == []


def test_default_paths_round_order(tmp_path, monkeypatch):
    for name in ("BENCH_r02.json", "BENCH_r01.json", "BENCH_CPU.json",
                 "BENCH_r10.json"):
        (tmp_path / name).write_text("{}")
    monkeypatch.delenv("MXNET_TELEMETRY_EMIT_PATH", raising=False)
    got = [os.path.basename(p) for p in regress.default_paths(str(tmp_path))]
    assert got == ["BENCH_CPU.json", "BENCH_r01.json", "BENCH_r02.json",
                   "BENCH_r10.json"]


# ---------------------------------------------------------------------------
# the acceptance replay: seeded slowdown flagged, unchanged rerun clean
# ---------------------------------------------------------------------------

def _committed_history(tmp_path, values, seed_last=None):
    """A BENCH_r* sequence shaped like the repo's committed files."""
    paths = []
    vals = list(values) + ([seed_last] if seed_last is not None else [])
    for i, v in enumerate(vals, 1):
        p = tmp_path / ("BENCH_r%02d.json" % i)
        p.write_text(json.dumps(_line(v, extra={"batch": 8})))
        paths.append(str(p))
    return paths


def test_replay_flags_seeded_slowdown_only(tmp_path):
    clean = [5400.0, 5450.0, 5380.0, 5420.0]
    paths = _committed_history(tmp_path, clean, seed_last=4000.0)
    store = regress.TrajectoryStore()
    verdicts = []
    for p in paths:
        for line in regress.iter_bench_lines(p):
            verdicts.append(store.verdict(line))
            store.add(line, source=os.path.basename(p))
    # exactly ONE confirmed verdict: the seeded 26% slowdown at the end
    confirmed = [v for v in verdicts if v["confirmed"]]
    assert len(confirmed) == 1
    assert confirmed[0] is verdicts[-1]
    assert confirmed[0]["verdict"] == "regression"


def test_replay_unchanged_rerun_zero_false_positives(tmp_path):
    paths = _committed_history(tmp_path, [5400.0, 5450.0, 5380.0, 5420.0])
    store = regress.build_store(paths)
    # rerunning the same workload at the same speed: always ok
    for v in (5400.0, 5450.0, 5380.0, 5420.0):
        verdict = store.verdict(_line(v, extra={"batch": 8}))
        assert verdict["verdict"] == "ok", verdict
        assert not verdict["confirmed"]


def test_committed_repo_history_replays_with_zero_false_verdicts():
    # the real BENCH_r01..r05 trail: dead rounds are no_value (their
    # error is the signal), nothing is ever a confirmed regression
    store = regress.TrajectoryStore()
    for path in regress.default_paths(REPO):
        if os.path.basename(path) == "telemetry.jsonl":
            continue  # uncommitted local emitter tail, if any
        for line in regress.iter_bench_lines(path):
            v = store.verdict(line)
            assert not v["confirmed"], (path, v)
            assert v["verdict"] in ("no_history", "insufficient_history",
                                    "no_value", "ok", "improvement"), v
            store.add(line, source=os.path.basename(path))
    assert store.keys(), "committed history produced no trajectories"


def test_config_change_starts_new_trajectory_not_regression():
    store = regress.TrajectoryStore()
    _seed(store, [100.0, 101.0, 99.0], extra={"batch": 32})
    # same metric at batch 4 is 10x slower — a different config, not a
    # regression of the batch-32 trajectory
    v = store.verdict(_line(10.0, extra={"batch": 4}))
    assert v["verdict"] == "no_history" and not v["confirmed"]


# ---------------------------------------------------------------------------
# stamp_line / recent verdicts
# ---------------------------------------------------------------------------

def test_stamp_line_verdicts_then_absorbs():
    store = regress.TrajectoryStore()
    for v in (100.0, 101.0, 99.0):
        regress.stamp_line(_line(v), store=store)
    verdict = regress.stamp_line(_line(50.0), store=store)
    assert verdict["confirmed"] and verdict["verdict"] == "regression"
    recents = regress.recent_verdicts()
    assert len(recents) == 4
    assert recents[-1] is verdict
    # the regressed point is IN history now (next identical run is ok
    # against the median, not double-flagged forever)
    assert 50.0 in store.history(store.key(_line(50.0)))


# ---------------------------------------------------------------------------
# benchwatch CLI
# ---------------------------------------------------------------------------

def _benchwatch(*argv, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "benchwatch.py")]
        + list(argv), capture_output=True, text=True, cwd=cwd, timeout=120)


def test_benchwatch_committed_history_is_clean():
    res = _benchwatch()
    assert res.returncode == 0, res.stdout + res.stderr
    assert "no confirmed regressions at head" in res.stdout


def test_benchwatch_flags_seeded_slowdown(tmp_path):
    paths = _committed_history(
        tmp_path, [5400.0, 5450.0, 5380.0, 5420.0], seed_last=4000.0)
    res = _benchwatch(*paths)
    assert res.returncode == 9, res.stdout + res.stderr
    assert "CONFIRMED REGRESSION" in res.stdout
    res = _benchwatch("--json", *paths)
    assert res.returncode == 9
    doc = json.loads(res.stdout)
    assert doc["rc"] == 9 and len(doc["regressions_at_head"]) == 1


def test_benchwatch_recovered_head_is_clean(tmp_path):
    # a mid-history regression that later recovered: the rc gate judges
    # only the trajectory head, so the tree is clean today
    paths = _committed_history(
        tmp_path, [5400.0, 5450.0, 5380.0, 5420.0, 4000.0, 5410.0])
    res = _benchwatch(*paths)
    assert res.returncode == 0, res.stdout + res.stderr


def test_benchwatch_line_judged_against_history(tmp_path):
    hist = _committed_history(tmp_path, [5400.0, 5450.0, 5380.0, 5420.0])
    cand = tmp_path / "candidate.json"
    cand.write_text(json.dumps(_line(4000.0, extra={"batch": 8})))
    res = _benchwatch(*hist, "--line", str(cand), "--json")
    assert res.returncode == 9
    doc = json.loads(res.stdout)
    assert doc["verdicts"][-1]["source"] == "candidate.json"
    assert doc["verdicts"][-1]["confirmed"]


def test_benchwatch_usage_error_on_missing_file():
    res = _benchwatch("/nonexistent/history.json")
    assert res.returncode == 2
