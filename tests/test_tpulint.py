"""Tests for tools/tpulint — the AST-based TPU-correctness linter.

Pure AST analysis: no JAX import, no device work — tier-1 fast by
construction. Each pass gets positive + negative fixtures; suppression,
baseline, the repo-wide gate, and the CLI exit-code contract are covered.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.tpulint import core  # noqa: E402
from tools.tpulint.cli import filter_to_scope, lint_paths, main  # noqa: E402
from tools.tpulint.core import (DEFAULT_BASELINE, apply_baseline,  # noqa: E402
                                baseline_counts, collect_files, lint_files,
                                lint_source, load_baseline, write_baseline)


def lint(src, rule=None, relpath="mxnet_tpu/fake.py"):
    """Lint a snippet; returns findings (optionally for one rule)."""
    findings = lint_source(relpath, textwrap.dedent(src),
                           passes=[rule] if rule else None)
    return findings


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

def test_host_sync_asnumpy_in_loop():
    found = lint("""
        def f(batches):
            out = []
            for b in batches:
                out.append(b.asnumpy())
            return out
    """, "host-sync")
    assert len(found) == 1 and found[0].line == 5


def test_host_sync_float_of_call_in_loop():
    found = lint("""
        def f(xs):
            total = 0.0
            while xs:
                total += float(xs.pop().sum())
            return total
    """, "host-sync")
    assert len(found) == 1


def test_host_sync_in_jit_even_outside_loop():
    found = lint("""
        import jax

        @jax.jit
        def step(x):
            return x * x.item()
    """, "host-sync")
    assert len(found) == 1 and "trace time" in found[0].message


def test_host_sync_jit_reaches_helpers_transitively():
    found = lint("""
        import jax, numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(x):
            return helper(x) + 1
    """, "host-sync")
    assert len(found) == 1 and found[0].line == 5


def test_host_sync_negative():
    assert not lint("""
        def f(batches):
            x = batches[0].asnumpy()      # outside any loop: one sync, fine
            n = float(len(batches))       # len() never touches the device
            for b in batches:
                n += 1.0
            return x, n
    """, "host-sync")


def test_host_sync_comprehension_counts_as_loop():
    found = lint("""
        def f(batches):
            return [b.asnumpy() for b in batches]
    """, "host-sync")
    assert len(found) == 1


# ---------------------------------------------------------------------------
# tracer-leak
# ---------------------------------------------------------------------------

def test_tracer_leak_positive():
    found = lint("""
        import jax, os, time

        @jax.jit
        def step(x):
            print("step!")
            t = time.time()
            flag = os.environ.get("MXNET_FLAG")
            return x + t
    """, "tracer-leak")
    msgs = " ".join(f.message for f in found)
    assert len(found) == 3
    assert "print" in msgs and "time.time" in msgs and "os.environ" in msgs


def test_tracer_leak_global_and_wrapped_lambda():
    found = lint("""
        import jax

        _calls = 0

        def bump(x):
            global _calls
            _calls += 1
            return x

        f = jax.jit(lambda x: bump(x) + 1)
    """, "tracer-leak")
    assert len(found) == 1 and "global _calls" in found[0].message


def test_tracer_leak_curried_partial_wrap():
    found = lint("""
        import jax
        from functools import partial

        def step(x):
            print("traced")
            return x

        fast_step = partial(jax.jit, donate_argnums=0)(step)
    """, "tracer-leak")
    assert len(found) == 1 and "print" in found[0].message


def test_tracer_leak_partial_decorator_and_np_random():
    found = lint("""
        import jax, numpy as np
        from functools import partial

        @partial(jax.jit, static_argnums=0)
        def step(n, x):
            return x + np.random.rand(n)
    """, "tracer-leak")
    assert len(found) == 1 and "np.random.rand" in found[0].message


def test_tracer_leak_negative_outside_jit():
    assert not lint("""
        import os, time

        def host_loop(x):
            print("fine here")
            return x, time.time(), os.getenv("HOME")
    """, "tracer-leak")


# ---------------------------------------------------------------------------
# dtype-drift
# ---------------------------------------------------------------------------

def test_dtype_drift_positive():
    found = lint("""
        import numpy as np
        import jax.numpy as jnp

        def f(x):
            return np.zeros(3, dtype=np.float64) + x.astype(jnp.float64)
    """, "dtype-drift")
    assert len(found) == 2


def test_dtype_drift_registry_exempt():
    assert not lint("""
        import jax.numpy as jnp

        DTYPE_NP = {
            "float64": jnp.float64,
            "float32": jnp.float32,
        }
    """, "dtype-drift")


def test_dtype_drift_negative():
    assert not lint("""
        import numpy as np

        def f(x):
            return x.astype(np.float32)
    """, "dtype-drift")


# ---------------------------------------------------------------------------
# native-guard
# ---------------------------------------------------------------------------

def test_native_guard_unguarded_assign():
    found = lint("""
        from mxnet_tpu import _native

        def stats():
            lib = _native.get_lib()
            return lib.MXTPUStorageStats()
    """, "native-guard")
    assert len(found) == 1 and "never checked" in found[0].message


def test_native_guard_guarded_variants():
    assert not lint("""
        from mxnet_tpu import _native

        def a():
            lib = _native.get_lib()
            if lib is None:
                return 0
            return lib.f()

        def b():
            lib = _native.get_lib()
            return lib.f() if lib is not None else 0

        def c():
            lib = _native.get_lib()
            if not lib:
                return 0
            return lib.f()

        def d():
            lib = _native.get_lib()
            return getattr(lib, "_name", None) or "unavailable"

        def e():
            return _native.get_lib() is not None
    """, "native-guard")


def test_native_guard_return_forward_and_direct_use():
    found = lint("""
        from mxnet_tpu import _native

        def forward():
            return _native.get_lib()

        def direct():
            return _native.get_lib().f()
    """, "native-guard")
    assert len(found) == 2
    assert any("forwards an unguarded Optional" in f.message for f in found)
    assert any("used directly" in f.message for f in found)


# ---------------------------------------------------------------------------
# env-knob
# ---------------------------------------------------------------------------

def test_env_knob_positive_reads():
    found = lint("""
        import os

        A = os.environ.get("MXNET_A", "1")
        B = os.getenv("MXNET_B")
        C = os.environ["MXNET_C"]
        D = os.environ.setdefault("MXNET_D", "x")
    """, "env-knob")
    assert len(found) == 4


def test_env_knob_mutations_not_flagged():
    assert not lint("""
        import os

        os.environ["MXNET_A"] = "1"
        os.environ.pop("MXNET_B", None)
        del os.environ["MXNET_C"]
    """, "env-knob")


def test_env_knob_scoped_to_mxnet_tpu():
    src = """
        import os
        A = os.environ.get("MXNET_A")
    """
    assert lint(src, "env-knob", relpath="mxnet_tpu/x.py")
    assert not lint(src, "env-knob", relpath="tools/x.py")
    assert not lint(src, "env-knob", relpath="mxnet_tpu/base.py")


# ---------------------------------------------------------------------------
# swallowed-error
# ---------------------------------------------------------------------------

def test_swallowed_error_positive_variants():
    found = lint("""
        def f(q):
            try:
                q.get()
            except Exception:
                pass
            while True:
                try:
                    q.get()
                except:
                    continue
            try:
                q.get()
            except (ValueError, BaseException):
                ...
    """, "swallowed-error")
    assert len(found) == 3


def test_swallowed_error_negative_handled_or_narrow():
    assert not lint("""
        import queue

        def f(q, log):
            try:
                q.get()
            except queue.Empty:
                pass
            try:
                q.get()
            except Exception as exc:
                log.warning("boom: %s", exc)
            try:
                q.get()
            except Exception:
                return None
            try:
                q.get()
            except Exception:
                raise
    """, "swallowed-error")


def test_swallowed_error_scoped_to_runtime_package():
    src = """
        def f(q):
            try:
                q.get()
            except Exception:
                pass
    """
    assert lint(src, "swallowed-error", relpath="mxnet_tpu/x.py")
    assert not lint(src, "swallowed-error", relpath="tools/x.py")


def test_swallowed_error_suppressible():
    found = lint("""
        def __del__(self):
            try:
                self.close()
            except Exception:  # tpulint: disable=swallowed-error
                pass
    """, "swallowed-error")
    assert not found


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------

def test_inline_suppression():
    src = """
        import os
        A = os.environ.get("MXNET_A")  # tpulint: disable=env-knob -- justified
        B = os.environ.get("MXNET_B")  # tpulint: disable=all
        C = os.environ.get("MXNET_C")  # tpulint: disable=host-sync (wrong rule)
    """
    found = lint(src, "env-knob")
    assert len(found) == 1 and found[0].line == 5


def test_baseline_roundtrip(tmp_path):
    src_v1 = "import os\nA = os.environ.get('MXNET_A')\n"
    f1 = lint_source("mxnet_tpu/x.py", src_v1, passes=["env-knob"])
    assert len(f1) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(f1, bl)
    baseline = load_baseline(bl)
    # same findings -> nothing new, even when lines shift
    shifted = lint_source("mxnet_tpu/x.py", "import os\n\n\nA = os.environ.get('MXNET_A')\n",
                          passes=["env-knob"])
    assert apply_baseline(shifted, baseline) == []
    # a second occurrence of the same key -> exactly the surplus is new
    src_v2 = src_v1 + "B = os.environ.get('MXNET_A')\n"
    f2 = lint_source("mxnet_tpu/x.py", src_v2, passes=["env-knob"])
    new = apply_baseline(f2, baseline)
    assert len(new) == 1 and new[0].line == 3


def test_baseline_counts_keys_have_no_line_numbers():
    f = lint_source("mxnet_tpu/x.py", "import os\nA = os.environ.get('X')\n",
                    passes=["env-knob"])
    (key,) = baseline_counts(f)
    assert key.startswith("mxnet_tpu/x.py::env-knob::")
    assert "\n" not in key and ":2:" not in key


# ---------------------------------------------------------------------------
# repo gate + CLI contract
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# perparam-jit
# ---------------------------------------------------------------------------

def test_perparam_jit_immediate_and_cached_dispatch():
    f = lint("""
        import jax
        def apply(params, fns, cache):
            for p in params:
                jax.jit(lambda x: x + 1)(p)
            for k, p in params.items():
                cache._step_cache[k](p)
        """, rule="perparam-jit")
    assert len(f) == 2
    assert all(x.rule == "perparam-jit" for x in f)


def test_perparam_jit_fused_invocation_and_bound_name():
    f = lint("""
        import jax
        def update_all(self, params, g, lr, wd):
            step = jax.jit(lambda w: w - lr * w)
            for w in params:
                self._fused("sgd", None)(w, g, lr, wd)
            for w in params:
                step(w)
        """, rule="perparam-jit")
    assert len(f) == 2


def test_perparam_jit_optimizer_and_kvstore_dispatch():
    f = lint("""
        def update(self, params, grads):
            for i, (w, g) in enumerate(zip(params, grads)):
                self._updater(i, g, w)
            for i, g in enumerate(grads):
                self._kvstore.push(i, g)
                self._kvstore.pull(i, g)
            for i, (w, g) in enumerate(zip(params, grads)):
                self.optimizer.update(i, w, g, None)
        """, rule="perparam-jit")
    assert len(f) == 4


def test_perparam_jit_negative_outside_loop_and_scope():
    # one-shot dispatches and non-loop calls are fine
    f = lint("""
        import jax
        def apply(self, tree, g):
            fn = jax.jit(lambda x: x)
            fn(tree)
            self._updater(0, g, tree)
            self._kvstore.push(0, g)
        """, rule="perparam-jit")
    assert f == []
    # dict/set merges named `opt`/`cfg` are NOT optimizer dispatch
    f = lint("""
        def merge(configs):
            opt = {}
            for cfg in configs:
                opt.update(cfg)
            return opt
        """, rule="perparam-jit")
    assert f == []
    # the pass polices mxnet_tpu/ only (user tools keep their loops)
    f = lint("""
        import jax
        def bench(params):
            for p in params:
                jax.jit(lambda x: x)(p)
        """, rule="perparam-jit", relpath="tools/bench_thing.py")
    assert f == []


def test_gate_repo_is_clean_against_committed_baseline():
    """The acceptance gate: zero non-baselined findings across mxnet_tpu/
    and tools/. A new hazard in a PR lands here as a failure."""
    new, all_findings = lint_paths(["mxnet_tpu", "tools"])
    assert new == [], "new tpulint findings (fix, suppress with justification," \
                      " or --write-baseline):\n" + "\n".join(map(str, new))
    # the baseline itself must stay honest: every entry still matches code
    counts = baseline_counts(all_findings)
    baseline = load_baseline(DEFAULT_BASELINE)
    stale = [k for k in baseline if counts.get(k, 0) < baseline[k]]
    assert stale == [], "stale baseline entries (regenerate with " \
                        "--write-baseline):\n" + "\n".join(stale)


def test_cli_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu", "tools"],
        cwd=str(REPO), capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = tmp_path / "viol.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", str(bad)],
        cwd=str(REPO), capture_output=True, text=True)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    assert "host-sync" in dirty.stdout


def test_cli_json_format_and_select(tmp_path, capsys):
    bad = tmp_path / "viol.py"
    bad.write_text("import os\ndef f(xs):\n    return [x.asnumpy() for x in xs]\n")
    rc = main([str(bad), "--format", "json", "--select", "host-sync"])
    out = capsys.readouterr().out
    payload = json.loads(out)
    assert rc == 1
    assert payload["total"] == 1 and payload["new"][0]["rule"] == "host-sync"
    # unknown rule -> usage error
    assert main([str(bad), "--select", "no-such-rule"]) == 2


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "viol.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl)]) == 0
    # an additional violation beyond the baselined one -> fails again
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n"
                   "def g(xs):\n    return [x.item() for x in xs]\n")
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl)]) == 1


def test_collect_files_survives_hidden_ancestor(tmp_path):
    # a dotted ancestor of the scanned dir must not empty the lint scope
    pkg = tmp_path / ".work" / "repo" / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    (pkg / ".hidden" ).mkdir()
    (pkg / ".hidden" / "skip.py").write_text("x = 1\n")
    files = collect_files([str(pkg)])
    assert [f.name for f in files] == ["mod.py"]


def test_write_baseline_scoped_run_keeps_other_entries(tmp_path, capsys):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    b.write_text("def g(xs):\n    return [x.item() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(a), str(b), "--baseline", str(bl), "--write-baseline"]) == 0
    # re-baselining only a.py must not drop b.py's entry
    assert main([str(a), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert main([str(a), str(b), "--baseline", str(bl)]) == 0
    # and a scoped *check* of a.py alone must not report b.py's entry stale
    assert main([str(a), "--baseline", str(bl)]) == 0
    assert "stale" not in capsys.readouterr().out


def test_nonexistent_path_is_usage_error(tmp_path, capsys):
    # a typo'd path must not produce a green "0 findings" run
    assert main([str(tmp_path / "does_not_exist.py")]) == 2
    assert main(["mxnet_tpu/no_such_file.py"]) == 2


def test_changed_only_git_failure_is_loud(monkeypatch):
    from tools.tpulint import cli as cli_mod

    monkeypatch.setattr(cli_mod, "changed_files", lambda: None)
    assert cli_mod.main(["--changed-only"]) == 2


def test_changed_only_filter():
    scope = collect_files(["mxnet_tpu"])
    changed = ["mxnet_tpu/base.py", "mxnet_tpu/does_not_exist.py", "README.md"]
    picked = filter_to_scope(changed, scope)
    assert [p.name for p in picked] == ["base.py"]


def test_list_rules_names_all_five(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("host-sync", "tracer-leak", "dtype-drift", "native-guard",
                 "env-knob"):
        assert rule in out


def test_parse_error_is_a_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    found = lint_files([bad], root=tmp_path)
    assert len(found) == 1 and found[0].rule == "parse-error"


def test_undecodable_and_null_byte_files_are_findings_not_crashes(tmp_path):
    latin = tmp_path / "latin.py"
    latin.write_bytes(b"# caf\xe9\nx = 1\n")
    nul = tmp_path / "nul.py"
    nul.write_bytes(b"x = 1\x00\n")
    found = lint_files([latin, nul], root=tmp_path)
    assert sorted(f.rule for f in found) == ["parse-error", "parse-error"]


# ---------------------------------------------------------------------------
# eager-step
# ---------------------------------------------------------------------------

def test_eager_step_gluon_idiom_flagged():
    f = lint("""
        def train(net, loss_fn, trainer, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(x.shape[0])
        """, rule="eager-step")
    assert len(f) == 1 and f[0].rule == "eager-step"


def test_eager_step_module_idiom_flagged():
    f = lint("""
        def fit(self, train_data):
            for epoch in range(3):
                for batch in train_data:
                    self.forward_backward(batch)
                    self.update()
        """, rule="eager-step")
    # both the epoch loop and the batch loop contain the full step
    assert len(f) == 2


def test_eager_step_negative_cases():
    # a step outside any loop is a single step, not a loop regime
    f = lint("""
        def one(net, loss_fn, trainer, x, y):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
        """, rule="eager-step")
    assert f == []
    # forward-only loops (eval/predict) are fine
    f = lint("""
        def score(net, batches, metric):
            for x, y in batches:
                metric.update(y, net(x))
        """, rule="eager-step")
    assert f == []
    # backward without an update is grad accumulation, not a train step
    f = lint("""
        def grads(net, loss_fn, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
        """, rule="eager-step")
    assert f == []
    # ...and metric bookkeeping next to it is still not an optimizer step
    f = lint("""
        def grads(net, loss_fn, batches, eval_metric):
            for x, y in batches:
                with autograd.record():
                    out = net(x)
                    loss = loss_fn(out, y)
                loss.backward()
                eval_metric.update(y, out)
        """, rule="eager-step")
    assert f == []


def test_eager_step_nested_function_not_attributed_to_loop():
    # a step packaged in a closure defined inside a loop body runs when
    # called, not per definition — the loop itself is not flagged
    f = lint("""
        def build(net, loss_fn, trainer, batches):
            fns = []
            for x, y in batches:
                def one_step(x=x, y=y):
                    with autograd.record():
                        loss = loss_fn(net(x), y)
                    loss.backward()
                    trainer.step(1)
                fns.append(one_step)
            return fns
        """, rule="eager-step")
    assert f == []


def test_eager_step_scoped_to_mxnet_tpu():
    src = """
        def train(net, loss_fn, trainer, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(1)
    """
    assert lint(src, rule="eager-step",
                relpath="tools/somewhere.py") == []
    assert len(lint(src, rule="eager-step")) == 1


# ---------------------------------------------------------------------------
# decode-host-sync
# ---------------------------------------------------------------------------

def test_decode_host_sync_flags_syncs_in_decode_scope():
    # straight-line code, no loop: the generic host-sync pass is blind
    # here, the cadence comes from the scope name
    f = lint("""
        def decode_step(engine, step):
            sampled = step()
            return fetch_host([sampled])[0]
        """, rule="decode-host-sync")
    assert len(f) == 1 and "fetch_host" in f[0].message

    f = lint("""
        def generate(model, prompt):
            logits = model(prompt)
            return logits.asnumpy()
        """, rule="decode-host-sync")
    assert len(f) == 1 and ".asnumpy" in f[0].message


def test_decode_host_sync_class_scope_and_item():
    # any method of a Decode* class is per-token cadence, whatever its
    # name; .item() and .tolist() are sync calls too
    f = lint("""
        class DecodeEngine:
            def _tick(self):
                tok = self._step()
                return tok.item()
        """, rule="decode-host-sync")
    assert len(f) == 1 and ".item" in f[0].message


def test_decode_host_sync_negative_cases():
    # imdecode (host-side image decoding) must not match the word scope;
    # sync calls outside any decode scope belong to the generic pass
    assert lint("""
        def imdecode(buf):
            return fetch_host([buf])[0]
        """, rule="decode-host-sync") == []
    assert lint("""
        def forward(engine, batch):
            out = engine(batch)
            return fetch_host([out])[0]
        """, rule="decode-host-sync") == []
    # non-sync calls inside decode scope stay clean
    assert lint("""
        def decode_step(engine, toks):
            return engine.step(toks)
        """, rule="decode-host-sync") == []


def test_decode_host_sync_scoped_to_mxnet_tpu():
    src = """
        def decode_loop(step):
            return fetch_host([step()])[0]
    """
    assert lint(src, rule="decode-host-sync",
                relpath="tools/elsewhere.py") == []
    assert len(lint(src, rule="decode-host-sync")) == 1


def test_decode_host_sync_repo_sites_are_baselined():
    # the decode plane keeps exactly its two justified syncs (the tick's
    # sampled-token fetch + the prefill first-token fetch) — baselined,
    # so the repo gate stays clean and any NEW sync is a finding
    counts = load_baseline(DEFAULT_BASELINE)
    key = ("mxnet_tpu/serving/decode.py::decode-host-sync::"
           "`fetch_host()` in decode-plane code runs per token — "
           "a device->host stall every tick")
    assert counts.get(key) == 2


# ---------------------------------------------------------------------------
# replicated-state
# ---------------------------------------------------------------------------

def test_replicated_state_flags_eager_copy_and_device_put():
    f = lint("""
        def restore(updater):
            for i in updater.states:
                updater.states[i] = jnp.copy(updater.states[i])
        """, rule="replicated-state")
    assert len(f) == 1 and "jnp.copy" in f[0].message

    f = lint("""
        def spread(opt_states, repl):
            return [jax.device_put(s, repl) for s in opt_states]
        """, rule="replicated-state")
    assert len(f) == 1 and "device_put" in f[0].message


def test_replicated_state_flags_tree_map_full_tree_copy():
    f = lint("""
        def gather(states, repl):
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, repl), states)
        """, rule="replicated-state")
    assert len(f) == 1 and "tree_map" in f[0].message


def test_replicated_state_negative_cases():
    # non-state arrays stay out of scope
    assert lint("""
        def copy_params(pvals):
            return {n: jnp.copy(v) for n, v in pvals.items()}
        """, rule="replicated-state") == []
    # the blessed layout-aware helpers are the FIX, not a finding
    assert lint("""
        def gather(states, mesh):
            return [parallel.fresh_replicate(s, mesh) for s in states]
        """, rule="replicated-state") == []
    # states_synced is bool bookkeeping, not device state
    assert lint("""
        def mark(updater):
            updater.states_synced = jnp.copy(updater.states_synced)
        """, rule="replicated-state") == []
    # tree_map without a copy/device_put inside is fine
    assert lint("""
        def cast(states):
            return jax.tree_util.tree_map(lambda x: x.astype("f4"), states)
        """, rule="replicated-state") == []


def test_replicated_state_blessed_homes_exempt():
    src = """
        def fresh_replicate(states, repl):
            return jax.device_put(states, repl)
    """
    assert lint(src, rule="replicated-state",
                relpath="mxnet_tpu/parallel.py") == []
    assert lint(src, rule="replicated-state",
                relpath="mxnet_tpu/fastpath/zero.py") == []
    assert lint(src, rule="replicated-state",
                relpath="tools/whatever.py") == []
    assert len(lint(src, rule="replicated-state")) == 1


def test_replicated_state_repo_gate_clean():
    # the repo itself carries ZERO eager state placements — nothing to
    # baseline, and the first regression is a finding
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["replicated-state"])
                if f.rule == "replicated-state"]
    assert findings == []


# ---------------------------------------------------------------------------
# non-atomic-write
# ---------------------------------------------------------------------------

def test_non_atomic_write_flags_bare_open_on_ckpt_path():
    f = lint("""
        def store(ckpt_path, blob):
            with open(ckpt_path, "wb") as fh:
                fh.write(blob)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "open" in f[0].message
    # checkpoint-ish by FUNCTION even when the path arg is opaque
    f = lint("""
        def save_states(fname, blob):
            open(fname, "wb").write(blob)
        """, rule="non-atomic-write")
    assert len(f) == 1


def test_non_atomic_write_flags_np_save_and_pickle_dump():
    f = lint("""
        def snapshot(path, arr):
            np.save(path, arr)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "np.save" in f[0].message
    f = lint("""
        def write(obj, manifest_file):
            pickle.dump(obj, manifest_file)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "pickle.dump" in f[0].message


def test_non_atomic_write_negative_cases():
    # reads are fine, and writes to non-checkpoint paths are out of scope
    assert lint("""
        def load(ckpt_path):
            with open(ckpt_path, "rb") as fh:
                return fh.read()
        """, rule="non-atomic-write") == []
    assert lint("""
        def emit(log_path, line):
            open(log_path, "a").write(line)
        """, rule="non-atomic-write") == []
    # tools/tests are out of scope — only mxnet_tpu/ carries the contract
    assert lint("""
        def save(ckpt_path, blob):
            open(ckpt_path, "wb").write(blob)
        """, rule="non-atomic-write", relpath="tools/whatever.py") == []


def test_non_atomic_write_commit_helpers_exempt():
    # the atomic helpers themselves, and writer lambdas routed through
    # them, ARE the sanctioned implementation
    assert lint("""
        def _atomic_write(path, writer):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(b"checkpoint")
            os.replace(tmp, path)
        """, rule="non-atomic-write") == []
    assert lint("""
        def save(self, epoch, blob):
            self._commit(self._params_path(epoch),
                         lambda p: open(p, "wb").write(blob))
        """, rule="non-atomic-write") == []
    assert lint("""
        def save(self, epoch, blob):
            self._commit_bytes(self._shard_path(epoch), blob, "shard")
        """, rule="non-atomic-write") == []


def test_non_atomic_write_repo_gate_clean():
    # every pre-existing bare write rides the committed baseline; the
    # elastic checkpoint plane itself must be finding-free
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["non-atomic-write"])]
    baseline = load_baseline(DEFAULT_BASELINE)
    assert apply_baseline(findings, baseline) == []
    assert [f for f in findings if "elastic" in f.path] == []


# ---------------------------------------------------------------------------
# whole-program graph engine (symbol table / call graph / lattices)
# ---------------------------------------------------------------------------

from tools.tpulint import graph as graph_mod  # noqa: E402
from tools.tpulint.core import FileContext, lint_sources  # noqa: E402


def make_graph(files, depth=graph_mod.DEFAULT_DEPTH):
    """Build a ProjectGraph over {relpath: source} fixtures."""
    ctxs = [FileContext(rp, textwrap.dedent(src), filename=rp)
            for rp, src in sorted(files.items())]
    return graph_mod.build_graph([(c.relpath, c.tree) for c in ctxs],
                                 depth=depth)


def fn_of(gph, qname):
    for info in gph.funcs.values():
        if info.qname == qname:
            return info
    raise AssertionError("no function %r in graph (have: %s)"
                         % (qname, sorted(i.qname for i in gph.funcs.values())))


def test_graph_aliased_import_call_edges():
    gph = make_graph({
        "mxnet_tpu/a.py": """
            def helper(x):
                return x + 1
        """,
        "mxnet_tpu/b.py": """
            from mxnet_tpu.a import helper as h2
            import mxnet_tpu.a as amod
            from .a import helper as h3

            def via_from_alias(x):
                return h2(x)

            def via_module_alias(x):
                return amod.helper(x)

            def via_relative(x):
                return h3(x)
        """})
    helper = fn_of(gph, "mxnet_tpu/a.py::helper")
    for caller in ("via_from_alias", "via_module_alias", "via_relative"):
        info = fn_of(gph, "mxnet_tpu/b.py::%s" % caller)
        assert helper in info.callees, caller


def test_graph_package_init_reexport_resolves():
    # `from .mod import helper` inside pkg/__init__.py resolves against
    # pkg itself (not one level up), so re-export chains through package
    # __init__ files keep their call edges — the mxnet_tpu subpackages
    # (fastpath, serving, telemetry) all re-export this way
    gph = make_graph({
        "pkg/__init__.py": """
            from .mod import helper
        """,
        "pkg/mod.py": """
            def helper(x):
                return x.asnumpy()
        """,
        "pkg/use.py": """
            import jax
            from pkg import helper

            @jax.jit
            def step(x):
                return helper(x)
        """})
    helper = fn_of(gph, "pkg/mod.py::helper")
    step = fn_of(gph, "pkg/use.py::step")
    assert helper in step.callees
    assert gph.is_traced(helper.node)


def test_graph_method_binding_self_and_base_class():
    gph = make_graph({
        "mxnet_tpu/base_mod.py": """
            class Base:
                def shared(self):
                    return 1
        """,
        "mxnet_tpu/impl.py": """
            from mxnet_tpu.base_mod import Base

            class Impl(Base):
                def own(self):
                    return 2

                def caller(self):
                    return self.own() + self.shared() + Impl.own(self)
        """})
    caller = fn_of(gph, "mxnet_tpu/impl.py::Impl.caller")
    own = fn_of(gph, "mxnet_tpu/impl.py::Impl.own")
    shared = fn_of(gph, "mxnet_tpu/base_mod.py::Base.shared")
    assert own in caller.callees          # self-binding (and Class.method)
    assert shared in caller.callees       # base-class binding by name


def test_graph_decorated_functions_still_resolve():
    gph = make_graph({
        "mxnet_tpu/d.py": """
            import functools

            def deco(fn):
                return fn

            @deco
            def decorated(x):
                return x

            def caller(x):
                return decorated(x)
        """})
    assert fn_of(gph, "mxnet_tpu/d.py::decorated") in \
        fn_of(gph, "mxnet_tpu/d.py::caller").callees


def test_graph_recursion_terminates_and_depth_cutoff():
    # direct + mutual recursion must terminate; a chain longer than the
    # propagation bound is cut off at DEFAULT_DEPTH frames from the seed.
    # (Seeded via the graph-only `_leaf_step` name seed: the same-file
    # jit closure in `core.jit_functions` is deliberately unbounded.)
    depth = graph_mod.DEFAULT_DEPTH
    n = depth + 2
    chain = "\n".join(
        "def f%d(x):\n    return f%d(x)" % (i, i + 1) for i in range(n))
    src = """
        import jax

        def rec(x):
            return rec(x)

        def _leaf_step(x):
            return f0(x)

        %s

        def f%d(x):
            return x

        jax.jit(rec)
    """ % (chain.replace("\n", "\n        "), n)
    gph = make_graph({"mxnet_tpu/r.py": src})
    assert gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::rec").node)
    # fk sits at distance k+1 from the seed: within the bound traced,
    # beyond it cut off
    assert gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % (depth - 1)).node)
    assert not gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % depth).node)
    assert not gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % n).node)


def test_graph_traced_lattice_seeds_and_chain():
    gph = make_graph({
        "mxnet_tpu/opt.py": """
            class SGD:
                def _leaf_step(self, w, g):
                    return self._clip(w - g)

                def _clip(self, x):
                    return x
        """,
        "mxnet_tpu/plane.py": """
            import jax

            class Plane:
                def _build_step(self):
                    def step(x):
                        return helper(x)
                    return step

                def activate(self):
                    self._fn = jax.jit(self._build_step())

            def helper(x):
                return x
        """})
    clip = fn_of(gph, "mxnet_tpu/opt.py::SGD._clip")
    assert gph.is_traced(clip.node)                 # seeded at _leaf_step
    assert gph.traced_chain(clip.node) == ["SGD._leaf_step", "SGD._clip"]
    # factory-returned nested function + its callees are traced
    step = fn_of(gph, "mxnet_tpu/plane.py::Plane._build_step.step")
    helper = fn_of(gph, "mxnet_tpu/plane.py::helper")
    assert gph.is_traced(step.node) and gph.is_traced(helper.node)


def test_graph_thread_lattice_seeds():
    gph = make_graph({
        "mxnet_tpu/w.py": """
            import threading

            class Emitter(threading.Thread):
                def run(self):
                    self.emit()

                def emit(self):
                    pass

            class Server:
                def start(self):
                    self._t = threading.Thread(target=self._worker)

                def _worker(self):
                    helper()

            class Saver:
                def save(self):
                    def commit():
                        finish()
                    self._engine.push(commit)

            def helper():
                pass

            def finish():
                pass

            def main_only():
                helper()
        """})
    for q in ("Emitter.run", "Emitter.emit", "Server._worker", "helper",
              "Saver.save.commit", "finish"):
        assert gph.is_threaded(fn_of(gph, "mxnet_tpu/w.py::%s" % q).node), q
    assert not gph.is_threaded(fn_of(gph, "mxnet_tpu/w.py::main_only").node)
    assert gph.thread_entry(
        fn_of(gph, "mxnet_tpu/w.py::Server._worker").node) == "Server._worker"


# ---------------------------------------------------------------------------
# traced-host-sync
# ---------------------------------------------------------------------------

def test_traced_host_sync_two_calls_below_leaf_step():
    f = lint("""
        def _leaf_step(w, g):
            return _apply(w, g)

        def _apply(w, g):
            return _norm(w - g)

        def _norm(x):
            return x / float(x.sum())
    """, "traced-host-sync")
    assert len(f) == 1
    assert "float()" in f[0].message and "_leaf_step" in f[0].message
    assert "_norm" in f[0].message


def test_traced_host_sync_cross_file_jit_reachability():
    found = lint_sources([
        ("mxnet_tpu/helpers.py", textwrap.dedent("""
            def helper(x):
                return x.asnumpy()
        """)),
        ("mxnet_tpu/steps.py", textwrap.dedent("""
            import jax
            from mxnet_tpu.helpers import helper

            @jax.jit
            def step(x):
                return helper(x)
        """)),
    ], passes=["traced-host-sync"])
    assert len(found) == 1 and found[0].path == "mxnet_tpu/helpers.py"
    assert ".asnumpy()" in found[0].message


def test_traced_host_sync_flags_get_env_and_locks():
    f = lint("""
        def _leaf_step(w):
            knob = get_env("MXNET_X", 0, int, cache=False)
            with self._lock:
                w = w + knob
            self._mu.acquire()
            return w
    """, "traced-host-sync")
    msgs = " ".join(x.message for x in f)
    assert len(f) == 3
    assert "get_env(cache=False)" in msgs and "lock" in msgs


def test_traced_host_sync_negative_and_no_double_report():
    # not reachable from any traced seed -> clean
    assert lint("""
        def host_loop(xs):
            return xs[0].asnumpy()
    """, "traced-host-sync") == []
    # lexically inside a same-file jit closure: host-sync owns the report
    src = """
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """
    assert lint(src, "traced-host-sync") == []
    assert len(lint(src, "host-sync")) == 1


def test_traced_host_sync_scoped_to_mxnet_tpu():
    src = """
        def _leaf_step(w):
            return float(w.sum())
    """
    assert lint(src, "traced-host-sync", relpath="tools/x.py") == []
    assert len(lint(src, "traced-host-sync")) == 1


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------

def test_use_after_donate_read_after_fused_apply():
    f = lint("""
        def apply(opt, idx, grads, weights, states):
            new_w, new_s = fused_apply(opt, idx, grads, weights, states)
            return weights[0], new_w
    """, "use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message


def test_use_after_donate_rebind_and_invalidate_clear():
    assert lint("""
        def rebound(opt, idx, g, weights, states):
            weights = fused_apply(opt, idx, g, weights, states)
            return weights
    """, "use-after-donate") == []
    assert lint("""
        def disciplined(opt, idx, g, weights, states):
            new_w = fused_apply(opt, idx, g, weights, states)
            invalidate_consumed(consumed, (new_w,))
            return weights
    """, "use-after-donate") == []


def test_use_after_donate_donation_prep_window_opens_at_consumer():
    # reads between prep and the consuming jit are the sanctioned pattern
    assert lint("""
        def ok(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            new_ws, new_buckets = fn(flat_ws, buckets)
            buckets = new_buckets
            return new_ws
    """, "use-after-donate") == []
    # ...but a read AFTER the consumer is stale
    f = lint("""
        def stale(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            new_ws = fn(flat_ws, buckets)
            return flat_ws[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`flat_ws`" in f[0].message


def test_use_after_donate_local_donating_jit_and_self_attr():
    f = lint("""
        import jax

        def local_jit(pools, x):
            step = jax.jit(kernel, donate_argnums=(0,))
            out = step(pools, x)
            return pools[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`pools`" in f[0].message
    # the decode pattern: a donating jit installed in __init__, the pool
    # donated in another method, rebound from the jit's outputs -> clean
    assert lint("""
        import jax

        class Engine:
            def __init__(self):
                self._step = jax.jit(kernel, donate_argnums=(0,))

            def tick(self, x):
                out, pools = self._step(self._pools, x)
                self._pools = pools
                return out
    """, "use-after-donate") == []
    # ...without the rebind, the next read is stale
    f = lint("""
        import jax

        class Engine:
            def __init__(self):
                self._step = jax.jit(kernel, donate_argnums=(0,))

            def tick(self, x):
                out = self._step(self._pools, x)
                return self._pools
    """, "use-after-donate")
    assert len(f) == 1 and "self._pools" in f[0].message


def test_use_after_donate_fused_py_is_exempt():
    src = """
        def probe(weights):
            new = fused_apply(None, None, None, weights, None)
            return weights
    """
    assert lint(src, "use-after-donate",
                relpath="mxnet_tpu/fastpath/fused.py") == []
    assert len(lint(src, "use-after-donate")) == 1


# ---------------------------------------------------------------------------
# shared-state-race
# ---------------------------------------------------------------------------

def test_shared_state_race_unlocked_cross_thread_write():
    f = lint("""
        import threading

        class W:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._n += 1

            def snapshot(self):
                return self._n
    """, "shared-state-race")
    assert len(f) == 1
    assert "`self._n`" in f[0].message and "W.snapshot" in f[0].message


def test_shared_state_race_common_lock_is_clean():
    assert lint("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self._n += 1

            def snapshot(self):
                with self._lock:
                    return self._n
    """, "shared-state-race") == []


def test_shared_state_race_one_sided_lock_still_flagged():
    f = lint("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self._n += 1

            def snapshot(self):
                return self._n
    """, "shared-state-race")
    assert len(f) == 1


def test_shared_state_race_init_exemptions():
    # writes in __init__ are pre-start() on either side — including an
    # object CONSTRUCTED on the worker thread (publication via queue)
    assert lint("""
        import threading

        class Batch:
            def __init__(self, data):
                self.data = data

            def __str__(self):
                return str(self.data)

        class W:
            def __init__(self):
                self._t = threading.Thread(target=self._run)

            def _run(self):
                b = Batch([1])
                self._q.put(b)
    """, "shared-state-race") == []


def test_shared_state_race_worker_closure_in_init_is_thread_context():
    # a closure defined in __init__ but handed to Thread(target=...) runs
    # on the worker — its writes do NOT get the construction exemption
    f = lint("""
        import threading

        class W:
            def __init__(self):
                def worker():
                    self._state = 1
                self._t = threading.Thread(target=worker)

            def peek(self):
                return self._state
    """, "shared-state-race")
    assert len(f) == 1 and "`self._state`" in f[0].message


def test_shared_state_race_scoped_to_mxnet_tpu():
    src = """
        import threading

        class W:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._n += 1

            def peek(self):
                return self._n
    """
    assert lint(src, "shared-state-race", relpath="tools/x.py") == []
    assert len(lint(src, "shared-state-race")) == 1


def test_shared_state_race_repo_findings_are_baselined_with_justifications():
    # every baselined interprocedural finding must carry a one-line
    # justification (the acceptance contract for the whole-program gate)
    counts = load_baseline(DEFAULT_BASELINE)
    justs = core.load_justifications(DEFAULT_BASELINE)
    race_keys = [k for k in counts if "::shared-state-race::" in k]
    assert race_keys, "expected the known worker-counter findings baselined"
    for k in race_keys:
        assert justs.get(k), "baselined finding lacks a justification: %s" % k


# ---------------------------------------------------------------------------
# seeded synthetic bugs (fixture module): each pass catches exactly its bug
# ---------------------------------------------------------------------------

SEEDED = (REPO / "tests" / "fixtures" / "tpulint_seeded_bugs.py").read_text()


def _lint_seeded(rule):
    # linted under a mxnet_tpu/ pseudo-path: the passes police the
    # framework package only
    return lint_source("mxnet_tpu/_seeded_bugs.py", SEEDED, passes=[rule])


def test_seeded_bug_traced_host_sync():
    f = _lint_seeded("traced-host-sync")
    assert len(f) == 1
    assert "float()" in f[0].message and "_leaf_step" in f[0].message


def test_seeded_bug_use_after_donate():
    f = _lint_seeded("use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message


def test_seeded_bug_shared_state_race():
    f = _lint_seeded("shared-state-race")
    assert len(f) == 1 and "`self._count`" in f[0].message


def test_seeded_bugs_exactly_three_across_all_passes():
    f = lint_source("mxnet_tpu/_seeded_bugs.py", SEEDED)
    assert sorted(x.rule for x in f) == \
        ["shared-state-race", "traced-host-sync", "use-after-donate"]


# ---------------------------------------------------------------------------
# incremental cache + --stats + runtime gates
# ---------------------------------------------------------------------------

from tools.tpulint.cache import LintCache  # noqa: E402


def test_cache_warm_hits_and_identical_findings(tmp_path):
    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    cache1 = LintCache(tmp_path / "c.json")
    cold = lint_files([a], root=tmp_path, cache=cache1)
    assert cache1.hits == 0 and cache1.misses > 0
    cache2 = LintCache(tmp_path / "c.json")
    warm = lint_files([a], root=tmp_path, cache=cache2)
    assert cache2.misses == 0 and cache2.hits > 0
    assert [str(f) for f in warm] == [str(f) for f in cold]


def test_cache_invalidated_by_edit_and_scope_change(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    b.write_text("X = 1\n")
    path = tmp_path / "c.json"
    lint_files([a, b], root=tmp_path, cache=LintCache(path))

    # editing b: a's LOCAL results stay cached, project results (keyed by
    # the scope signature) re-run for everyone
    b.write_text("X = 2\n")
    c = LintCache(path)
    stats = {}
    lint_files([a, b], root=tmp_path, cache=c, stats=stats)
    assert c.hits > 0 and c.misses > 0
    from tools.tpulint.core import all_passes
    n_project = sum(1 for p in all_passes().values() if p.project)
    # both files re-run every project pass; only b re-runs local passes
    assert c.misses >= 2 * n_project

    # unchanged again -> full hit, and no pass executed at all
    c2 = LintCache(path)
    stats2 = {}
    lint_files([a, b], root=tmp_path, cache=c2, stats=stats2)
    assert c2.misses == 0 and stats2["pass_ms"] == {}


def test_cache_findings_survive_roundtrip_suppressed(tmp_path):
    # suppressions live in the hashed content: cached results honor them
    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n"
                 "    return [x.asnumpy() for x in xs]"
                 "  # tpulint: disable=host-sync\n")
    path = tmp_path / "c.json"
    assert lint_files([a], root=tmp_path, cache=LintCache(path),
                      passes=["host-sync"]) == []
    assert lint_files([a], root=tmp_path, cache=LintCache(path),
                      passes=["host-sync"]) == []


def test_cli_stats_flag(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    rc = main([str(bad), "--stats", "--format", "json",
               "--cache", str(tmp_path / "c.json")])
    captured = capsys.readouterr()
    assert rc == 1
    # stats go to stderr so --format json keeps a parseable stdout
    json.loads(captured.out)
    assert "tpulint --stats:" in captured.err and "cache:" in captured.err \
        and "pass " in captured.err and "total:" in captured.err


def test_runtime_gate_cold_under_30s_warm_under_5s(tmp_path):
    """The tier-1 cost contract for the whole-program engine: a cold run
    over mxnet_tpu/ completes in under 30s, a warm (fully cached) run in
    under 5s."""
    import time

    cache = str(tmp_path / "gate-cache.json")
    t0 = time.monotonic()
    cold = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu",
         "--cache", cache],
        cwd=str(REPO), capture_output=True, text=True)
    cold_s = time.monotonic() - t0
    assert cold.returncode == 0, cold.stdout + cold.stderr
    assert cold_s < 30.0, "cold whole-program lint took %.1fs" % cold_s

    t0 = time.monotonic()
    warm = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu",
         "--cache", cache],
        cwd=str(REPO), capture_output=True, text=True)
    warm_s = time.monotonic() - t0
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert warm_s < 5.0, "warm (cached) lint took %.1fs" % warm_s


def test_write_baseline_preserves_justifications(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(tmp_path / "c.json")]) == 0
    counts = load_baseline(bl)
    (key,) = counts
    core.write_baseline_counts(counts, bl, justifications={key: "because"})
    assert core.load_justifications(bl) == {key: "because"}
    # a rewrite keeps the surviving entry's justification
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(tmp_path / "c.json")]) == 0
    assert core.load_justifications(bl) == {key: "because"}


def test_lint_sources_duplicate_relpath_does_not_crash():
    # lint_sources is the documented multi-file entry point; duplicate
    # relpaths must not crash the graph build's ordering
    pairs = [("mxnet_tpu/x.py", "def f(xs):\n    return [x.item() for x in xs]\n"),
             ("mxnet_tpu/x.py", "def g():\n    return 1\n")]
    found = lint_sources(pairs, passes=["host-sync"])
    assert len(found) == 1


def test_cache_prunes_entries_for_deleted_files(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("X = 1\n")
    b.write_text("Y = 2\n")
    path = tmp_path / "c.json"
    lint_files([a, b], root=tmp_path, cache=LintCache(path))
    b.unlink()
    lint_files([a], root=tmp_path, cache=LintCache(path))
    import json as _json
    entries = _json.loads(path.read_text())["files"]
    assert "a.py" in entries and "b.py" not in entries


def test_use_after_donate_intermediate_introspection_not_a_consumer():
    # len()/logging touching a prep'd name first must NOT open the
    # donation window (and must not steal the consumer's identity)
    assert lint("""
        def ok(flat_ws, buckets, fn, log):
            argnums, consumed = donation_prep(flat_ws, buckets)
            n = len(flat_ws)
            log.debug("packing %d", n)
            new_ws = fn(flat_ws, buckets)
            return new_ws
    """, "use-after-donate") == []
    # the real consumer still opens it
    f = lint("""
        def stale(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            n = len(flat_ws)
            new_ws = fn(flat_ws, buckets)
            return flat_ws[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`flat_ws`" in f[0].message


def test_use_after_donate_same_statement_read_after_call():
    # positional order approximates evaluation order: a read AFTER the
    # donating call in one statement is stale...
    f = lint("""
        def bad(opt, idx, g, weights, states):
            out = fused_apply(opt, idx, g, weights, states) + weights[0]
            return out
    """, "use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message
    # ...a read BEFORE it is not
    assert lint("""
        def ok(opt, idx, g, weights, states):
            out = weights[0] + fused_apply(opt, idx, g, weights, states)
            return out
    """, "use-after-donate") == []


def test_project_scope_gives_changed_only_cross_file_context(tmp_path):
    # --changed-only semantics: report only changed files, but keep the
    # full scope as graph context so cross-file traced seeds still reach
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    helpers = pkg / "helpers.py"
    steps = pkg / "steps.py"
    helpers.write_text("def helper(x):\n    return x.asnumpy()\n")
    steps.write_text("import jax\n"
                     "from mxnet_tpu.helpers import helper\n\n"
                     "@jax.jit\n"
                     "def step(x):\n"
                     "    return helper(x)\n")
    # changed file alone: no seed visible, false clean
    alone = lint_files([helpers], root=tmp_path,
                       passes=["traced-host-sync"])
    assert alone == []
    # with the unchanged file as graph context: the hazard is visible,
    # and findings still come only from the changed file
    ctxd = lint_files([helpers], root=tmp_path, passes=["traced-host-sync"],
                      project_scope=[helpers, steps])
    assert len(ctxd) == 1 and ctxd[0].path == "mxnet_tpu/helpers.py"


def test_cli_stats_emitted_with_write_baseline(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    assert main([str(bad), "--write-baseline", "--stats",
                 "--baseline", str(tmp_path / "bl.json"),
                 "--cache", str(tmp_path / "c.json")]) == 0
    assert "tpulint --stats:" in capsys.readouterr().err
