"""Tests for tools/tpulint — the AST-based TPU-correctness linter.

Pure AST analysis: no JAX import, no device work — tier-1 fast by
construction. Each pass gets positive + negative fixtures; suppression,
baseline, the repo-wide gate, and the CLI exit-code contract are covered.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.tpulint import core  # noqa: E402
from tools.tpulint.cli import filter_to_scope, lint_paths, main  # noqa: E402
from tools.tpulint.core import (DEFAULT_BASELINE, apply_baseline,  # noqa: E402
                                baseline_counts, collect_files, lint_files,
                                lint_source, load_baseline, write_baseline)


def lint(src, rule=None, relpath="mxnet_tpu/fake.py"):
    """Lint a snippet; returns findings (optionally for one rule)."""
    findings = lint_source(relpath, textwrap.dedent(src),
                           passes=[rule] if rule else None)
    return findings


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

def test_host_sync_asnumpy_in_loop():
    found = lint("""
        def f(batches):
            out = []
            for b in batches:
                out.append(b.asnumpy())
            return out
    """, "host-sync")
    assert len(found) == 1 and found[0].line == 5


def test_host_sync_float_of_call_in_loop():
    found = lint("""
        def f(xs):
            total = 0.0
            while xs:
                total += float(xs.pop().sum())
            return total
    """, "host-sync")
    assert len(found) == 1


def test_host_sync_in_jit_even_outside_loop():
    found = lint("""
        import jax

        @jax.jit
        def step(x):
            return x * x.item()
    """, "host-sync")
    assert len(found) == 1 and "trace time" in found[0].message


def test_host_sync_jit_reaches_helpers_transitively():
    found = lint("""
        import jax, numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(x):
            return helper(x) + 1
    """, "host-sync")
    assert len(found) == 1 and found[0].line == 5


def test_host_sync_negative():
    assert not lint("""
        def f(batches):
            x = batches[0].asnumpy()      # outside any loop: one sync, fine
            n = float(len(batches))       # len() never touches the device
            for b in batches:
                n += 1.0
            return x, n
    """, "host-sync")


def test_host_sync_comprehension_counts_as_loop():
    found = lint("""
        def f(batches):
            return [b.asnumpy() for b in batches]
    """, "host-sync")
    assert len(found) == 1


# ---------------------------------------------------------------------------
# tracer-leak
# ---------------------------------------------------------------------------

def test_tracer_leak_positive():
    found = lint("""
        import jax, os, time

        @jax.jit
        def step(x):
            print("step!")
            t = time.time()
            flag = os.environ.get("MXNET_FLAG")
            return x + t
    """, "tracer-leak")
    msgs = " ".join(f.message for f in found)
    assert len(found) == 3
    assert "print" in msgs and "time.time" in msgs and "os.environ" in msgs


def test_tracer_leak_global_and_wrapped_lambda():
    found = lint("""
        import jax

        _calls = 0

        def bump(x):
            global _calls
            _calls += 1
            return x

        f = jax.jit(lambda x: bump(x) + 1)
    """, "tracer-leak")
    assert len(found) == 1 and "global _calls" in found[0].message


def test_tracer_leak_curried_partial_wrap():
    found = lint("""
        import jax
        from functools import partial

        def step(x):
            print("traced")
            return x

        fast_step = partial(jax.jit, donate_argnums=0)(step)
    """, "tracer-leak")
    assert len(found) == 1 and "print" in found[0].message


def test_tracer_leak_partial_decorator_and_np_random():
    found = lint("""
        import jax, numpy as np
        from functools import partial

        @partial(jax.jit, static_argnums=0)
        def step(n, x):
            return x + np.random.rand(n)
    """, "tracer-leak")
    assert len(found) == 1 and "np.random.rand" in found[0].message


def test_tracer_leak_negative_outside_jit():
    assert not lint("""
        import os, time

        def host_loop(x):
            print("fine here")
            return x, time.time(), os.getenv("HOME")
    """, "tracer-leak")


# ---------------------------------------------------------------------------
# dtype-drift
# ---------------------------------------------------------------------------

def test_dtype_drift_positive():
    found = lint("""
        import numpy as np
        import jax.numpy as jnp

        def f(x):
            return np.zeros(3, dtype=np.float64) + x.astype(jnp.float64)
    """, "dtype-drift")
    assert len(found) == 2


def test_dtype_drift_registry_exempt():
    assert not lint("""
        import jax.numpy as jnp

        DTYPE_NP = {
            "float64": jnp.float64,
            "float32": jnp.float32,
        }
    """, "dtype-drift")


def test_dtype_drift_negative():
    assert not lint("""
        import numpy as np

        def f(x):
            return x.astype(np.float32)
    """, "dtype-drift")


# ---------------------------------------------------------------------------
# native-guard
# ---------------------------------------------------------------------------

def test_native_guard_unguarded_assign():
    found = lint("""
        from mxnet_tpu import _native

        def stats():
            lib = _native.get_lib()
            return lib.MXTPUStorageStats()
    """, "native-guard")
    assert len(found) == 1 and "never checked" in found[0].message


def test_native_guard_guarded_variants():
    assert not lint("""
        from mxnet_tpu import _native

        def a():
            lib = _native.get_lib()
            if lib is None:
                return 0
            return lib.f()

        def b():
            lib = _native.get_lib()
            return lib.f() if lib is not None else 0

        def c():
            lib = _native.get_lib()
            if not lib:
                return 0
            return lib.f()

        def d():
            lib = _native.get_lib()
            return getattr(lib, "_name", None) or "unavailable"

        def e():
            return _native.get_lib() is not None
    """, "native-guard")


def test_native_guard_return_forward_and_direct_use():
    found = lint("""
        from mxnet_tpu import _native

        def forward():
            return _native.get_lib()

        def direct():
            return _native.get_lib().f()
    """, "native-guard")
    assert len(found) == 2
    assert any("forwards an unguarded Optional" in f.message for f in found)
    assert any("used directly" in f.message for f in found)


# ---------------------------------------------------------------------------
# env-knob
# ---------------------------------------------------------------------------

def test_env_knob_positive_reads():
    found = lint("""
        import os

        A = os.environ.get("MXNET_A", "1")
        B = os.getenv("MXNET_B")
        C = os.environ["MXNET_C"]
        D = os.environ.setdefault("MXNET_D", "x")
    """, "env-knob")
    assert len(found) == 4


def test_env_knob_mutations_not_flagged():
    assert not lint("""
        import os

        os.environ["MXNET_A"] = "1"
        os.environ.pop("MXNET_B", None)
        del os.environ["MXNET_C"]
    """, "env-knob")


def test_env_knob_scoped_to_mxnet_tpu():
    src = """
        import os
        A = os.environ.get("MXNET_A")
    """
    assert lint(src, "env-knob", relpath="mxnet_tpu/x.py")
    assert not lint(src, "env-knob", relpath="tools/x.py")
    assert not lint(src, "env-knob", relpath="mxnet_tpu/base.py")


# ---------------------------------------------------------------------------
# swallowed-error
# ---------------------------------------------------------------------------

def test_swallowed_error_positive_variants():
    found = lint("""
        def f(q):
            try:
                q.get()
            except Exception:
                pass
            while True:
                try:
                    q.get()
                except:
                    continue
            try:
                q.get()
            except (ValueError, BaseException):
                ...
    """, "swallowed-error")
    assert len(found) == 3


def test_swallowed_error_negative_handled_or_narrow():
    assert not lint("""
        import queue

        def f(q, log):
            try:
                q.get()
            except queue.Empty:
                pass
            try:
                q.get()
            except Exception as exc:
                log.warning("boom: %s", exc)
            try:
                q.get()
            except Exception:
                return None
            try:
                q.get()
            except Exception:
                raise
    """, "swallowed-error")


def test_swallowed_error_scoped_to_runtime_package():
    src = """
        def f(q):
            try:
                q.get()
            except Exception:
                pass
    """
    assert lint(src, "swallowed-error", relpath="mxnet_tpu/x.py")
    assert not lint(src, "swallowed-error", relpath="tools/x.py")


def test_swallowed_error_suppressible():
    found = lint("""
        def __del__(self):
            try:
                self.close()
            except Exception:  # tpulint: disable=swallowed-error
                pass
    """, "swallowed-error")
    assert not found


# ---------------------------------------------------------------------------
# oom-masking
# ---------------------------------------------------------------------------

def test_oom_masking_positive_broad_and_xla():
    found = lint("""
        import telemetry

        def step(fn, x, log):
            try:
                return telemetry.jit_call("s", fn, x)
            except Exception as exc:
                log.warning("boom: %r", exc)
                return None

        def fetch(arrays, XlaRuntimeError):
            try:
                return fetch_host(arrays)
            except XlaRuntimeError:
                return None
    """, "oom-masking")
    assert len(found) == 2
    assert all("hbm.classify" in f.message for f in found)


def test_oom_masking_negative_routed_or_reraised():
    assert not lint("""
        import telemetry
        from mxnet_tpu.resilience import hbm

        def survives(fn, x):
            try:
                return telemetry.jit_call("s", fn, x)
            except Exception as exc:
                if not hbm.oom_survival("s", exc):
                    raise
                return None

        def reraises(fn, x, log):
            try:
                return telemetry.jit_call("s", fn, x)
            except Exception as exc:
                log.warning("boom: %r", exc)
                raise

        def classifies(fn, x, log):
            try:
                return telemetry.jit_call("s", fn, x)
            except Exception as exc:
                kind = hbm.classify(exc)
                log.warning("kind=%s", kind)
                return None
    """, "oom-masking")


def test_oom_masking_needs_dispatch_in_try():
    # a broad catch around host-only work is swallowed-error's beat, not
    # an OOM mask — no dispatch/transfer call, no finding
    assert not lint("""
        def f(q, log):
            try:
                q.get()
            except Exception as exc:
                log.warning("boom: %r", exc)
                return None
    """, "oom-masking")


def test_oom_masking_narrow_catch_and_scope():
    src = """
        import telemetry

        def step(fn, x):
            try:
                return telemetry.jit_call("s", fn, x)
            except KeyError:
                return None
    """
    assert not lint(src, "oom-masking")
    broad = src.replace("KeyError", "Exception")
    assert lint(broad, "oom-masking", relpath="mxnet_tpu/x.py")
    assert not lint(broad, "oom-masking", relpath="tools/x.py")


OOM_BUGS = (REPO / "tests" / "fixtures" / "tpulint_oom_bugs.py").read_text()


def test_oom_masking_seeded_fixture():
    found = lint_source("mxnet_tpu/_oom_bugs.py", OOM_BUGS,
                        passes=["oom-masking"])
    lines = sorted(f.line for f in found)
    assert len(found) == 2
    # the two seeded masks fire; the routed/re-raising/narrow handlers
    # below them stay clean
    texts = [OOM_BUGS.splitlines()[ln - 1] for ln in lines]
    assert all("BUG" in t for t in texts)


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------

def test_inline_suppression():
    src = """
        import os
        A = os.environ.get("MXNET_A")  # tpulint: disable=env-knob -- justified
        B = os.environ.get("MXNET_B")  # tpulint: disable=all
        C = os.environ.get("MXNET_C")  # tpulint: disable=host-sync (wrong rule)
    """
    found = lint(src, "env-knob")
    assert len(found) == 1 and found[0].line == 5


def test_baseline_roundtrip(tmp_path):
    src_v1 = "import os\nA = os.environ.get('MXNET_A')\n"
    f1 = lint_source("mxnet_tpu/x.py", src_v1, passes=["env-knob"])
    assert len(f1) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(f1, bl)
    baseline = load_baseline(bl)
    # same findings -> nothing new, even when lines shift
    shifted = lint_source("mxnet_tpu/x.py", "import os\n\n\nA = os.environ.get('MXNET_A')\n",
                          passes=["env-knob"])
    assert apply_baseline(shifted, baseline) == []
    # a second occurrence of the same key -> exactly the surplus is new
    src_v2 = src_v1 + "B = os.environ.get('MXNET_A')\n"
    f2 = lint_source("mxnet_tpu/x.py", src_v2, passes=["env-knob"])
    new = apply_baseline(f2, baseline)
    assert len(new) == 1 and new[0].line == 3


def test_baseline_counts_keys_have_no_line_numbers():
    f = lint_source("mxnet_tpu/x.py", "import os\nA = os.environ.get('X')\n",
                    passes=["env-knob"])
    (key,) = baseline_counts(f)
    assert key.startswith("mxnet_tpu/x.py::env-knob::")
    assert "\n" not in key and ":2:" not in key


# ---------------------------------------------------------------------------
# repo gate + CLI contract
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# perparam-jit
# ---------------------------------------------------------------------------

def test_perparam_jit_immediate_and_cached_dispatch():
    f = lint("""
        import jax
        def apply(params, fns, cache):
            for p in params:
                jax.jit(lambda x: x + 1)(p)
            for k, p in params.items():
                cache._step_cache[k](p)
        """, rule="perparam-jit")
    assert len(f) == 2
    assert all(x.rule == "perparam-jit" for x in f)


def test_perparam_jit_fused_invocation_and_bound_name():
    f = lint("""
        import jax
        def update_all(self, params, g, lr, wd):
            step = jax.jit(lambda w: w - lr * w)
            for w in params:
                self._fused("sgd", None)(w, g, lr, wd)
            for w in params:
                step(w)
        """, rule="perparam-jit")
    assert len(f) == 2


def test_perparam_jit_optimizer_and_kvstore_dispatch():
    f = lint("""
        def update(self, params, grads):
            for i, (w, g) in enumerate(zip(params, grads)):
                self._updater(i, g, w)
            for i, g in enumerate(grads):
                self._kvstore.push(i, g)
                self._kvstore.pull(i, g)
            for i, (w, g) in enumerate(zip(params, grads)):
                self.optimizer.update(i, w, g, None)
        """, rule="perparam-jit")
    assert len(f) == 4


def test_perparam_jit_negative_outside_loop_and_scope():
    # one-shot dispatches and non-loop calls are fine
    f = lint("""
        import jax
        def apply(self, tree, g):
            fn = jax.jit(lambda x: x)
            fn(tree)
            self._updater(0, g, tree)
            self._kvstore.push(0, g)
        """, rule="perparam-jit")
    assert f == []
    # dict/set merges named `opt`/`cfg` are NOT optimizer dispatch
    f = lint("""
        def merge(configs):
            opt = {}
            for cfg in configs:
                opt.update(cfg)
            return opt
        """, rule="perparam-jit")
    assert f == []
    # the pass polices mxnet_tpu/ only (user tools keep their loops)
    f = lint("""
        import jax
        def bench(params):
            for p in params:
                jax.jit(lambda x: x)(p)
        """, rule="perparam-jit", relpath="tools/bench_thing.py")
    assert f == []


def test_gate_repo_is_clean_against_committed_baseline():
    """The acceptance gate: zero non-baselined findings across mxnet_tpu/
    and tools/. A new hazard in a PR lands here as a failure."""
    new, all_findings = lint_paths(["mxnet_tpu", "tools"])
    assert new == [], "new tpulint findings (fix, suppress with justification," \
                      " or --write-baseline):\n" + "\n".join(map(str, new))
    # the baseline itself must stay honest: every entry still matches code
    counts = baseline_counts(all_findings)
    baseline = load_baseline(DEFAULT_BASELINE)
    stale = [k for k in baseline if counts.get(k, 0) < baseline[k]]
    assert stale == [], "stale baseline entries (regenerate with " \
                        "--write-baseline):\n" + "\n".join(stale)


def test_cli_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu", "tools"],
        cwd=str(REPO), capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = tmp_path / "viol.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", str(bad)],
        cwd=str(REPO), capture_output=True, text=True)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    assert "host-sync" in dirty.stdout


def test_cli_json_format_and_select(tmp_path, capsys):
    bad = tmp_path / "viol.py"
    bad.write_text("import os\ndef f(xs):\n    return [x.asnumpy() for x in xs]\n")
    rc = main([str(bad), "--format", "json", "--select", "host-sync"])
    out = capsys.readouterr().out
    payload = json.loads(out)
    assert rc == 1
    assert payload["total"] == 1 and payload["new"][0]["rule"] == "host-sync"
    # unknown rule -> usage error
    assert main([str(bad), "--select", "no-such-rule"]) == 2


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "viol.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl)]) == 0
    # an additional violation beyond the baselined one -> fails again
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n"
                   "def g(xs):\n    return [x.item() for x in xs]\n")
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl)]) == 1


def test_collect_files_survives_hidden_ancestor(tmp_path):
    # a dotted ancestor of the scanned dir must not empty the lint scope
    pkg = tmp_path / ".work" / "repo" / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text("x = 1\n")
    (pkg / ".hidden" ).mkdir()
    (pkg / ".hidden" / "skip.py").write_text("x = 1\n")
    files = collect_files([str(pkg)])
    assert [f.name for f in files] == ["mod.py"]


def test_write_baseline_scoped_run_keeps_other_entries(tmp_path, capsys):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    b.write_text("def g(xs):\n    return [x.item() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(a), str(b), "--baseline", str(bl), "--write-baseline"]) == 0
    # re-baselining only a.py must not drop b.py's entry
    assert main([str(a), "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert main([str(a), str(b), "--baseline", str(bl)]) == 0
    # and a scoped *check* of a.py alone must not report b.py's entry stale
    assert main([str(a), "--baseline", str(bl)]) == 0
    assert "stale" not in capsys.readouterr().out


def test_nonexistent_path_is_usage_error(tmp_path, capsys):
    # a typo'd path must not produce a green "0 findings" run
    assert main([str(tmp_path / "does_not_exist.py")]) == 2
    assert main(["mxnet_tpu/no_such_file.py"]) == 2


def test_changed_only_git_failure_is_loud(monkeypatch):
    from tools.tpulint import cli as cli_mod

    monkeypatch.setattr(cli_mod, "changed_files", lambda: None)
    assert cli_mod.main(["--changed-only"]) == 2


def test_changed_only_filter():
    scope = collect_files(["mxnet_tpu"])
    changed = ["mxnet_tpu/base.py", "mxnet_tpu/does_not_exist.py", "README.md"]
    picked = filter_to_scope(changed, scope)
    assert [p.name for p in picked] == ["base.py"]


def test_list_rules_names_all_five(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("host-sync", "tracer-leak", "dtype-drift", "native-guard",
                 "env-knob"):
        assert rule in out


def test_parse_error_is_a_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    found = lint_files([bad], root=tmp_path)
    assert len(found) == 1 and found[0].rule == "parse-error"


def test_undecodable_and_null_byte_files_are_findings_not_crashes(tmp_path):
    latin = tmp_path / "latin.py"
    latin.write_bytes(b"# caf\xe9\nx = 1\n")
    nul = tmp_path / "nul.py"
    nul.write_bytes(b"x = 1\x00\n")
    found = lint_files([latin, nul], root=tmp_path)
    assert sorted(f.rule for f in found) == ["parse-error", "parse-error"]


# ---------------------------------------------------------------------------
# eager-step
# ---------------------------------------------------------------------------

def test_eager_step_gluon_idiom_flagged():
    f = lint("""
        def train(net, loss_fn, trainer, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(x.shape[0])
        """, rule="eager-step")
    assert len(f) == 1 and f[0].rule == "eager-step"


def test_eager_step_module_idiom_flagged():
    f = lint("""
        def fit(self, train_data):
            for epoch in range(3):
                for batch in train_data:
                    self.forward_backward(batch)
                    self.update()
        """, rule="eager-step")
    # both the epoch loop and the batch loop contain the full step
    assert len(f) == 2


def test_eager_step_negative_cases():
    # a step outside any loop is a single step, not a loop regime
    f = lint("""
        def one(net, loss_fn, trainer, x, y):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
        """, rule="eager-step")
    assert f == []
    # forward-only loops (eval/predict) are fine
    f = lint("""
        def score(net, batches, metric):
            for x, y in batches:
                metric.update(y, net(x))
        """, rule="eager-step")
    assert f == []
    # backward without an update is grad accumulation, not a train step
    f = lint("""
        def grads(net, loss_fn, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
        """, rule="eager-step")
    assert f == []
    # ...and metric bookkeeping next to it is still not an optimizer step
    f = lint("""
        def grads(net, loss_fn, batches, eval_metric):
            for x, y in batches:
                with autograd.record():
                    out = net(x)
                    loss = loss_fn(out, y)
                loss.backward()
                eval_metric.update(y, out)
        """, rule="eager-step")
    assert f == []


def test_eager_step_nested_function_not_attributed_to_loop():
    # a step packaged in a closure defined inside a loop body runs when
    # called, not per definition — the loop itself is not flagged
    f = lint("""
        def build(net, loss_fn, trainer, batches):
            fns = []
            for x, y in batches:
                def one_step(x=x, y=y):
                    with autograd.record():
                        loss = loss_fn(net(x), y)
                    loss.backward()
                    trainer.step(1)
                fns.append(one_step)
            return fns
        """, rule="eager-step")
    assert f == []


def test_eager_step_scoped_to_mxnet_tpu():
    src = """
        def train(net, loss_fn, trainer, batches):
            for x, y in batches:
                with autograd.record():
                    loss = loss_fn(net(x), y)
                loss.backward()
                trainer.step(1)
    """
    assert lint(src, rule="eager-step",
                relpath="tools/somewhere.py") == []
    assert len(lint(src, rule="eager-step")) == 1


# ---------------------------------------------------------------------------
# decode-host-sync
# ---------------------------------------------------------------------------

def test_decode_host_sync_flags_syncs_in_decode_scope():
    # straight-line code, no loop: the generic host-sync pass is blind
    # here, the cadence comes from the scope name
    f = lint("""
        def decode_step(engine, step):
            sampled = step()
            return fetch_host([sampled])[0]
        """, rule="decode-host-sync")
    assert len(f) == 1 and "fetch_host" in f[0].message

    f = lint("""
        def generate(model, prompt):
            logits = model(prompt)
            return logits.asnumpy()
        """, rule="decode-host-sync")
    assert len(f) == 1 and ".asnumpy" in f[0].message


def test_decode_host_sync_class_scope_and_item():
    # any method of a Decode* class is per-token cadence, whatever its
    # name; .item() and .tolist() are sync calls too
    f = lint("""
        class DecodeEngine:
            def _tick(self):
                tok = self._step()
                return tok.item()
        """, rule="decode-host-sync")
    assert len(f) == 1 and ".item" in f[0].message


def test_decode_host_sync_negative_cases():
    # imdecode (host-side image decoding) must not match the word scope;
    # sync calls outside any decode scope belong to the generic pass
    assert lint("""
        def imdecode(buf):
            return fetch_host([buf])[0]
        """, rule="decode-host-sync") == []
    assert lint("""
        def forward(engine, batch):
            out = engine(batch)
            return fetch_host([out])[0]
        """, rule="decode-host-sync") == []
    # non-sync calls inside decode scope stay clean
    assert lint("""
        def decode_step(engine, toks):
            return engine.step(toks)
        """, rule="decode-host-sync") == []


def test_decode_host_sync_scoped_to_mxnet_tpu():
    src = """
        def decode_loop(step):
            return fetch_host([step()])[0]
    """
    assert lint(src, rule="decode-host-sync",
                relpath="tools/elsewhere.py") == []
    assert len(lint(src, rule="decode-host-sync")) == 1


def test_decode_host_sync_repo_sites_are_baselined():
    # the decode plane keeps exactly its two justified syncs (the tick's
    # sampled-token fetch + the prefill first-token fetch) — baselined,
    # so the repo gate stays clean and any NEW sync is a finding
    counts = load_baseline(DEFAULT_BASELINE)
    key = ("mxnet_tpu/serving/decode.py::decode-host-sync::"
           "`fetch_host()` in decode-plane code runs per token — "
           "a device->host stall every tick")
    assert counts.get(key) == 2


# ---------------------------------------------------------------------------
# replicated-state
# ---------------------------------------------------------------------------

def test_replicated_state_flags_eager_copy_and_device_put():
    f = lint("""
        def restore(updater):
            for i in updater.states:
                updater.states[i] = jnp.copy(updater.states[i])
        """, rule="replicated-state")
    assert len(f) == 1 and "jnp.copy" in f[0].message

    f = lint("""
        def spread(opt_states, repl):
            return [jax.device_put(s, repl) for s in opt_states]
        """, rule="replicated-state")
    assert len(f) == 1 and "device_put" in f[0].message


def test_replicated_state_flags_tree_map_full_tree_copy():
    f = lint("""
        def gather(states, repl):
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, repl), states)
        """, rule="replicated-state")
    assert len(f) == 1 and "tree_map" in f[0].message


def test_replicated_state_negative_cases():
    # non-state arrays stay out of scope
    assert lint("""
        def copy_params(pvals):
            return {n: jnp.copy(v) for n, v in pvals.items()}
        """, rule="replicated-state") == []
    # the blessed layout-aware helpers are the FIX, not a finding
    assert lint("""
        def gather(states, mesh):
            return [parallel.fresh_replicate(s, mesh) for s in states]
        """, rule="replicated-state") == []
    # states_synced is bool bookkeeping, not device state
    assert lint("""
        def mark(updater):
            updater.states_synced = jnp.copy(updater.states_synced)
        """, rule="replicated-state") == []
    # tree_map without a copy/device_put inside is fine
    assert lint("""
        def cast(states):
            return jax.tree_util.tree_map(lambda x: x.astype("f4"), states)
        """, rule="replicated-state") == []


def test_replicated_state_blessed_homes_exempt():
    src = """
        def fresh_replicate(states, repl):
            return jax.device_put(states, repl)
    """
    assert lint(src, rule="replicated-state",
                relpath="mxnet_tpu/parallel.py") == []
    assert lint(src, rule="replicated-state",
                relpath="mxnet_tpu/fastpath/zero.py") == []
    assert lint(src, rule="replicated-state",
                relpath="tools/whatever.py") == []
    assert len(lint(src, rule="replicated-state")) == 1


def test_replicated_state_repo_gate_clean():
    # the repo itself carries ZERO eager state placements — nothing to
    # baseline, and the first regression is a finding
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["replicated-state"])
                if f.rule == "replicated-state"]
    assert findings == []


# ---------------------------------------------------------------------------
# non-atomic-write
# ---------------------------------------------------------------------------

def test_non_atomic_write_flags_bare_open_on_ckpt_path():
    f = lint("""
        def store(ckpt_path, blob):
            with open(ckpt_path, "wb") as fh:
                fh.write(blob)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "open" in f[0].message
    # checkpoint-ish by FUNCTION even when the path arg is opaque
    f = lint("""
        def save_states(fname, blob):
            open(fname, "wb").write(blob)
        """, rule="non-atomic-write")
    assert len(f) == 1


def test_non_atomic_write_flags_np_save_and_pickle_dump():
    f = lint("""
        def snapshot(path, arr):
            np.save(path, arr)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "np.save" in f[0].message
    f = lint("""
        def write(obj, manifest_file):
            pickle.dump(obj, manifest_file)
        """, rule="non-atomic-write")
    assert len(f) == 1 and "pickle.dump" in f[0].message


def test_non_atomic_write_negative_cases():
    # reads are fine, and writes to non-checkpoint paths are out of scope
    assert lint("""
        def load(ckpt_path):
            with open(ckpt_path, "rb") as fh:
                return fh.read()
        """, rule="non-atomic-write") == []
    assert lint("""
        def emit(log_path, line):
            open(log_path, "a").write(line)
        """, rule="non-atomic-write") == []
    # tools/tests are out of scope — only mxnet_tpu/ carries the contract
    assert lint("""
        def save(ckpt_path, blob):
            open(ckpt_path, "wb").write(blob)
        """, rule="non-atomic-write", relpath="tools/whatever.py") == []


def test_non_atomic_write_commit_helpers_exempt():
    # the atomic helpers themselves, and writer lambdas routed through
    # them, ARE the sanctioned implementation
    assert lint("""
        def _atomic_write(path, writer):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(b"checkpoint")
            os.replace(tmp, path)
        """, rule="non-atomic-write") == []
    assert lint("""
        def save(self, epoch, blob):
            self._commit(self._params_path(epoch),
                         lambda p: open(p, "wb").write(blob))
        """, rule="non-atomic-write") == []
    assert lint("""
        def save(self, epoch, blob):
            self._commit_bytes(self._shard_path(epoch), blob, "shard")
        """, rule="non-atomic-write") == []


def test_non_atomic_write_repo_gate_clean():
    # every pre-existing bare write rides the committed baseline; the
    # elastic checkpoint plane itself must be finding-free
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["non-atomic-write"])]
    baseline = load_baseline(DEFAULT_BASELINE)
    assert apply_baseline(findings, baseline) == []
    assert [f for f in findings if "elastic" in f.path] == []


# ---------------------------------------------------------------------------
# unbounded-queue
# ---------------------------------------------------------------------------

def test_unbounded_queue_flags_bare_queue():
    f = lint("""
        def start(ctx):
            tasks = queue.Queue()
            return tasks
        """, rule="unbounded-queue")
    assert len(f) == 1 and "queue.Queue" in f[0].message
    # multiprocessing / context spellings and attribute targets count too
    f = lint("""
        class P:
            def __init__(self, ctx):
                self._task_q = ctx.Queue()
        """, rule="unbounded-queue")
    assert len(f) == 1


def test_unbounded_queue_flags_queueish_deque():
    f = lint("""
        class Server:
            def __init__(self):
                self._queue = collections.deque()
        """, rule="unbounded-queue")
    assert len(f) == 1 and "maxlen" in f[0].message
    # a literal maxlen=None is spelled-out unboundedness, not a bound
    f = lint("""
        def make():
            req_queue = deque(maxlen=None)
            return req_queue
        """, rule="unbounded-queue")
    assert len(f) == 1
    # subscript target: per-tenant sub-queue dicts are still queues
    f = lint("""
        def add(self, tid):
            self._queues[tid] = collections.deque()
        """, rule="unbounded-queue")
    assert len(f) == 1


def test_unbounded_queue_negative_cases():
    # bounded constructions are the fix, not a finding
    assert lint("""
        def start(self, depth):
            self._queue = queue.Queue(maxsize=depth)
            self._q2 = queue.Queue(depth)
        """, rule="unbounded-queue") == []
    assert lint("""
        class T:
            def __init__(self, depth):
                self.queue = collections.deque(maxlen=depth)
        """, rule="unbounded-queue") == []
    # a deque that is NOT queue-named is a general container — out of
    # scope (flagging every deque would bury the signal)
    assert lint("""
        def collect():
            pending = collections.deque()
            history = deque()
            return pending, history
        """, rule="unbounded-queue") == []


def test_unbounded_queue_scope_is_mxnet_tpu():
    src = """
        def start():
            tasks = queue.Queue()
            return tasks
    """
    assert lint(src, rule="unbounded-queue",
                relpath="tools/whatever.py") == []
    assert lint(src, rule="unbounded-queue",
                relpath="tests/test_x.py") == []
    assert len(lint(src, rule="unbounded-queue")) == 1


def test_unbounded_queue_repo_gate_clean_and_justified():
    # the serving planes (batcher, decode, tenancy sub-queues) are
    # bounded by construction — finding-free; the two multiprocessing
    # image-pipeline queues ride the baseline WITH a justification
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["unbounded-queue"])]
    assert [f for f in findings if "serving" in f.path] == []
    baseline = load_baseline(DEFAULT_BASELINE)
    assert apply_baseline(findings, baseline) == []
    justs = core.load_justifications(DEFAULT_BASELINE)
    for f in findings:
        assert f.baseline_key() in justs, \
            "unbounded-queue baseline entries must carry a justification"


# ---------------------------------------------------------------------------
# metric-cardinality
# ---------------------------------------------------------------------------

def test_metric_cardinality_flags_interpolated_labels():
    # f-string, %-format and .format label values are runtime data
    f = lint("""
        REQS = telemetry.counter("mxnet_x_total", labels=("rid",))
        def f(request_id):
            REQS.inc(rid=f"req-{request_id}")
        """, rule="metric-cardinality")
    assert len(f) == 1 and "'rid'" in f[0].message
    f = lint("""
        REQS = telemetry.counter("mxnet_x_total", labels=("who",))
        def f(uid):
            REQS.inc(who="user-%s" % uid)
        """, rule="metric-cardinality")
    assert len(f) == 1
    f = lint("""
        H = telemetry.histogram("mxnet_h_ms", labels=("k",))
        def f(x, ms):
            H.observe(ms, k="{}".format(x))
        """, rule="metric-cardinality")
    assert len(f) == 1


def test_metric_cardinality_flags_exception_text_and_ids():
    # str(e) / a bare except-handler binding IS exception text; id-ish
    # parameter names (request_id, trace_id, prompt) are per-request data
    f = lint("""
        G = telemetry.gauge("mxnet_g", labels=("err",))
        def f():
            try:
                pass
            except Exception as e:
                G.set(1, err=str(e))
        """, rule="metric-cardinality")
    assert len(f) == 1 and "str()" in f[0].message
    f = lint("""
        G = telemetry.gauge("mxnet_g", labels=("err",))
        def f():
            try:
                pass
            except Exception as e:
                G.set(1, err=e)
        """, rule="metric-cardinality")
    assert len(f) == 1
    f = lint("""
        H = telemetry.histogram("mxnet_h_ms", labels=("req",))
        def f(trace_id, ms):
            H.observe(ms, req=trace_id)
        """, rule="metric-cardinality")
    assert len(f) == 1


def test_metric_cardinality_sees_chained_and_cross_module_handles():
    # telemetry.counter(...).inc(...) and the ALL-CAPS cross-module
    # handle convention (telemetry.RECOMPILES) are both update sites
    f = lint("""
        def f(prompt):
            telemetry.counter("mxnet_p_total", labels=("p",)).inc(p=prompt)
        """, rule="metric-cardinality")
    assert len(f) == 1
    f = lint("""
        from .. import telemetry
        def f(request_id):
            telemetry.RECOMPILES.inc(site="x-%s" % request_id)
        """, rule="metric-cardinality")
    assert len(f) == 1


def test_metric_cardinality_negative_cases():
    # constant labels, plain bounded names, attribute reads and the
    # tenant exemption (TenantRegistry bounds tenant ids) are all legal
    assert lint("""
        T = telemetry.counter("mxnet_t", labels=("event",))
        def f():
            T.inc(event="shed")
        """, rule="metric-cardinality") == []
    assert lint("""
        T = telemetry.counter("mxnet_t", labels=("tenant",))
        def f(tenant_id):
            T.inc(tenant="t-%s" % tenant_id)
        """, rule="metric-cardinality") == []
    assert lint("""
        T = telemetry.counter("mxnet_t", labels=("site",))
        def f(site):
            T.inc(site=site)
        """, rule="metric-cardinality") == []
    assert lint("""
        T = telemetry.gauge("mxnet_g", labels=("server",))
        class S:
            def f(self):
                T.set(1, server=self.name)
        """, rule="metric-cardinality") == []
    # a non-metric receiver's .set() is out of scope
    assert lint("""
        def f(x, request_id):
            x.set(1, rid=request_id)
        """, rule="metric-cardinality") == []
    # scope is mxnet_tpu/ only
    assert lint("""
        T = telemetry.counter("t", labels=("rid",))
        def f(request_id):
            T.inc(rid=f"{request_id}")
        """, rule="metric-cardinality",
        relpath="tools/whatever.py") == []


def test_metric_cardinality_repo_gate_clean_and_justified():
    # survivors (PJRT device ordinals, exception CLASS names) ride the
    # baseline WITH a justification each; everything else is clean
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["metric-cardinality"])]
    baseline = load_baseline(DEFAULT_BASELINE)
    assert apply_baseline(findings, baseline) == []
    justs = core.load_justifications(DEFAULT_BASELINE)
    for f in findings:
        assert f.baseline_key() in justs, \
            "metric-cardinality baseline entries must carry a justification"
    # the new telemetry v2 modules are finding-free by construction
    assert [f for f in findings
            if "tracing" in f.path or "flightrec" in f.path
            or "slo" in f.path or "httpd" in f.path] == []


# ---------------------------------------------------------------------------
# whole-program graph engine (symbol table / call graph / lattices)
# ---------------------------------------------------------------------------

from tools.tpulint import graph as graph_mod  # noqa: E402
from tools.tpulint.core import FileContext, lint_sources  # noqa: E402


def make_graph(files, depth=graph_mod.DEFAULT_DEPTH):
    """Build a ProjectGraph over {relpath: source} fixtures."""
    ctxs = [FileContext(rp, textwrap.dedent(src), filename=rp)
            for rp, src in sorted(files.items())]
    return graph_mod.build_graph([(c.relpath, c.tree) for c in ctxs],
                                 depth=depth)


def fn_of(gph, qname):
    for info in gph.funcs.values():
        if info.qname == qname:
            return info
    raise AssertionError("no function %r in graph (have: %s)"
                         % (qname, sorted(i.qname for i in gph.funcs.values())))


def test_graph_aliased_import_call_edges():
    gph = make_graph({
        "mxnet_tpu/a.py": """
            def helper(x):
                return x + 1
        """,
        "mxnet_tpu/b.py": """
            from mxnet_tpu.a import helper as h2
            import mxnet_tpu.a as amod
            from .a import helper as h3

            def via_from_alias(x):
                return h2(x)

            def via_module_alias(x):
                return amod.helper(x)

            def via_relative(x):
                return h3(x)
        """})
    helper = fn_of(gph, "mxnet_tpu/a.py::helper")
    for caller in ("via_from_alias", "via_module_alias", "via_relative"):
        info = fn_of(gph, "mxnet_tpu/b.py::%s" % caller)
        assert helper in info.callees, caller


def test_graph_package_init_reexport_resolves():
    # `from .mod import helper` inside pkg/__init__.py resolves against
    # pkg itself (not one level up), so re-export chains through package
    # __init__ files keep their call edges — the mxnet_tpu subpackages
    # (fastpath, serving, telemetry) all re-export this way
    gph = make_graph({
        "pkg/__init__.py": """
            from .mod import helper
        """,
        "pkg/mod.py": """
            def helper(x):
                return x.asnumpy()
        """,
        "pkg/use.py": """
            import jax
            from pkg import helper

            @jax.jit
            def step(x):
                return helper(x)
        """})
    helper = fn_of(gph, "pkg/mod.py::helper")
    step = fn_of(gph, "pkg/use.py::step")
    assert helper in step.callees
    assert gph.is_traced(helper.node)


def test_graph_method_binding_self_and_base_class():
    gph = make_graph({
        "mxnet_tpu/base_mod.py": """
            class Base:
                def shared(self):
                    return 1
        """,
        "mxnet_tpu/impl.py": """
            from mxnet_tpu.base_mod import Base

            class Impl(Base):
                def own(self):
                    return 2

                def caller(self):
                    return self.own() + self.shared() + Impl.own(self)
        """})
    caller = fn_of(gph, "mxnet_tpu/impl.py::Impl.caller")
    own = fn_of(gph, "mxnet_tpu/impl.py::Impl.own")
    shared = fn_of(gph, "mxnet_tpu/base_mod.py::Base.shared")
    assert own in caller.callees          # self-binding (and Class.method)
    assert shared in caller.callees       # base-class binding by name


def test_graph_decorated_functions_still_resolve():
    gph = make_graph({
        "mxnet_tpu/d.py": """
            import functools

            def deco(fn):
                return fn

            @deco
            def decorated(x):
                return x

            def caller(x):
                return decorated(x)
        """})
    assert fn_of(gph, "mxnet_tpu/d.py::decorated") in \
        fn_of(gph, "mxnet_tpu/d.py::caller").callees


def test_graph_recursion_terminates_and_depth_cutoff():
    # direct + mutual recursion must terminate; a chain longer than the
    # propagation bound is cut off at DEFAULT_DEPTH frames from the seed.
    # (Seeded via the graph-only `_leaf_step` name seed: the same-file
    # jit closure in `core.jit_functions` is deliberately unbounded.)
    depth = graph_mod.DEFAULT_DEPTH
    n = depth + 2
    chain = "\n".join(
        "def f%d(x):\n    return f%d(x)" % (i, i + 1) for i in range(n))
    src = """
        import jax

        def rec(x):
            return rec(x)

        def _leaf_step(x):
            return f0(x)

        %s

        def f%d(x):
            return x

        jax.jit(rec)
    """ % (chain.replace("\n", "\n        "), n)
    gph = make_graph({"mxnet_tpu/r.py": src})
    assert gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::rec").node)
    # fk sits at distance k+1 from the seed: within the bound traced,
    # beyond it cut off
    assert gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % (depth - 1)).node)
    assert not gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % depth).node)
    assert not gph.is_traced(fn_of(gph, "mxnet_tpu/r.py::f%d" % n).node)


def test_graph_traced_lattice_seeds_and_chain():
    gph = make_graph({
        "mxnet_tpu/opt.py": """
            class SGD:
                def _leaf_step(self, w, g):
                    return self._clip(w - g)

                def _clip(self, x):
                    return x
        """,
        "mxnet_tpu/plane.py": """
            import jax

            class Plane:
                def _build_step(self):
                    def step(x):
                        return helper(x)
                    return step

                def activate(self):
                    self._fn = jax.jit(self._build_step())

            def helper(x):
                return x
        """})
    clip = fn_of(gph, "mxnet_tpu/opt.py::SGD._clip")
    assert gph.is_traced(clip.node)                 # seeded at _leaf_step
    assert gph.traced_chain(clip.node) == ["SGD._leaf_step", "SGD._clip"]
    # factory-returned nested function + its callees are traced
    step = fn_of(gph, "mxnet_tpu/plane.py::Plane._build_step.step")
    helper = fn_of(gph, "mxnet_tpu/plane.py::helper")
    assert gph.is_traced(step.node) and gph.is_traced(helper.node)


def test_graph_thread_lattice_seeds():
    gph = make_graph({
        "mxnet_tpu/w.py": """
            import threading

            class Emitter(threading.Thread):
                def run(self):
                    self.emit()

                def emit(self):
                    pass

            class Server:
                def start(self):
                    self._t = threading.Thread(target=self._worker)

                def _worker(self):
                    helper()

            class Saver:
                def save(self):
                    def commit():
                        finish()
                    self._engine.push(commit)

            def helper():
                pass

            def finish():
                pass

            def main_only():
                helper()
        """})
    for q in ("Emitter.run", "Emitter.emit", "Server._worker", "helper",
              "Saver.save.commit", "finish"):
        assert gph.is_threaded(fn_of(gph, "mxnet_tpu/w.py::%s" % q).node), q
    assert not gph.is_threaded(fn_of(gph, "mxnet_tpu/w.py::main_only").node)
    assert gph.thread_entry(
        fn_of(gph, "mxnet_tpu/w.py::Server._worker").node) == "Server._worker"


# ---------------------------------------------------------------------------
# traced-host-sync
# ---------------------------------------------------------------------------

def test_traced_host_sync_two_calls_below_leaf_step():
    f = lint("""
        def _leaf_step(w, g):
            return _apply(w, g)

        def _apply(w, g):
            return _norm(w - g)

        def _norm(x):
            return x / float(x.sum())
    """, "traced-host-sync")
    assert len(f) == 1
    assert "float()" in f[0].message and "_leaf_step" in f[0].message
    assert "_norm" in f[0].message


def test_traced_host_sync_cross_file_jit_reachability():
    found = lint_sources([
        ("mxnet_tpu/helpers.py", textwrap.dedent("""
            def helper(x):
                return x.asnumpy()
        """)),
        ("mxnet_tpu/steps.py", textwrap.dedent("""
            import jax
            from mxnet_tpu.helpers import helper

            @jax.jit
            def step(x):
                return helper(x)
        """)),
    ], passes=["traced-host-sync"])
    assert len(found) == 1 and found[0].path == "mxnet_tpu/helpers.py"
    assert ".asnumpy()" in found[0].message


def test_traced_host_sync_flags_get_env_and_locks():
    f = lint("""
        def _leaf_step(w):
            knob = get_env("MXNET_X", 0, int, cache=False)
            with self._lock:
                w = w + knob
            self._mu.acquire()
            return w
    """, "traced-host-sync")
    msgs = " ".join(x.message for x in f)
    assert len(f) == 3
    assert "get_env(cache=False)" in msgs and "lock" in msgs


def test_traced_host_sync_negative_and_no_double_report():
    # not reachable from any traced seed -> clean
    assert lint("""
        def host_loop(xs):
            return xs[0].asnumpy()
    """, "traced-host-sync") == []
    # lexically inside a same-file jit closure: host-sync owns the report
    src = """
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """
    assert lint(src, "traced-host-sync") == []
    assert len(lint(src, "host-sync")) == 1


def test_traced_host_sync_scoped_to_mxnet_tpu():
    src = """
        def _leaf_step(w):
            return float(w.sum())
    """
    assert lint(src, "traced-host-sync", relpath="tools/x.py") == []
    assert len(lint(src, "traced-host-sync")) == 1


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------

def test_use_after_donate_read_after_fused_apply():
    f = lint("""
        def apply(opt, idx, grads, weights, states):
            new_w, new_s = fused_apply(opt, idx, grads, weights, states)
            return weights[0], new_w
    """, "use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message


def test_use_after_donate_rebind_and_invalidate_clear():
    assert lint("""
        def rebound(opt, idx, g, weights, states):
            weights = fused_apply(opt, idx, g, weights, states)
            return weights
    """, "use-after-donate") == []
    assert lint("""
        def disciplined(opt, idx, g, weights, states):
            new_w = fused_apply(opt, idx, g, weights, states)
            invalidate_consumed(consumed, (new_w,))
            return weights
    """, "use-after-donate") == []


def test_use_after_donate_donation_prep_window_opens_at_consumer():
    # reads between prep and the consuming jit are the sanctioned pattern
    assert lint("""
        def ok(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            new_ws, new_buckets = fn(flat_ws, buckets)
            buckets = new_buckets
            return new_ws
    """, "use-after-donate") == []
    # ...but a read AFTER the consumer is stale
    f = lint("""
        def stale(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            new_ws = fn(flat_ws, buckets)
            return flat_ws[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`flat_ws`" in f[0].message


def test_use_after_donate_local_donating_jit_and_self_attr():
    f = lint("""
        import jax

        def local_jit(pools, x):
            step = jax.jit(kernel, donate_argnums=(0,))
            out = step(pools, x)
            return pools[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`pools`" in f[0].message
    # the decode pattern: a donating jit installed in __init__, the pool
    # donated in another method, rebound from the jit's outputs -> clean
    assert lint("""
        import jax

        class Engine:
            def __init__(self):
                self._step = jax.jit(kernel, donate_argnums=(0,))

            def tick(self, x):
                out, pools = self._step(self._pools, x)
                self._pools = pools
                return out
    """, "use-after-donate") == []
    # ...without the rebind, the next read is stale
    f = lint("""
        import jax

        class Engine:
            def __init__(self):
                self._step = jax.jit(kernel, donate_argnums=(0,))

            def tick(self, x):
                out = self._step(self._pools, x)
                return self._pools
    """, "use-after-donate")
    assert len(f) == 1 and "self._pools" in f[0].message


def test_use_after_donate_fused_py_is_exempt():
    src = """
        def probe(weights):
            new = fused_apply(None, None, None, weights, None)
            return weights
    """
    assert lint(src, "use-after-donate",
                relpath="mxnet_tpu/fastpath/fused.py") == []
    assert len(lint(src, "use-after-donate")) == 1


# ---------------------------------------------------------------------------
# shared-state-race
# ---------------------------------------------------------------------------

def test_shared_state_race_unlocked_cross_thread_write():
    f = lint("""
        import threading

        class W:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._n += 1

            def snapshot(self):
                return self._n
    """, "shared-state-race")
    assert len(f) == 1
    assert "`self._n`" in f[0].message and "W.snapshot" in f[0].message


def test_shared_state_race_common_lock_is_clean():
    assert lint("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self._n += 1

            def snapshot(self):
                with self._lock:
                    return self._n
    """, "shared-state-race") == []


def test_shared_state_race_one_sided_lock_still_flagged():
    f = lint("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self._n += 1

            def snapshot(self):
                return self._n
    """, "shared-state-race")
    assert len(f) == 1


def test_shared_state_race_init_exemptions():
    # writes in __init__ are pre-start() on either side — including an
    # object CONSTRUCTED on the worker thread (publication via queue)
    assert lint("""
        import threading

        class Batch:
            def __init__(self, data):
                self.data = data

            def __str__(self):
                return str(self.data)

        class W:
            def __init__(self):
                self._t = threading.Thread(target=self._run)

            def _run(self):
                b = Batch([1])
                self._q.put(b)
    """, "shared-state-race") == []


def test_shared_state_race_worker_closure_in_init_is_thread_context():
    # a closure defined in __init__ but handed to Thread(target=...) runs
    # on the worker — its writes do NOT get the construction exemption
    f = lint("""
        import threading

        class W:
            def __init__(self):
                def worker():
                    self._state = 1
                self._t = threading.Thread(target=worker)

            def peek(self):
                return self._state
    """, "shared-state-race")
    assert len(f) == 1 and "`self._state`" in f[0].message


def test_shared_state_race_scoped_to_mxnet_tpu():
    src = """
        import threading

        class W:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._n += 1

            def peek(self):
                return self._n
    """
    assert lint(src, "shared-state-race", relpath="tools/x.py") == []
    assert len(lint(src, "shared-state-race")) == 1


def test_shared_state_race_repo_findings_are_baselined_with_justifications():
    # every baselined interprocedural finding must carry a one-line
    # justification (the acceptance contract for the whole-program gate)
    counts = load_baseline(DEFAULT_BASELINE)
    justs = core.load_justifications(DEFAULT_BASELINE)
    race_keys = [k for k in counts if "::shared-state-race::" in k]
    assert race_keys, "expected the known worker-counter findings baselined"
    for k in race_keys:
        assert justs.get(k), "baselined finding lacks a justification: %s" % k


# ---------------------------------------------------------------------------
# seeded synthetic bugs (fixture module): each pass catches exactly its bug
# ---------------------------------------------------------------------------

SEEDED = (REPO / "tests" / "fixtures" / "tpulint_seeded_bugs.py").read_text()


def _lint_seeded(rule):
    # linted under a mxnet_tpu/ pseudo-path: the passes police the
    # framework package only
    return lint_source("mxnet_tpu/_seeded_bugs.py", SEEDED, passes=[rule])


def test_seeded_bug_traced_host_sync():
    f = _lint_seeded("traced-host-sync")
    assert len(f) == 1
    assert "float()" in f[0].message and "_leaf_step" in f[0].message


def test_seeded_bug_use_after_donate():
    f = _lint_seeded("use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message


def test_seeded_bug_shared_state_race():
    f = _lint_seeded("shared-state-race")
    assert len(f) == 1 and "`self._count`" in f[0].message


def test_seeded_bugs_exactly_three_across_all_passes():
    f = lint_source("mxnet_tpu/_seeded_bugs.py", SEEDED)
    assert sorted(x.rule for x in f) == \
        ["shared-state-race", "traced-host-sync", "use-after-donate"]


# ---------------------------------------------------------------------------
# v4 concurrency passes: lock-order-cycle / blocking-under-lock /
# cv-protocol / resource-lifecycle (tools/tpulint/locks.py)
# ---------------------------------------------------------------------------

LOCK_RULES = ["blocking-under-lock", "cv-protocol", "lock-order-cycle",
              "resource-lifecycle"]
LOCK_BUGS = (REPO / "tests" / "fixtures" / "tpulint_lock_bugs.py").read_text()
LOCK_CLEAN = (REPO / "tests" / "fixtures"
              / "tpulint_lock_clean.py").read_text()


def _lint_lock_bugs(rule):
    return lint_source("mxnet_tpu/_lock_bugs.py", LOCK_BUGS, passes=[rule])


def test_lock_bug_lock_order_cycle():
    f = _lint_lock_bugs("lock-order-cycle")
    assert len(f) == 1
    assert "PoolA._lock" in f[0].message and "PoolB._lock" in f[0].message
    # both witness directions are named
    assert "PoolA.forward" in f[0].message
    assert "PoolB.backward" in f[0].message


def test_lock_bug_blocking_under_lock():
    f = _lint_lock_bugs("blocking-under-lock")
    assert len(f) == 1
    assert "fetch_host" in f[0].message and "Sampler._lock" in f[0].message


def test_lock_bug_cv_protocol():
    f = _lint_lock_bugs("cv-protocol")
    assert len(f) == 1
    assert "bare" in f[0].message and "while" in f[0].message


def test_lock_bug_resource_lifecycle():
    f = _lint_lock_bugs("resource-lifecycle")
    assert len(f) == 1
    assert "reserve" in f[0].message and "KV cache pages" in f[0].message


def test_lock_bugs_exactly_four_across_all_passes():
    # each seeded bug is caught by EXACTLY its pass — no cross-talk with
    # any other pass in the registry
    f = lint_source("mxnet_tpu/_lock_bugs.py", LOCK_BUGS)
    assert sorted(x.rule for x in f) == LOCK_RULES


def test_lock_clean_fixture_zero_findings_across_all_passes():
    # the tick-boundary swap, caller-protection, subscript-store transfer
    # and lifecycle-synchronized hand-off idioms must never be flagged —
    # by ANY pass, not just the four new ones
    f = lint_source("mxnet_tpu/_lock_clean.py", LOCK_CLEAN)
    assert f == []


def test_lock_order_one_way_hierarchy_is_clean():
    # a strict A->B ordering (the repo's engine->tenant shape) is fine;
    # only a cycle deadlocks
    src = """
        import threading

        class Outer:
            def __init__(self, inner: "Inner"):
                self._lock = threading.Lock()
                self.inner = inner

            def step(self):
                with self._lock:
                    return self.inner.poke()

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    return 1
    """
    assert lint(src, "lock-order-cycle") == []


def test_blocking_under_lock_transitive_names_witness_chain():
    src = """
        import threading

        class Holder:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    return self._drain()

            def _drain(self):
                import time
                time.sleep(0.1)
    """
    f = lint(src, "blocking-under-lock")
    assert len(f) == 1
    assert "time.sleep" in f[0].message and "_drain" in f[0].message


def test_blocking_under_lock_str_join_and_timed_get_are_clean():
    src = """
        import threading

        class Holder:
            def __init__(self, q):
                self._lock = threading.Lock()
                self._q = q

            def fmt(self, xs):
                with self._lock:
                    item = self._q.get(timeout=0.5)
                    return ", ".join(str(x) for x in xs) + str(item)
    """
    assert lint(src, "blocking-under-lock") == []


def test_blocking_under_lock_untimed_queue_get_flagged():
    src = """
        import threading

        class Holder:
            def __init__(self, q):
                self._lock = threading.Lock()
                self._q = q

            def pull(self):
                with self._lock:
                    return self._q.get()
    """
    f = lint(src, "blocking-under-lock")
    assert len(f) == 1 and "queue.get()" in f[0].message


def test_cv_protocol_untimed_wait_without_shutdown_flag():
    src = """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []

            def pull(self):
                with self._cv:
                    while not self._items:
                        self._cv.wait()
                    return self._items.pop()
    """
    f = lint(src, "cv-protocol")
    assert len(f) == 1 and "shutdown" in f[0].message


def test_cv_protocol_timed_looped_shutdown_wait_is_clean():
    src = """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()
                self._items = []
                self._closed = False

            def pull(self):
                with self._cv:
                    while not self._items and not self._closed:
                        self._cv.wait(0.5)
                    self._cv.notify_all()
    """
    assert lint(src, "cv-protocol") == []


def test_cv_protocol_notify_without_cv_lock():
    src = """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()

            def kick(self):
                self._cv.notify_all()
    """
    f = lint(src, "cv-protocol")
    assert len(f) == 1 and "notify" in f[0].message


def test_resource_lifecycle_try_finally_is_clean():
    src = """
        class C:
            def __init__(self, cache):
                self._cache = cache

            def run(self, slot, pages):
                self._cache.reserve(slot, pages)
                try:
                    return self._work(slot)
                finally:
                    self._cache.free(slot)

            def _work(self, slot):
                return slot
    """
    assert lint(src, "resource-lifecycle") == []


def test_resource_lifecycle_early_return_leak():
    src = """
        class C:
            def __init__(self, cache):
                self._cache = cache

            def run(self, slot, pages, fast):
                self._cache.reserve(slot, pages)
                if fast:
                    return None
                self._cache.free(slot)
    """
    f = lint(src, "resource-lifecycle")
    assert len(f) == 1 and "return" in f[0].message


def test_lock_rule_repo_findings_are_baselined_with_justifications():
    # same acceptance contract as shared-state-race: every baselined
    # finding from the four concurrency passes carries a justification
    counts = load_baseline(DEFAULT_BASELINE)
    justs = core.load_justifications(DEFAULT_BASELINE)
    keys = [k for k in counts
            if any("::%s::" % r in k for r in LOCK_RULES)]
    # the deliberate admission-guard hand-offs are known and must stay
    # documented
    assert any("::resource-lifecycle::" in k for k in keys), \
        "expected the admission-guard hand-off findings baselined"
    for k in keys:
        assert justs.get(k), "baselined finding lacks a justification: %s" % k


# ---------------------------------------------------------------------------
# incremental cache + --stats + runtime gates
# ---------------------------------------------------------------------------

from tools.tpulint.cache import LintCache  # noqa: E402


def test_cache_warm_hits_and_identical_findings(tmp_path):
    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    cache1 = LintCache(tmp_path / "c.json")
    cold = lint_files([a], root=tmp_path, cache=cache1)
    assert cache1.hits == 0 and cache1.misses > 0
    cache2 = LintCache(tmp_path / "c.json")
    warm = lint_files([a], root=tmp_path, cache=cache2)
    assert cache2.misses == 0 and cache2.hits > 0
    assert [str(f) for f in warm] == [str(f) for f in cold]


def test_cache_invalidated_by_edit_and_scope_change(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    b.write_text("X = 1\n")
    path = tmp_path / "c.json"
    lint_files([a, b], root=tmp_path, cache=LintCache(path))

    # editing b: a's LOCAL results stay cached, project results (keyed by
    # the scope signature) re-run for everyone
    b.write_text("X = 2\n")
    c = LintCache(path)
    stats = {}
    lint_files([a, b], root=tmp_path, cache=c, stats=stats)
    assert c.hits > 0 and c.misses > 0
    from tools.tpulint.core import all_passes
    n_project = sum(1 for p in all_passes().values() if p.project)
    # both files re-run every project pass; only b re-runs local passes
    assert c.misses >= 2 * n_project

    # unchanged again -> full hit, and no pass executed at all
    c2 = LintCache(path)
    stats2 = {}
    lint_files([a, b], root=tmp_path, cache=c2, stats=stats2)
    assert c2.misses == 0 and stats2["pass_ms"] == {}


def test_cache_findings_survive_roundtrip_suppressed(tmp_path):
    # suppressions live in the hashed content: cached results honor them
    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n"
                 "    return [x.asnumpy() for x in xs]"
                 "  # tpulint: disable=host-sync\n")
    path = tmp_path / "c.json"
    assert lint_files([a], root=tmp_path, cache=LintCache(path),
                      passes=["host-sync"]) == []
    assert lint_files([a], root=tmp_path, cache=LintCache(path),
                      passes=["host-sync"]) == []


def test_cli_stats_flag(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    rc = main([str(bad), "--stats", "--format", "json",
               "--cache", str(tmp_path / "c.json")])
    captured = capsys.readouterr()
    assert rc == 1
    # stats go to stderr so --format json keeps a parseable stdout
    json.loads(captured.out)
    assert "tpulint --stats:" in captured.err and "cache:" in captured.err \
        and "pass " in captured.err and "total:" in captured.err


def test_runtime_gate_cold_under_30s_warm_under_5s(tmp_path):
    """The tier-1 cost contract for the whole-program engine: a cold run
    over mxnet_tpu/ completes in under 30s, a warm (fully cached) run in
    under 5s."""
    import time

    cache = str(tmp_path / "gate-cache.json")
    t0 = time.monotonic()
    cold = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu",
         "--cache", cache],
        cwd=str(REPO), capture_output=True, text=True)
    cold_s = time.monotonic() - t0
    assert cold.returncode == 0, cold.stdout + cold.stderr
    assert cold_s < 30.0, "cold whole-program lint took %.1fs" % cold_s

    t0 = time.monotonic()
    warm = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", "mxnet_tpu",
         "--cache", cache],
        cwd=str(REPO), capture_output=True, text=True)
    warm_s = time.monotonic() - t0
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert warm_s < 5.0, "warm (cached) lint took %.1fs" % warm_s


def test_write_baseline_preserves_justifications(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(tmp_path / "c.json")]) == 0
    counts = load_baseline(bl)
    (key,) = counts
    core.write_baseline_counts(counts, bl, justifications={key: "because"})
    assert core.load_justifications(bl) == {key: "because"}
    # a rewrite keeps the surviving entry's justification
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(tmp_path / "c.json")]) == 0
    assert core.load_justifications(bl) == {key: "because"}


def test_lint_sources_duplicate_relpath_does_not_crash():
    # lint_sources is the documented multi-file entry point; duplicate
    # relpaths must not crash the graph build's ordering
    pairs = [("mxnet_tpu/x.py", "def f(xs):\n    return [x.item() for x in xs]\n"),
             ("mxnet_tpu/x.py", "def g():\n    return 1\n")]
    found = lint_sources(pairs, passes=["host-sync"])
    assert len(found) == 1


def test_cache_prunes_entries_for_deleted_files(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("X = 1\n")
    b.write_text("Y = 2\n")
    path = tmp_path / "c.json"
    lint_files([a, b], root=tmp_path, cache=LintCache(path))
    b.unlink()
    lint_files([a], root=tmp_path, cache=LintCache(path))
    import json as _json
    # default extra_sig "" section of the per-baseline-signature layout
    entries = _json.loads(path.read_text())["sections"][""]["files"]
    assert "a.py" in entries and "b.py" not in entries


def test_use_after_donate_intermediate_introspection_not_a_consumer():
    # len()/logging touching a prep'd name first must NOT open the
    # donation window (and must not steal the consumer's identity)
    assert lint("""
        def ok(flat_ws, buckets, fn, log):
            argnums, consumed = donation_prep(flat_ws, buckets)
            n = len(flat_ws)
            log.debug("packing %d", n)
            new_ws = fn(flat_ws, buckets)
            return new_ws
    """, "use-after-donate") == []
    # the real consumer still opens it
    f = lint("""
        def stale(flat_ws, buckets, fn):
            argnums, consumed = donation_prep(flat_ws, buckets)
            n = len(flat_ws)
            new_ws = fn(flat_ws, buckets)
            return flat_ws[0]
    """, "use-after-donate")
    assert len(f) == 1 and "`flat_ws`" in f[0].message


def test_use_after_donate_same_statement_read_after_call():
    # positional order approximates evaluation order: a read AFTER the
    # donating call in one statement is stale...
    f = lint("""
        def bad(opt, idx, g, weights, states):
            out = fused_apply(opt, idx, g, weights, states) + weights[0]
            return out
    """, "use-after-donate")
    assert len(f) == 1 and "`weights`" in f[0].message
    # ...a read BEFORE it is not
    assert lint("""
        def ok(opt, idx, g, weights, states):
            out = weights[0] + fused_apply(opt, idx, g, weights, states)
            return out
    """, "use-after-donate") == []


def test_project_scope_gives_changed_only_cross_file_context(tmp_path):
    # --changed-only semantics: report only changed files, but keep the
    # full scope as graph context so cross-file traced seeds still reach
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    helpers = pkg / "helpers.py"
    steps = pkg / "steps.py"
    helpers.write_text("def helper(x):\n    return x.asnumpy()\n")
    steps.write_text("import jax\n"
                     "from mxnet_tpu.helpers import helper\n\n"
                     "@jax.jit\n"
                     "def step(x):\n"
                     "    return helper(x)\n")
    # changed file alone: no seed visible, false clean
    alone = lint_files([helpers], root=tmp_path,
                       passes=["traced-host-sync"])
    assert alone == []
    # with the unchanged file as graph context: the hazard is visible,
    # and findings still come only from the changed file
    ctxd = lint_files([helpers], root=tmp_path, passes=["traced-host-sync"],
                      project_scope=[helpers, steps])
    assert len(ctxd) == 1 and ctxd[0].path == "mxnet_tpu/helpers.py"


def test_cli_stats_emitted_with_write_baseline(tmp_path, capsys):
    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    assert main([str(bad), "--write-baseline", "--stats",
                 "--baseline", str(tmp_path / "bl.json"),
                 "--cache", str(tmp_path / "c.json")]) == 0
    assert "tpulint --stats:" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# v3: abstract shape/sharding interpreter (tools/tpulint/shapes.py) and the
# recompile-risk / pallas-kernel-check / sharding-flow passes
# ---------------------------------------------------------------------------

from tools.tpulint import shapes  # noqa: E402
from tools.tpulint.shapes import Dim, derived, join_dims  # noqa: E402


def test_dim_lattice_joins():
    c8, c16 = Dim.const(8), Dim.const(16)
    knob = Dim.knob("MXNET_DECODE_SLOTS")
    top = Dim.top("len() of host data")
    unk = Dim.unknown()
    # unknown is the join identity (ignorance is not evidence)
    assert join_dims(unk, c8).kind == "const"
    assert join_dims(c8, unk).value == 8
    # equal consts stay const; distinct sizes join to a bounded set
    assert join_dims(c8, Dim.const(8)).value == 8
    assert join_dims(c8, c16).kind == "bounded"
    assert join_dims(c8, knob).kind == "bounded"
    # top absorbs everything and keeps its origin for the message
    assert join_dims(top, c8).kind == "top"
    assert join_dims(knob, top).origin == "len() of host data"
    # derived arithmetic: top taints, unknown stays unknown
    assert derived(c8, top).kind == "top"
    assert derived(c8, unk).kind == "unknown"
    assert derived(c8, knob).kind == "knob"


def test_recompile_risk_loop_accumulator_into_jit():
    found = lint("""
        import jax
        import numpy as np

        def _impl(x):
            return x * 2

        _STEP = jax.jit(_impl)

        def collate(batches):
            rows = []
            for b in batches:
                rows.append(np.asarray(b))
            return _STEP(np.stack(rows))
    """, "recompile-risk")
    assert len(found) == 1
    assert "⊤" in found[0].message and "_STEP" in found[0].message


def test_recompile_risk_len_of_host_data_into_jit():
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def handle(prompt):
            arr = np.zeros((3, len(prompt)), np.int32)
            return step(arr)
    """, "recompile-risk")
    assert len(found) == 1 and "len()" in found[0].message


def test_recompile_risk_interprocedural_top_flow():
    # the ⊤ array is built in one function, dispatched in another: only
    # the interprocedural parameter summary can see it
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def inner(arr):
            return step(arr)

        def outer(data):
            return inner(np.zeros((len(data), 4)))
    """, "recompile-risk")
    assert len(found) == 1 and "step" in found[0].message


def test_recompile_risk_jit_attr_and_wrapper_dispatch():
    # the decode idiom: jit installed as an instance attribute in
    # __init__, dispatched through telemetry.jit_call on a retry closure
    found = lint("""
        import jax
        import numpy as np

        class Engine:
            def __init__(self, fn):
                self._step = jax.jit(fn)

            def tick(self, host_rows):
                from . import telemetry
                x = np.zeros((len(host_rows),))

                def attempt():
                    return telemetry.jit_call("site", self._step, x)
                return attempt()
    """, "recompile-risk")
    assert len(found) == 1 and "self._step" in found[0].message


def test_recompile_risk_bucket_ladder_and_knob_clean():
    # the sanctioned shapes: select_bucket rungs and get_env knobs are
    # bounded — one compile per rung / per process, warmup covers them
    found = lint("""
        import jax
        import numpy as np
        from .base import get_env
        from .serving.buckets import select_bucket

        @jax.jit
        def step(x):
            return x + 1

        def prefill(prompt, ladder):
            rung = select_bucket(len(prompt), ladder)
            return step(np.zeros((3, rung), np.int32))

        def tick():
            s = get_env("MXNET_DECODE_SLOTS", 8, int, cache=False)
            return step(np.zeros((5, s), np.int32))
    """, "recompile-risk")
    assert found == []


def test_recompile_risk_speculative_widened_step_clean():
    # the ISSUE-20 widened decode tick: the packed operand is
    # (5, slots * (spec_k + 1)) where BOTH factors are get_env knobs.
    # The shape interpreter must resolve the arithmetic over two knob
    # lattice values to `knob` (bounded: one compile per process), not
    # widen to ⊤ and flag the jitted step as a recompile hazard.
    found = lint("""
        import jax
        import numpy as np
        from .base import get_env

        @jax.jit
        def step(x):
            return x + 1

        def spec_tick():
            s = get_env("MXNET_DECODE_SLOTS", 8, int, cache=False)
            k = get_env("MXNET_DECODE_SPEC_K", 0, int, cache=False)
            return step(np.zeros((5, s * (k + 1)), np.int32))
    """, "recompile-risk")
    assert found == []


def test_recompile_risk_warmup_rung_loop_clean():
    # one compile per rung of a knob-parsed ladder is the warmup
    # CONTRACT, not a hazard — bounded by construction
    found = lint("""
        import jax
        import numpy as np
        from .base import get_env

        @jax.jit
        def step(x):
            return x + 1

        def warmup():
            raw = get_env("MXNET_DECODE_PREFILL_BUCKETS", "16,64", str,
                          cache=False)
            ladder = [int(t) for t in str(raw).split(",") if t.strip()]
            for rung in ladder:
                step(np.zeros((3, rung), np.int32))
    """, "recompile-risk")
    assert found == []


def test_recompile_risk_unknown_never_reported():
    # a jit over shapes the interpreter cannot derive must stay silent:
    # the pass reports positively-derived ⊤ only
    found = lint("""
        import jax

        @jax.jit
        def step(x):
            return x + 1

        def run(batch):
            return step(batch)
    """, "recompile-risk")
    assert found == []


def test_recompile_risk_scoped_to_mxnet_tpu():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(data):
            return step(np.zeros((len(data),)))
    """
    assert lint(src, "recompile-risk", relpath="tools/helper.py") == []
    assert len(lint(src, "recompile-risk")) == 1


def test_pallas_check_off_tile_block_and_sublane():
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((5, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((20, 128), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 2
    assert "last dim 100" in msgs and "second-to-last dim 5" in msgs


def test_pallas_check_module_const_folding():
    # LANES/_SUBLANES-style module constants fold into the block check
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        LANES = 128
        HALF = LANES // 2

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((8, HALF), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, LANES), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1 and "last dim 64" in found[0].message


def test_pallas_check_grid_index_map_arity():
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
                out_shape=jax.ShapeDtypeStruct((32, 256), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1 and "arity mismatch" in found[0].message


def test_pallas_check_scalar_prefetch_arity():
    # PrefetchScalarGridSpec appends N scalar refs to every index_map:
    # a lambda that ignores them is an on-device TypeError
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def run(x, tbl, kern):
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
                out_specs=pl.BlockSpec((8, 128),
                                       lambda i, j, t: (i, j)),
            )
            return pl.pallas_call(
                kern,
                grid_spec=grid_spec,
                out_shape=jax.ShapeDtypeStruct((32, 256), jnp.float32),
            )(tbl, x)
    """, "pallas-kernel-check")
    assert len(found) == 1
    assert "scalar-prefetch" in found[0].message \
        and "takes 2 argument(s)" in found[0].message


def test_pallas_check_vmem_budget():
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((1024, 2048), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((4096, 2048), jnp.float32),
                scratch_shapes=[pltpu.VMEM((1024, 2048), jnp.float32)],
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1
    assert "VMEM" in found[0].message and "16 MB" in found[0].message


def test_pallas_check_clean_kernel_negative():
    # tile-aligned blocks, consistent arity, modest VMEM: silent —
    # including symbolic dims the const folder cannot (and must not)
    # guess at
    found = lint("""
        import functools
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        LANES = 128

        def flash(q, k, v, kern, bq, bk, d, n_q, n_kv, b, h, sp):
            return pl.pallas_call(
                kern,
                grid=(b * h, n_q, n_kv),
                in_specs=[
                    pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
                    pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
                ],
                out_specs=pl.BlockSpec((1, bq, d),
                                       lambda bh, qi, ki: (bh, qi, 0)),
                out_shape=jax.ShapeDtypeStruct((8, 128, 128), jnp.float32),
                scratch_shapes=[pltpu.VMEM((8, LANES), jnp.float32)],
            )(q, k, v)
    """, "pallas-kernel-check")
    assert found == []


def test_pallas_check_scoped_to_mxnet_tpu():
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
            )(x)
    """
    assert lint(src, "pallas-kernel-check", relpath="example/k.py") == []
    assert len(lint(src, "pallas-kernel-check")) == 1


def test_sharding_flow_undefined_axis():
    found = lint("""
        import numpy as np
        import jax
        from jax import lax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def shard(devs, x):
            mesh = Mesh(np.asarray(devs), ("dp",))
            y = jax.device_put(x, NamedSharding(mesh, P("tp")))
            return lax.psum(y, "model")
    """, "sharding-flow")
    assert len(found) == 2
    msgs = " | ".join(f.message for f in found)
    assert "'tp'" in msgs and "'model'" in msgs


def test_sharding_flow_cross_file_axis_definition():
    # "dp" is defined by a Mesh in another file of the same lint scope:
    # the whole-program axis set must see it
    meshes = """
        import numpy as np
        from jax.sharding import Mesh

        def device_mesh(devs, axis_names=("dp",)):
            return Mesh(np.asarray(devs), tuple(axis_names))
    """
    user = """
        from jax.sharding import NamedSharding, PartitionSpec as P

        def spec(mesh):
            return NamedSharding(mesh, P("dp"))
    """
    found = core.lint_sources(
        [("mxnet_tpu/parallel2.py", textwrap.dedent(meshes)),
         ("mxnet_tpu/user2.py", textwrap.dedent(user))],
        passes=["sharding-flow"])
    assert found == []
    # without the defining file the same use IS a finding
    alone = core.lint_sources([("mxnet_tpu/user2.py", textwrap.dedent(user))],
                              passes=["sharding-flow"])
    assert len(alone) == 1 and "'dp'" in alone[0].message


def test_sharding_flow_bare_p_requires_partitionspec_import():
    # a helper that HAPPENS to be called P must not alias into the check
    found = lint("""
        def P(name):
            return name

        def run():
            return P("whatever")
    """, "sharding-flow")
    assert found == []


def test_sharding_flow_donated_layout_mismatch():
    found = lint("""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            return jax.jit(fn,
                           in_shardings=(P("dp"), P()),
                           out_shardings=(P(), P()),
                           donate_argnums=(0,))
    """, "sharding-flow")
    assert len(found) == 1 and "silent copy" in found[0].message


def test_sharding_flow_donation_clean_cases():
    # matching layouts, and the common out_shardings-only state threading
    found = lint("""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            a = jax.jit(fn,
                        in_shardings=(P("dp"), P()),
                        out_shardings=(P("dp"), P()),
                        donate_argnums=(0,))
            b = jax.jit(fn, out_shardings=(P(), P()),
                        donate_argnums=(0, 1))
            return a, b
    """, "sharding-flow")
    assert found == []


def test_sharding_flow_scoped_to_mxnet_tpu():
    src = """
        from jax.sharding import NamedSharding, PartitionSpec as P

        def spec(mesh):
            return NamedSharding(mesh, P("nowhere"))
    """
    assert lint(src, "sharding-flow", relpath="tools/helper.py") == []
    assert len(lint(src, "sharding-flow")) == 1


# -- seeded shape bugs (fixture): each new pass catches exactly its bug -----

SHAPE_SEEDED = (REPO / "tests" / "fixtures"
                / "tpulint_shape_bugs.py").read_text()
SHAPE_CLEAN = (REPO / "tests" / "fixtures"
               / "tpulint_shape_clean.py").read_text()


def _lint_shape_fixture(src, rule=None):
    return lint_source("mxnet_tpu/_shape_fixture.py", src,
                       passes=[rule] if rule else None)


def test_shape_seeded_bug_recompile_risk():
    f = _lint_shape_fixture(SHAPE_SEEDED, "recompile-risk")
    assert len(f) == 1
    assert "_STEP" in f[0].message and "⊤" in f[0].message


def test_shape_seeded_bug_pallas_kernel_check():
    f = _lint_shape_fixture(SHAPE_SEEDED, "pallas-kernel-check")
    assert len(f) == 1 and "last dim 100" in f[0].message


def test_shape_seeded_bug_sharding_flow():
    f = _lint_shape_fixture(SHAPE_SEEDED, "sharding-flow")
    assert len(f) == 1 and "'tp'" in f[0].message


def test_shape_seeded_bugs_exactly_three_across_all_passes():
    f = _lint_shape_fixture(SHAPE_SEEDED)
    assert sorted(x.rule for x in f) == \
        ["pallas-kernel-check", "recompile-risk", "sharding-flow"]


def test_shape_clean_fixture_zero_findings_all_passes():
    """The false-positive suite: the sanctioned bucket-ladder, warmup,
    knob-shape, scalar-prefetch-pallas and defined-axis idioms produce
    ZERO findings — across the three new passes AND every other pass."""
    assert _lint_shape_fixture(SHAPE_CLEAN) == []


def test_recompile_risk_zero_findings_on_real_serving_plane():
    """Acceptance: the REAL decode engine (bucket ladders, warmed step,
    knob-sized slots) is clean by construction under the abstract
    interpreter — the PR-3 runtime recompile gauge's zero is now a
    statically proven property."""
    serving = [REPO / "mxnet_tpu" / "serving" / p
               for p in ("decode.py", "engine.py", "buckets.py",
                         "batcher.py", "kvcache.py")]
    found = lint_files(serving, passes=["recompile-risk"])
    assert found == [], "\n".join(map(str, found))


# -- cache invalidation on baseline edit (the PR-12 regression) --------------

def test_cache_invalidated_by_baseline_edit(tmp_path, capsys):
    """Editing the baseline must invalidate cached pass results: a warm
    run after dropping a baseline entry re-RUNS the passes and
    re-reports from fresh findings. (Reported findings were already
    correct — cached results are stored pre-baseline — but cache entries
    could outlive the baseline they were computed under; keying the
    cache by baseline content makes the invariant hold at the cache
    layer, and keeps any future baseline-consulting pass correct by
    construction.)"""
    from tools.tpulint.cache import LintCache, baseline_sig

    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    cache = tmp_path / "c.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(cache)]) == 0
    # warm + baselined: clean
    assert main([str(bad), "--baseline", str(bl), "--cache",
                 str(cache)]) == 0
    capsys.readouterr()
    # drop the baseline entry: the SAME warm cache must re-report
    bl.write_text('{"version": 1, "counts": {}}\n')
    assert main([str(bad), "--baseline", str(bl), "--cache",
                 str(cache)]) == 1
    assert "host-sync" in capsys.readouterr().out
    # and the invalidation is at the CACHE layer, not a lucky re-report:
    # a cache opened under the new baseline signature starts cold
    stale = LintCache(cache, extra_sig="different-baseline")
    assert stale.get_local("v.py", "whatever", "host-sync") is None
    assert baseline_sig(bl) != "" and baseline_sig(None) == ""
    assert baseline_sig(tmp_path / "missing.json") == ""


def test_lint_gate_script_syntax_and_exec_bit():
    gate = REPO / "tools" / "lint_gate.sh"
    assert gate.exists()
    import os
    assert os.access(str(gate), os.X_OK), "tools/lint_gate.sh must be +x"
    check = subprocess.run(["bash", "-n", str(gate)], capture_output=True,
                           text=True)
    assert check.returncode == 0, check.stderr


def test_bench_lint_stamp_fields():
    """bench.py stamps lint_clean/lint_findings on every JSON line."""
    import importlib
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_for_lint",
                                                  str(REPO / "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    stamp = bench._lint_stamp()
    assert stamp.get("lint_clean") is True, stamp
    assert stamp.get("lint_findings") == 0, stamp
    # memoized: the second call must not re-run the linter
    assert bench._lint_stamp() is stamp


# -- review hardening: pinned fixes -----------------------------------------

def test_sharding_flow_axis_name_kwarg_does_not_self_define():
    # an `axis_name=` kwarg on a COLLECTIVE is a use, not a definition —
    # it must not legitimize its own typo'd axis
    found = lint("""
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        def collect(devs, x):
            mesh = Mesh(np.asarray(devs), ("dp",))
            return lax.psum(x, axis_name="bogus")
    """, "sharding-flow")
    assert len(found) == 1 and "'bogus'" in found[0].message
    # ...while the same kwarg on a mesh CONSTRUCTOR does define the axis
    clean = lint("""
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        def sequence_mesh(devices, axis_name="sp"):
            return Mesh(np.asarray(devices), (axis_name,))

        def run(devs, x):
            mesh = sequence_mesh(devs, axis_name="sp")
            return lax.psum(x, axis_name="sp")
    """, "sharding-flow")
    assert clean == []


def test_pallas_check_smem_scratch_exempt():
    # SMEM is scalar memory: no (sublane, lane) tiling, not in the VMEM
    # pool — the standard (1, 1) scalar scratch must not be flagged or
    # counted into the budget
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
                scratch_shapes=[pltpu.SMEM((1, 1), jnp.int32)],
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_write_baseline_rekeys_cache_to_new_baseline(tmp_path):
    # --write-baseline changes the baseline content: the cache must be
    # re-keyed to the NEW baseline so the next run starts warm (not a
    # silently cold "warm" lap that trips the lint_gate time gate)
    from tools.tpulint.cache import LintCache, baseline_sig

    bad = tmp_path / "v.py"
    bad.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    bl = tmp_path / "bl.json"
    cache = tmp_path / "c.json"
    assert main([str(bad), "--baseline", str(bl), "--write-baseline",
                 "--cache", str(cache)]) == 0
    warm = LintCache(cache, extra_sig=baseline_sig(bl))
    # entries survived the re-key: a hit under the NEW baseline signature
    (rel,) = [k for k in warm._entries if k.endswith("v.py")]
    assert warm.get_local(rel, warm._entries[rel]["sha"],
                          "host-sync") is not None


def test_lint_gate_broken_environment_exits_2(tmp_path):
    # a crashing linter (rc >= 2) must exit the GATE with 2 — not be
    # misread as "new findings" via an empty JSON file
    fake = tmp_path / "fakepy"
    fake.write_text("#!/bin/sh\nexit 3\n")
    fake.chmod(0o755)
    proc = subprocess.run([str(REPO / "tools" / "lint_gate.sh")],
                          env={"PATH": "/usr/bin:/bin",
                               "PYTHON": str(fake)},
                          capture_output=True, text=True)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "failed (rc=3)" in proc.stderr


def test_recompile_risk_loop_counter_widens_to_top():
    # a loop-carried scalar counter over unbounded data is a ⊤ dim —
    # folding it once would claim a positively-WRONG constant shape
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(batches):
            n = 0
            for b in batches:
                n += 1
            return step(np.zeros((n,)))
    """, "recompile-risk")
    assert len(found) == 1 and "python-loop counter" in found[0].message
    # ...but a counter over a BOUNDED iterable inherits the bound
    clean = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run():
            n = 0
            for b in (16, 64, 256):
                n += 1
            return step(np.zeros((n,)))
    """, "recompile-risk")
    assert clean == []


def test_pallas_check_vmem_budget_uses_kernel_dtype():
    # a bf16 kernel's blocks are bf16: ~8 MB true footprint must NOT be
    # counted at f32 width into a fake over-ceiling finding
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_recompile_risk_bounded_loop_append_is_clean():
    # fixed-shape accumulate over a literal tuple: the accumulator's
    # length is the (bounded) trip count, not ⊤
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run():
            rows = []
            for r in (16, 64):
                rows.append(np.zeros((8, 128)))
            return step(np.stack(rows))
    """, "recompile-risk")
    assert found == []


def test_recompile_risk_keyword_operand_flagged():
    # a ⊤-shaped operand passed BY KEYWORD traces exactly like a
    # positional one
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x=None):
            return x + 1

        def run(data):
            return step(x=np.zeros((len(data),)))
    """, "recompile-risk")
    assert len(found) == 1 and "`x`" in found[0].message


def test_pallas_check_defaulted_index_map_params_ok():
    # lambda i, j=0: legally callable with 1 arg — not an arity mismatch
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i, j=0: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_sharding_flow_posonly_defaults_alignment():
    # positional-only params with defaults must not shift the
    # axis_names default out of (or a non-axis string into) the
    # definition set
    found = lint("""
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        def make(devices="cpu", /, axis_names=("dp",)):
            return Mesh(np.asarray(devices), tuple(axis_names))

        def run(x):
            return lax.psum(x, "dp")
    """, "sharding-flow")
    assert found == []
    bogus = lint("""
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        def make(devices="cpu", /, axis_names=("dp",)):
            return Mesh(np.asarray(devices), tuple(axis_names))

        def run(x):
            return lax.psum(x, "cpu")
    """, "sharding-flow")
    assert len(bogus) == 1 and "'cpu'" in bogus[0].message


def test_recompile_risk_min_clamp_is_bounded():
    # min(len(data), CAP) takes finitely many values: the cap idiom is
    # warmup-precompilable, not a storm
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(data):
            n = min(len(data), 128)
            return step(np.zeros((n,)))
    """, "recompile-risk")
    assert found == []
    # ...but max() over ⊤ is genuinely unbounded
    storm = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(data):
            n = max(len(data), 128)
            return step(np.zeros((n,)))
    """, "recompile-risk")
    assert len(storm) == 1


def test_pallas_check_vmem_budget_multi_output_dtype():
    # out_shape as a LIST of ShapeDtypeStructs (multi-output kernel)
    # must still feed the bf16 element size into the budget
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],
                out_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0)),
                           pl.BlockSpec((16, 128), lambda i: (i, 0))],
                out_shape=[jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),
                           jax.ShapeDtypeStruct((64, 128), jnp.bfloat16)],
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_pallas_check_bf16_sublane_applies_to_in_specs():
    # the kernel dtype (from out_shape) governs EVERY block: an (8, 128)
    # input block in a bf16 kernel is off the (16, 128) min tile
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1
    assert "second-to-last dim 8" in found[0].message \
        and "bfloat16" in found[0].message


def test_pallas_check_reassigned_local_not_folded():
    # a name assigned twice has no trustworthy value: the (8, 128)
    # runtime block must not be flagged with the STALE first value
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            bs = 100
            bs = 128
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, bs), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_recompile_risk_nested_comprehension_binds_own_iter():
    # the inner generator's target binds from ITS iterator: y is a
    # bounded ladder rung, not the outer ⊤ loop index
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(data, ladder=(16, 64)):
            return [step(np.zeros((y, 4)))
                    for x in range(len(data)) for y in ladder]
    """, "recompile-risk")
    assert found == []
    # inverse: a ⊤ INNER iterator behind a bounded first generator
    storm = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def run(data, ladder=(16, 64)):
            return [step(np.zeros((n, 4)))
                    for b in ladder for n in range(len(data))]
    """, "recompile-risk")
    assert len(storm) == 1


def test_lint_gate_unparseable_output_exits_2(tmp_path):
    # a linter that exits 0 but emits garbage stdout is a broken tool
    # (rc 2), not "new findings" (rc 1)
    fake = tmp_path / "fakepy"
    fake.write_text("#!/bin/sh\n"
                    "case \"$1\" in\n"
                    "  -m) echo 'not json'; exit 0 ;;\n"
                    # the heredoc check runs under the same $PY: delegate
                    # to the real python so json parsing actually runs
                    "  *) exec python3 \"$@\" ;;\n"
                    "esac\n")
    fake.chmod(0o755)
    proc = subprocess.run([str(REPO / "tools" / "lint_gate.sh")],
                          env={"PATH": "/usr/bin:/bin",
                               "PYTHON": str(fake)},
                          capture_output=True, text=True)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "unparseable" in proc.stderr


def test_pallas_check_dtype_keyword_argument():
    # ShapeDtypeStruct((...), dtype=jnp.bfloat16): the keyword spelling
    # must feed the tile tables exactly like the positional one
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128),
                                               dtype=jnp.bfloat16),
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1 and "bfloat16" in found[0].message


def test_sharding_flow_donation_resolves_named_specs():
    # an out_shardings referenced through a variable must compare equal
    # to the literal it was assigned from — no manufactured mismatch
    found = lint("""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            out_spec = P("dp")
            return jax.jit(fn,
                           in_shardings=(P("dp"),),
                           out_shardings=(out_spec,),
                           donate_argnums=(0,))
    """, "sharding-flow")
    assert found == []


def test_recompile_risk_posonly_nested_param_shadows_closure():
    # a positional-only param of a nested def shadows the ⊤ closure
    # variable: callers decide its shape, the closure value is stale
    found = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x + 1

        def outer(items):
            acc = []
            for i in items:
                acc.append(np.asarray(i))
            batch = np.stack(acc)

            def attempt(batch, /):
                return step(batch)
            return attempt
    """, "recompile-risk")
    assert found == []


def test_join_values_elts_monotone_across_call_sites():
    # two sites passing the same literal shape keep the tuple; a ⊤
    # element survives a join against a const one (summary can't mask a
    # storm-passing site)
    from tools.tpulint.shapes import AbsValue, Dim, join_values

    t1 = AbsValue(elts=(AbsValue(dim=Dim.const(8)),
                        AbsValue(dim=Dim.const(16))))
    t2 = AbsValue(elts=(AbsValue(dim=Dim.const(8)),
                        AbsValue(dim=Dim.const(16))))
    same = join_values(t1, t2)
    assert same.elts is not None and same.elts[1].dim.value == 16
    t3 = AbsValue(elts=(AbsValue(dim=Dim.const(8)),
                        AbsValue(dim=Dim.top("len() of host data"))))
    mixed = join_values(join_values(t1, t3), t2)
    assert mixed.elts is not None and mixed.elts[1].dim.kind == "top"


def test_sharding_flow_donation_name_bound_tuple():
    # out_shardings referenced as a Name-bound TUPLE must expand to its
    # elements, not compare as one opaque spec
    found = lint("""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            specs = (P("dp"),)
            return jax.jit(fn,
                           in_shardings=(P("dp"),),
                           out_shardings=specs,
                           donate_argnums=(0,))
    """, "sharding-flow")
    assert found == []


def test_pallas_check_positional_out_shape_dtype():
    # out_shape passed POSITIONALLY (pallas_call's 2nd parameter) must
    # feed the dtype tables like the keyword spelling
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern,
                jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),
            )(x)
    """, "pallas-kernel-check")
    assert len(found) == 1 and "bfloat16" in found[0].message


def test_cache_sections_alternating_modes_both_warm(tmp_path):
    # a --no-baseline run between gate runs must not evict the default
    # section: each baseline signature owns its own entries
    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    path = tmp_path / "c.json"
    lint_files([a], root=tmp_path, cache=LintCache(path, extra_sig="bl1"))
    lint_files([a], root=tmp_path, cache=LintCache(path, extra_sig=""))
    warm1 = LintCache(path, extra_sig="bl1")
    lint_files([a], root=tmp_path, cache=warm1)
    assert warm1.misses == 0 and warm1.hits > 0
    warm2 = LintCache(path, extra_sig="")
    lint_files([a], root=tmp_path, cache=warm2)
    assert warm2.misses == 0 and warm2.hits > 0


def test_lint_gate_works_through_symlink(tmp_path):
    # the documented pre-commit wiring is a SYMLINK into .git/hooks —
    # the gate must resolve it before deriving the repo root
    link = tmp_path / "pre-commit"
    link.symlink_to(REPO / "tools" / "lint_gate.sh")
    proc = subprocess.run([str(link)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "lint_gate: clean" in proc.stdout


def test_sharding_flow_donation_conditional_reassignment_bails():
    # a spec reassigned across branches has no single provable value:
    # picking either branch would report a mismatch no execution path
    # contains — the check must bail
    found = lint("""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        def build(mesh, fn, devs, compat):
            m = Mesh(devs, ("dp", "mp"))
            in_spec = P("mp")
            out_spec = P("dp")
            if compat:
                in_spec = P("dp")
            else:
                out_spec = P("mp")
            return jax.jit(fn,
                           in_shardings=(in_spec,),
                           out_shardings=(out_spec,),
                           donate_argnums=(0,))
    """, "sharding-flow")
    assert found == []


def test_sharding_flow_donation_spelling_variants_compare_equal():
    # P("dp") vs PartitionSpec("dp") vs NamedSharding(mesh, P("dp")) are
    # the SAME layout — spelling must not manufacture a mismatch
    found = lint("""
        import jax
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec,
                                  PartitionSpec as P)

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            return jax.jit(fn,
                           in_shardings=(P("dp"), NamedSharding(m, P())),
                           out_shardings=(PartitionSpec("dp"), P()),
                           donate_argnums=(0, 1))
    """, "sharding-flow")
    assert found == []


def test_cache_sections_capped_lru(tmp_path):
    # superseded baseline signatures are pruned LRU on save — the file
    # cannot grow one orphaned full-scope section per baseline edit
    from tools.tpulint.cache import MAX_SECTIONS

    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    path = tmp_path / "c.json"
    for i in range(MAX_SECTIONS + 3):
        lint_files([a], root=tmp_path,
                   cache=LintCache(path, extra_sig="bl%d" % i))
    data = json.loads(path.read_text())
    assert len(data["sections"]) <= MAX_SECTIONS
    assert "bl%d" % (MAX_SECTIONS + 2) in data["sections"]  # newest kept


def test_recompile_risk_chained_knob_parse_clean():
    # the chained spelling `get_env(..., typ=str).split(",")` carries
    # the same knob-str provenance as the assigned-name spelling
    found = lint("""
        import jax
        import numpy as np
        from .base import get_env

        @jax.jit
        def step(x):
            return x + 1

        def warmup():
            rungs = [int(s) for s in
                     get_env("MXNET_BUCKETS", "1,4", typ=str).split(",")]
            for r in rungs:
                out = []
                for _ in range(4):
                    out.append(np.zeros((r, 8)))
                step(np.stack(out))
    """, "recompile-risk")
    assert found == []


def test_cache_warm_runs_persist_lru_stamp(tmp_path):
    # fully-warm laps must persist their recency, or eviction retires
    # the most-actively-used section while keeping dead ones
    from tools.tpulint.cache import MAX_SECTIONS

    a = tmp_path / "a.py"
    a.write_text("def f(xs):\n    return [x.asnumpy() for x in xs]\n")
    path = tmp_path / "c.json"
    lint_files([a], root=tmp_path, cache=LintCache(path, extra_sig="hot"))
    for sig in ("cold1", "cold2"):
        lint_files([a], root=tmp_path, cache=LintCache(path, extra_sig=sig))
    # warm re-use of "hot" (no pass runs) must still refresh its stamp
    warm = LintCache(path, extra_sig="hot")
    lint_files([a], root=tmp_path, cache=warm)
    assert warm.misses == 0
    # push past the cap with fresh signatures: "hot" survives, the
    # stalest cold section is evicted
    for i in range(MAX_SECTIONS - 1):
        lint_files([a], root=tmp_path,
                   cache=LintCache(path, extra_sig="new%d" % i))
    data = json.loads(path.read_text())
    assert "hot" in data["sections"]
    assert "cold1" not in data["sections"]


def test_sharding_flow_donation_trailing_none_padding():
    # P("dp") == P("dp", None): PartitionSpec pads trailing dims
    found = lint("""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            return jax.jit(fn,
                           in_shardings=(P("dp", None),),
                           out_shardings=(P("dp"),),
                           donate_argnums=(0,))
    """, "sharding-flow")
    assert found == []


def test_sharding_flow_donation_bails_on_static_argnums():
    # static args shift donate_argnums vs in_shardings: unprovable
    found = lint("""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        def build(mesh, fn, devs):
            m = Mesh(devs, ("dp",))
            return jax.jit(fn, static_argnums=(0,),
                           in_shardings=(P("dp"), P(None)),
                           out_shardings=(P("dp"),),
                           donate_argnums=(1,))
    """, "sharding-flow")
    assert found == []


def test_pallas_check_unfoldable_local_shadows_module_const():
    # a runtime-chosen local TILE shadows the module-level TILE = 100:
    # the stale module value must not manufacture a tile finding
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        TILE = 100

        def run(x, kern, pick_tile):
            TILE = pick_tile(x)
            return pl.pallas_call(
                kern, grid=(4,),
                in_specs=[pl.BlockSpec((8, TILE), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert found == []


def test_pallas_check_positional_prefetch_grid_spec():
    # PrefetchScalarGridSpec(3, grid=(4, 2), ...) — positional
    # num_scalar_prefetch must feed the arity check
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def run(x, tbl, kern):
            grid_spec = pltpu.PrefetchScalarGridSpec(
                1,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128),
                                       lambda i, j, t: (t[i], j))],
                out_specs=pl.BlockSpec((8, 128),
                                       lambda i, j, t: (i, j)),
            )
            return pl.pallas_call(
                kern, grid_spec=grid_spec,
                out_shape=jax.ShapeDtypeStruct((32, 256), jnp.float32),
            )(tbl, x)
    """, "pallas-kernel-check")
    assert found == []


def test_pallas_check_loop_target_shadows_module_const():
    # a for-loop target shadowing a module const must drop the name
    # from the folder — no finding about a value no path holds
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        W = 100

        def run(x, kern):
            outs = []
            for W in (128, 256):
                outs.append(pl.pallas_call(
                    kern, grid=(4,),
                    in_specs=[pl.BlockSpec((8, W), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct((32, 128),
                                                   jnp.float32),
                )(x))
            return outs
    """, "pallas-kernel-check")
    assert found == []


def test_pallas_check_posonly_lambda_params_counted():
    # lambda i, /, j: two positional params — not an arity mismatch
    # against a 2-dim grid
    found = lint("""
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def run(x, kern):
            return pl.pallas_call(
                kern, grid=(4, 4),
                in_specs=[pl.BlockSpec((8, 128),
                                       index_map=lambda i, /, j: (i, j))],
                out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
                out_shape=jax.ShapeDtypeStruct((32, 512), jnp.float32),
            )(x)
    """, "pallas-kernel-check")
    assert found == []


# ---------------------------------------------------------------------------
# unattributed-dispatch (the ISSUE-18 perf-attribution gate)
# ---------------------------------------------------------------------------

def test_unattributed_dispatch_pass_registered():
    assert "unattributed-dispatch" in core.all_passes()


def test_unattributed_dispatch_flags_direct_and_resilience_not_wrapped():
    src = """
        import jax
        from mxnet_tpu import resilience, telemetry

        _STEP = jax.jit(lambda x: x * 2)

        def attributed(x):
            return telemetry.jit_call("plane.step", _STEP, x)

        def bare(x):
            return _STEP(x)

        def retried(x):
            # retries the dispatch but attributes nothing
            return resilience.call("plane.step", _STEP, x)
    """
    found = lint(src, "unattributed-dispatch")
    assert len(found) == 2
    msgs = "\n".join(f.message for f in found)
    assert "telemetry.jit_call" in msgs  # the fix is named in the message
    assert "resilience.call" in msgs
    # outside mxnet_tpu/ the pass does not apply
    assert lint(src, "unattributed-dispatch", relpath="tools/x.py") == []


def test_unattributed_dispatch_decorated_call_by_name():
    found = lint("""
        import jax

        @jax.jit
        def _kernel(x):
            return x + 1

        def run(x):
            return _kernel(x)
    """, "unattributed-dispatch")
    assert len(found) == 1
    assert "@jit-decorated" in found[0].message


def test_unattributed_dispatch_wrapped_sites_are_clean():
    assert lint("""
        import jax
        from mxnet_tpu import telemetry

        _STEP = jax.jit(lambda x: x * 2)

        def a(x):
            return telemetry.jit_call("plane.a", _STEP, x)

        def b(x):
            return telemetry.jit_call("plane.b", _STEP, x, donate=True)
    """, "unattributed-dispatch") == []


def test_unattributed_dispatch_repo_gate_clean_and_justified():
    # the serving/train planes dispatch ONLY through telemetry.jit_call;
    # the sanctioned bypasses (warmup laps, fused-optimizer internals,
    # kernel-module plumbing under already-wrapped engine sites) ride
    # the baseline WITH a justification each
    files = collect_files(["mxnet_tpu"], root=REPO)
    findings = [f for f in lint_files(files, root=REPO,
                                      passes=["unattributed-dispatch"])]
    baseline = load_baseline(DEFAULT_BASELINE)
    assert apply_baseline(findings, baseline) == []
    justs = core.load_justifications(DEFAULT_BASELINE)
    for f in findings:
        assert justs.get(f.baseline_key()), \
            "unattributed-dispatch baseline entries must carry a " \
            "justification: %s" % f.baseline_key()
    # the decode engine's steady-state loop itself is fully attributed:
    # its only baselined survivor is the warmup lap
    decode = [f for f in findings if "serving/decode" in f.path]
    assert all("warmup" in (justs.get(f.baseline_key()) or "").lower()
               or "warm" in (justs.get(f.baseline_key()) or "").lower()
               for f in decode)
