"""End-to-end test of the C predict API + C++ frontend.

The reference's equivalent surface is include/mxnet/c_predict_api.h consumed
by example/image-classification/predict-cpp; here the whole loop runs:
export a checkpoint from Python, build the embedded-interpreter predict
library and the C++ demo with make, run the binary, and compare its output
numbers against the Python executor bit-for-bit (1e-4).
"""
import os
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import model

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="C++ toolchain unavailable")


def _mlp():
    data = mx.symbol.var("data")
    h = mx.symbol.FullyConnected(data, num_hidden=8, name="fc1")
    a = mx.symbol.Activation(h, act_type="relu", name="relu1")
    return mx.symbol.softmax(
        mx.symbol.FullyConnected(a, num_hidden=3, name="fc2"), name="sm")


@pytest.mark.slow
def test_cpp_predict_matches_python(tmp_path):
    out = _mlp()
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = out.infer_shape(data=(2, 5))
    args = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.3)
            for n, s in zip(out.list_arguments(), arg_shapes) if n != "data"}
    prefix = str(tmp_path / "mlp")
    model.save_checkpoint(prefix, 0, out, args, {})

    x = np.arange(10, dtype=np.float32).reshape(2, 5) * 0.01
    ex = out.simple_bind(mx.cpu(), data=(2, 5))
    ex.copy_params_from({**args, "data": mx.nd.array(x)})
    expected = ex.forward()[0].asnumpy()

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    build = subprocess.run(["make", "-C", str(REPO / "cpp-package"),
                            "predict_demo"], capture_output=True, text=True,
                           timeout=300, env=env)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run([str(REPO / "cpp-package" / "predict_demo"),
                          prefix, "2", "5"], capture_output=True, text=True,
                         timeout=300, env=env)
    assert run.returncode == 0, run.stdout + run.stderr[-2000:]
    lines = run.stdout.strip().splitlines()
    assert lines[0].strip() == "output shape: 2 3"
    got = np.array([float(v) for v in lines[1:]], np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, expected, atol=1e-4)
