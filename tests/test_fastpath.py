"""mxnet_tpu.fastpath tests — ISSUE-5 acceptance.

Covers: bit-identical parity of the fused tree-apply vs the per-parameter
loop (fp32 + fp16/bf16 master-weight multi-precision), the ≥10× dispatch
reduction, the donation-safety guard (stale NDArray raises), gradient
bucketing (plan shapes, pack/unpack round-trip, pushpull parity incl.
odd sizes / mixed dtypes / chaos), the batched Trainer exchange, the
``update_on_kvstore`` fused path, ``ignore_stale_grad`` semantics, the
``MXNET_FASTPATH=0`` escape hatch, and the persistent compile cache
hitting on a second process.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, fastpath, gluon, nd, telemetry
from mxnet_tpu import optimizer as opt
from mxnet_tpu.fastpath import bucketing
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.resilience import chaos

from conftest import subprocess_env

SHAPES = [(4, 3), (7,), (2, 2, 2), (5, 1), (3,)]


def _param_bytes(arrs):
    return [np.asarray(a._data).tobytes() for a in arrs]


def _run_updates(path, name, dtype=jnp.float32, steps=5, shapes=SHAPES,
                 **kw):
    """Drive one optimizer over several parameters via the per-param loop
    or the fused tree-apply; returns (weight bytes, states)."""
    mx.random.seed(7)
    rs = np.random.RandomState(0)
    wvals = [rs.randn(*s).astype(np.float32) for s in shapes]
    gvals = [[rs.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(steps)]
    o = opt.create(name, learning_rate=0.05, wd=0.01, **kw)
    upd = opt.get_updater(o)
    ws = [NDArray(jnp.asarray(wvals[i], dtype), mx.cpu())
          for i in range(len(shapes))]
    for s in range(steps):
        gs = [NDArray(jnp.asarray(gvals[s][i], dtype), mx.cpu())
              for i in range(len(shapes))]
        if path == "fused":
            fastpath.apply_updater(
                upd, [(i, gs[i], ws[i]) for i in range(len(ws))])
        else:
            for i in range(len(ws)):
                upd(i, gs[i], ws[i])
    return _param_bytes(ws), upd.states


# ---------------------------------------------------------------------------
# fused tree-apply: bit-identical parity (the tentpole guarantee)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,kw", [
    ("sgd", {}),
    ("sgd", {"momentum": 0.9}),
    ("adam", {}),
])
def test_fused_apply_bit_identical_fp32(name, kw):
    a, _ = _run_updates("perparam", name, **kw)
    b, _ = _run_updates("fused", name, **kw)
    assert a == b, "fused tree-apply diverged from the per-param loop"


@pytest.mark.parametrize("name,kw", [
    ("nag", {"momentum": 0.9}), ("rmsprop", {"centered": True}),
    ("rmsprop", {}), ("ftrl", {}), ("adadelta", {}), ("adagrad", {}),
    ("adamax", {}), ("ftml", {}), ("nadam", {}), ("sgld", {}),
    ("signum", {"momentum": 0.9}), ("signsgd", {}),
    ("dcasgd", {"momentum": 0.9}), ("lbsgd", {"momentum": 0.9}),
    ("test", {}),
])
def test_fused_apply_bit_identical_all_optimizers(name, kw):
    """Every registered optimizer rides the fused path for free — the
    kernel protocol makes divergence structurally impossible, this pins
    it."""
    a, _ = _run_updates("perparam", name, **kw)
    b, _ = _run_updates("fused", name, **kw)
    assert a == b, name


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float16])
@pytest.mark.parametrize("name,kw", [("sgd", {"momentum": 0.9}),
                                     ("adam", {})])
def test_fused_apply_bit_identical_master_weight(name, kw, dtype):
    """fp16/bf16 weights with multi_precision: fused in-trace master-weight
    handling matches update_multi_precision bit for bit."""
    a, _ = _run_updates("perparam", name, dtype=dtype,
                        multi_precision=True, **kw)
    b, _ = _run_updates("fused", name, dtype=dtype,
                        multi_precision=True, **kw)
    assert a == b


@pytest.mark.parametrize("path", ["fused", "perparam"])
def test_multi_precision_migrates_pre_master_states(path):
    """A bf16 optimizer state saved BEFORE multi_precision covered bfloat16
    is a plain (m, v) tuple; restoring it must adopt an fp32 master instead
    of mis-unpacking the moments as (master, base)."""
    o = opt.create("adam", learning_rate=0.01, multi_precision=True)
    upd = opt.get_updater(o)
    w = NDArray(jnp.asarray(np.ones((4, 3), np.float32), jnp.bfloat16),
                mx.cpu())
    g = NDArray(jnp.asarray(np.full((4, 3), 0.5, np.float32), jnp.bfloat16),
                mx.cpu())
    # pre-migration layout: create_state on the raw weight (no master pair)
    upd.states[0] = o.create_state(0, w)
    upd.states_synced[0] = True
    if path == "fused":
        fastpath.apply_updater(upd, [(0, g, w)])
    else:
        upd(0, g, w)
    master, base = upd.states[0]  # migrated to the pair layout
    assert master.dtype == jnp.float32 and master.shape == w.shape
    assert len(base) == 2  # adam (m, v) kept as the base state
    assert np.all(np.asarray(w.asnumpy(), np.float32) < 1.0)  # stepped


def test_multi_precision_does_not_mistake_fp32_moments_for_master():
    """An fp32 Adam run's (m, v) state resumed onto bf16-cast weights is
    structurally a 2-tuple of fp32 weight-shaped arrays — it must be
    wrapped as the BASE of a fresh master pair, never unpacked as
    (master, base) with the first moment installed as the weight."""
    from mxnet_tpu.optimizer import ensure_mp_state

    o = opt.create("adam", learning_rate=0.01, multi_precision=True)
    w = NDArray(jnp.asarray(np.full((4, 3), 0.75, np.float32),
                            jnp.bfloat16), mx.cpu())
    m = jnp.full((4, 3), 1e-8, jnp.float32)
    v = jnp.full((4, 3), 1e-8, jnp.float32)
    state = ensure_mp_state(o, 0, w, (m, v))
    master, base = state
    # the master is the WEIGHT, not the near-zero first moment
    np.testing.assert_allclose(np.asarray(master), 0.75, rtol=1e-2)
    assert base is not None and len(base) == 2
    # and a genuine pair passes through untouched
    assert ensure_mp_state(o, 0, w, state) is state


def test_fused_apply_rejects_incapable_optimizer():
    class NoKernel(opt.Optimizer):
        pass

    o = NoKernel()
    w = nd.array(np.ones((2, 2), np.float32))
    g = nd.array(np.ones((2, 2), np.float32))
    with pytest.raises(fastpath.FusedApplyError):
        fastpath.fused_apply(o, [0], [g], [w], [None])


# ---------------------------------------------------------------------------
# dispatch accounting: >= 10x fewer update dispatches per step
# ---------------------------------------------------------------------------

def _mlp(n_layers=6):
    net = gluon.nn.Sequential()
    for _ in range(n_layers - 1):
        net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    net(nd.array(np.zeros((2, 8), np.float32)))
    return net


def _train_mlp(steps=3):
    mx.random.seed(0)  # identical init across the legacy/fused runs
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    rs = np.random.RandomState(1)
    for s in range(steps):
        x = nd.array(rs.rand(2, 8).astype(np.float32))
        y = nd.array(rs.rand(2, 4).astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(2)
    # positional keys: the global block-name counter differs across nets
    return [p.data().asnumpy().tobytes()
            for p in net.collect_params().values()]


def test_dispatches_per_step_10x_reduction(monkeypatch):
    """ISSUE-5 acceptance: ≥10× fewer optimizer-update dispatches per step
    on an MLP with ≥10 parameters (12 here: 6 layers × weight+bias)."""
    steps = 3
    monkeypatch.setenv("MXNET_FASTPATH", "0")
    pp0 = telemetry.OPT_DISPATCHES.value(path="perparam")
    _train_mlp(steps)
    perparam = telemetry.OPT_DISPATCHES.value(path="perparam") - pp0
    monkeypatch.setenv("MXNET_FASTPATH", "1")
    f0 = telemetry.OPT_DISPATCHES.value(path="fused")
    _train_mlp(steps)
    fused = telemetry.OPT_DISPATCHES.value(path="fused") - f0
    assert fused == steps  # ONE dispatch per step
    assert perparam / fused >= 10, (perparam, fused)


def test_trainer_fastpath_matches_legacy_bitwise(monkeypatch):
    """MXNET_FASTPATH=0 escape hatch and the fused route train to the SAME
    bits."""
    monkeypatch.setenv("MXNET_FASTPATH", "0")
    legacy = _train_mlp()
    monkeypatch.setenv("MXNET_FASTPATH", "1")
    fused = _train_mlp()
    assert legacy == fused


# ---------------------------------------------------------------------------
# ignore_stale_grad semantics (regression: previously silently ignored)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fast", ["1", "0"])
def test_trainer_ignore_stale_grad(monkeypatch, fast):
    monkeypatch.setenv("MXNET_FASTPATH", fast)
    net = _mlp(2)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    x = nd.array(np.ones((2, 8), np.float32))
    y = nd.array(np.ones((2, 4), np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(2)
    # no new backward: every grad is stale now
    with pytest.raises(UserWarning):
        trainer.step(2)
    before = {k: p.data().asnumpy().tobytes()
              for k, p in net.collect_params().items()}
    trainer.step(2, ignore_stale_grad=True)  # skips, doesn't corrupt
    after = {k: p.data().asnumpy().tobytes()
             for k, p in net.collect_params().items()}
    assert before == after


# ---------------------------------------------------------------------------
# donation-safety guard
# ---------------------------------------------------------------------------

def test_donation_invalidates_stale_handles(monkeypatch):
    """With donation forced on, an NDArray still wrapping the pre-step
    buffer raises on use instead of reading garbage."""
    monkeypatch.setenv("MXNET_FASTPATH_DONATE", "1")
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = nd.array(np.ones((4, 4), np.float32))
    g = nd.array(np.ones((4, 4), np.float32))
    stale = NDArray(w._data, w.context)  # aliases the pre-step buffer
    fastpath.apply_updater(upd, [(0, g, w)])
    np.asarray(w.asnumpy())  # the live handle moved to the new buffer
    with pytest.raises(Exception, match="[Dd]eleted"):
        stale.asnumpy()


def test_no_donation_keeps_old_buffers(monkeypatch):
    monkeypatch.setenv("MXNET_FASTPATH_DONATE", "0")
    o = opt.create("sgd", learning_rate=0.1)
    upd = opt.get_updater(o)
    w = nd.array(np.ones((4, 4), np.float32))
    g = nd.array(np.ones((4, 4), np.float32))
    stale = NDArray(w._data, w.context)
    fastpath.apply_updater(upd, [(0, g, w)])
    np.testing.assert_allclose(stale.asnumpy(), 1.0)  # untouched


def test_donation_skipped_for_duplicated_buffers(monkeypatch):
    """DCASGD's `prev` state starts as the weight buffer itself — duplicate
    donation must be detected and skipped, not crash."""
    monkeypatch.setenv("MXNET_FASTPATH_DONATE", "1")
    o = opt.create("dcasgd", learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = nd.array(np.ones((3, 3), np.float32))
    g = nd.array(np.ones((3, 3), np.float32))
    fastpath.apply_updater(upd, [(0, g, w)])
    w.asnumpy()  # live handle fine; no duplicate-donation error raised


# ---------------------------------------------------------------------------
# gradient bucketing
# ---------------------------------------------------------------------------

def test_bucket_plan_shapes_mixed_dtypes_and_solo():
    cap = 64  # bytes, tiny so the layout is forced
    leaves = [jnp.ones((4,), jnp.float32),     # 16 B
              jnp.ones((3,), jnp.float32),     # 12 B
              jnp.ones((5,), jnp.float16),     # 10 B
              jnp.ones((100,), jnp.float32),   # 400 B >= cap: solo
              jnp.ones((7,), jnp.float16),     # 14 B
              jnp.ones((2, 3), jnp.float32)]   # 24 B
    plan = bucketing.plan_for(leaves, cap)
    assert plan is not None
    flat = [i for b in plan.buckets for i in b]
    assert sorted(flat + plan.solo) == list(range(len(leaves)))
    assert 3 in plan.solo  # over-cap leaf rides alone
    for b in plan.buckets:
        dts = {str(leaves[i].dtype) for i in b}
        assert len(dts) == 1  # buckets never mix dtypes
        assert sum(leaves[i].nbytes for i in b) <= cap

    packed = plan.pack(leaves)
    assert len(packed) == plan.n_out
    out = plan.unpack(packed)
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_bucket_plan_disabled_or_degenerate():
    assert bucketing.plan_for([jnp.ones((4,))], 1024) is None  # one leaf
    assert bucketing.plan_for([jnp.ones((4,)), jnp.ones((4,))], 0) is None
    # nothing coalesces: every dtype has one small leaf
    assert bucketing.plan_for([jnp.ones((4,), jnp.float32),
                               jnp.ones((4,), jnp.float16)], 1024) is None


def _two_copy_values(rs, shapes_dtypes):
    """Per-key 2-device copy lists + expected elementwise sums."""
    devs = jax.devices()[:2]
    values, expect = [], []
    for shape, dt in shapes_dtypes:
        copies = [rs.rand(*shape).astype(dt) for _ in devs]
        expect.append(sum(c.astype(np.float64) for c in copies))
        values.append([NDArray(jax.device_put(jnp.asarray(c), d), mx.cpu())
                       for c, d in zip(copies, devs)])
    return values, expect


@pytest.mark.parametrize("bucket_mb", ["0", "1"])
def test_pushpull_multi_bucketing_parity(monkeypatch, bucket_mb):
    """Bucketed and unbucketed fused pushpull produce identical sums over
    odd sizes and mixed dtypes (bit-identical: sums are elementwise)."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", bucket_mb)
    rs = np.random.RandomState(3)
    shapes_dtypes = [((7,), np.float32), ((3, 5), np.float32),
                     ((2, 2, 2), np.float32), ((11,), np.float16),
                     ((1,), np.float32), ((5,), np.float16)]
    values, expect = _two_copy_values(rs, shapes_dtypes)
    kv = mx.kv.create("tpu")
    keys = list(range(len(values)))
    for k, v in zip(keys, values):
        kv.init(k, nd.zeros(v[0].shape, dtype=v[0].dtype))
    packs = []
    orig_pack = bucketing.Plan.pack
    monkeypatch.setattr(bucketing.Plan, "pack",
                        lambda self, leaves: packs.append(1)
                        or orig_pack(self, leaves))
    outs = [[nd.zeros(v[0].shape, dtype=v[0].dtype) for _ in v]
            for v in values]
    kv.pushpull_multi(keys, values, outs)
    if bucket_mb != "0":
        assert packs, "bucketing did not engage on the multi-copy exchange"
    else:
        assert not packs
    for o_list, exp, (shape, dt) in zip(outs, expect, shapes_dtypes):
        for o in o_list:
            np.testing.assert_allclose(
                o.asnumpy().astype(np.float64), exp,
                rtol=1e-2 if dt == np.float16 else 1e-6)


def test_pushpull_multi_bucketed_chaos_bit_identical(monkeypatch):
    """ISSUE-5 acceptance: the retried aggregate stays bit-identical under
    injected faults WITH bucketing enabled (pack/reduce/unpack all inside
    the pure phase, commit outside)."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "1")
    rs = np.random.RandomState(11)
    shapes_dtypes = [((7,), np.float32), ((3, 5), np.float32),
                     ((9,), np.float32)]

    def exchange():
        values, _ = _two_copy_values(np.random.RandomState(11),
                                     shapes_dtypes)
        kv = mx.kv.create("tpu")
        keys = list(range(len(values)))
        for k, v in zip(keys, values):
            kv.init(k, nd.zeros(v[0].shape, dtype=v[0].dtype))
        outs = [[nd.zeros(v[0].shape, dtype=v[0].dtype)] for v in values]
        for _ in range(6):
            kv.pushpull_multi(keys, values, outs)
        return [o[0].asnumpy().tobytes() for o in outs]

    clean = exchange()
    with chaos.active("seed=5,site=kvstore.*,p=0.3"):
        faulted = exchange()
        injected = chaos.injected_counts()
    assert any(s.startswith("kvstore.") for s in injected), injected
    assert clean == faulted


def test_chaos_training_bit_identical_with_bucketing(monkeypatch):
    """The PR-4 end-to-end chaos training acceptance, re-run with the
    bucketing knob enabled."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_MB", "1")
    from test_resilience import test_chaos_training_bit_identical

    test_chaos_training_bit_identical()


# ---------------------------------------------------------------------------
# batched gradient exchange (Trainer / base store / update_on_kvstore)
# ---------------------------------------------------------------------------

def test_trainer_allreduce_grads_single_pushpull():
    """allreduce_grads batches EVERY gradient through one pushpull_multi
    call instead of per-param push/pull."""
    from mxnet_tpu.kvstore import _T_OPS

    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="tpu")
    loss_fn = gluon.loss.L2Loss()
    x = nd.array(np.ones((2, 8), np.float32))
    y = nd.array(np.ones((2, 4), np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    p0 = _T_OPS.value(op="push")
    m0 = _T_OPS.value(op="pushpull_multi")
    trainer.allreduce_grads()
    assert _T_OPS.value(op="push") == p0  # zero per-key pushes
    assert _T_OPS.value(op="pushpull_multi") == m0 + 1  # ONE batched call


def test_escape_hatch_gates_the_exchange_plane(monkeypatch):
    """MXNET_FASTPATH=0 restores per-key push/pull too — an operator
    bisecting an exchange bug must be able to rule out the batched path."""
    from mxnet_tpu.kvstore import _T_OPS

    kv = mx.kv.create("tpu")
    monkeypatch.setenv("MXNET_FASTPATH", "0")
    assert not kv._can_fuse_pushpull()
    net = _mlp(2)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="tpu")
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net(nd.array(np.ones((2, 8), np.float32))),
                       nd.array(np.ones((2, 4), np.float32)))
    loss.backward()
    m0 = _T_OPS.value(op="pushpull_multi")
    p0 = _T_OPS.value(op="push")
    trainer.step(2)
    assert _T_OPS.value(op="pushpull_multi") == m0  # batched path off
    assert _T_OPS.value(op="push") > p0             # legacy per-key on


def test_base_store_pushpull_multi_matches_push_pull():
    """The host ('local') store's batched exchange equals its per-key
    push+pull sequence."""
    rs = np.random.RandomState(5)
    shapes = [(4,), (2, 3), (5,)]
    vals = [rs.rand(*s).astype(np.float32) for s in shapes]

    def drive(batched):
        kv = mx.kv.create("local")
        outs = []
        for i, (s, v) in enumerate(zip(shapes, vals)):
            kv.init(i, nd.zeros(s))
            outs.append(nd.zeros(s))
        if batched:
            kv.pushpull_multi(list(range(len(shapes))),
                              [nd.array(v) for v in vals], outs)
        else:
            for i, v in enumerate(vals):
                kv.push(i, nd.array(v))
                kv.pull(i, out=outs[i])
        return [o.asnumpy().tobytes() for o in outs]

    assert drive(True) == drive(False)


def test_update_params_on_kvstore_paths_agree(monkeypatch):
    """model._update_params_on_kvstore: the fused pushpull_update_multi
    exchange and the legacy per-key push/pull produce the same weights."""
    from mxnet_tpu import model as model_mod

    rs = np.random.RandomState(9)
    shapes = [(4, 3), (7,), (2, 5)]
    wvals = [rs.randn(*s).astype(np.float32) for s in shapes]
    gvals = [[rs.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(3)]

    def drive(fast):
        monkeypatch.setenv("MXNET_FASTPATH", fast)
        kv = mx.kv.create("local")
        params = [nd.array(w) for w in wvals]
        for i, p in enumerate(params):
            kv.init(i, p)
        kv.set_optimizer(opt.create("sgd", learning_rate=0.05,
                                    momentum=0.9))
        for step in range(3):
            grads = [nd.array(g) for g in gvals[step]]
            model_mod._update_params_on_kvstore(
                [[p] for p in params], [[g] for g in grads], kv,
                ["p%d" % i for i in range(len(params))])
        return [p.asnumpy().tobytes() for p in params]

    assert drive("1") == drive("0")


def test_multi_position_lr_scheduler_falls_back(monkeypatch):
    """lr_scheduler reads the optimizer-global num_update, which is
    iteration-order-sensitive across device positions — with >1 positions
    the fused grouping must fall back so MXNET_FASTPATH=1 stays
    bitwise-equal to =0."""
    from mxnet_tpu import lr_scheduler
    from mxnet_tpu import model as model_mod

    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    assert not fastpath.supports(
        opt.create("sgd", learning_rate=0.1, lr_scheduler=sched),
        n_positions=2)

    rs = np.random.RandomState(21)
    wvals = [[rs.randn(4, 3).astype(np.float32) for _ in range(2)]
             for _ in range(2)]
    gvals = [[[rs.randn(4, 3).astype(np.float32) for _ in range(2)]
              for _ in range(2)] for _ in range(4)]

    def drive(fast):
        monkeypatch.setenv("MXNET_FASTPATH", fast)
        params = [[nd.array(c) for c in w] for w in wvals]
        sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
        updater = opt.get_updater(opt.create(
            "sgd", learning_rate=0.1, momentum=0.9, lr_scheduler=sched))
        for step in range(4):
            grads = [[nd.array(c) for c in g] for g in gvals[step]]
            model_mod._update_params(params, grads, updater, 2)
        return [c.asnumpy().tobytes() for p in params for c in p]

    assert drive("1") == drive("0")


@pytest.mark.parametrize("name", ["nadam", "sgld", "adam"])
def test_update_params_multi_device_paths_agree(monkeypatch, name):
    """num_device > 1: optimizers with an order-sensitive host prologue
    (Nadam's m_schedule, SGLD's rng stream) must fall back to the legacy
    ordering so MXNET_FASTPATH=1 stays bitwise-equal to =0; order-free
    optimizers (adam) keep the fused path."""
    from mxnet_tpu import model as model_mod

    rs = np.random.RandomState(13)
    shapes = [(4, 3), (7,)]
    wvals = [[rs.randn(*s).astype(np.float32) for _ in range(2)]
             for s in shapes]
    gvals = [[[rs.randn(*s).astype(np.float32) for _ in range(2)]
              for s in shapes] for _ in range(3)]

    def drive(fast):
        mx.random.seed(3)  # sgld noise stream must restart identically
        monkeypatch.setenv("MXNET_FASTPATH", fast)
        params = [[nd.array(c) for c in w] for w in wvals]
        updater = opt.get_updater(opt.create(name, learning_rate=0.01))
        for step in range(3):
            grads = [[nd.array(c) for c in g] for g in gvals[step]]
            model_mod._update_params(params, grads, updater, 2)
        return [c.asnumpy().tobytes() for p in params for c in p]

    assert drive("1") == drive("0")


def test_update_params_host_updater_paths_agree(monkeypatch):
    """model._update_params (host-side updater): fused vs legacy bitwise."""
    from mxnet_tpu import model as model_mod

    rs = np.random.RandomState(4)
    shapes = [(4, 3), (7,), (2, 5)]
    wvals = [rs.randn(*s).astype(np.float32) for s in shapes]
    gvals = [[rs.randn(*s).astype(np.float32) for s in shapes]
             for _ in range(3)]

    def drive(fast):
        monkeypatch.setenv("MXNET_FASTPATH", fast)
        params = [nd.array(w) for w in wvals]
        updater = opt.get_updater(opt.create("adam", learning_rate=0.01))
        for step in range(3):
            grads = [nd.array(g) for g in gvals[step]]
            model_mod._update_params([[p] for p in params],
                                     [[g] for g in grads], updater, 1)
        return [p.asnumpy().tobytes() for p in params]

    assert drive("1") == drive("0")


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

_CACHE_PROBE = r"""
import json, sys
import mxnet_tpu as mx
import jax, jax.numpy as jnp
from mxnet_tpu.fastpath import cache
f = jax.jit(lambda x: x * 3 + 1)
f(jnp.ones((16, 16))).block_until_ready()
hits, misses = cache.cache_counts()
print(json.dumps({"hits": hits, "misses": misses,
                  "configured": cache.configured()}))
"""


@pytest.mark.slow
def test_compile_cache_hits_on_second_process(tmp_path):
    """ISSUE-5 acceptance: a restarted process deserializes executables
    from MXNET_COMPILE_CACHE_DIR instead of recompiling."""
    env = subprocess_env(MXNET_COMPILE_CACHE_DIR=str(tmp_path))

    def probe():
        out = subprocess.run([sys.executable, "-c", _CACHE_PROBE],
                             capture_output=True, text=True, env=env,
                             timeout=300, cwd=os.path.dirname(
                                 os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = probe()
    assert first["configured"] == str(tmp_path)
    if first["misses"] == 0 and first["hits"] == 0:
        pytest.skip("backend does not report compilation-cache events")
    assert first["misses"] > 0
    entries = list(tmp_path.iterdir())
    assert entries, "first process wrote no cache entries"
    second = probe()
    assert second["hits"] > 0, second
