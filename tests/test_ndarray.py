"""NDArray API tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = mx.nd.ones((4,), dtype="int32")
    assert b.asnumpy().tolist() == [1, 1, 1, 1]
    c = mx.nd.full((2, 2), 7.0)
    assert c.asnumpy().sum() == 28
    d = mx.nd.arange(0, 10, 2)
    assert d.asnumpy().tolist() == [0, 2, 4, 6, 8]
    e = mx.nd.array([[1, 2], [3, 4]])
    assert e.shape == (2, 2)
    assert mx.nd.eye(3).asnumpy().trace() == 3


def test_arithmetic():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert_almost_equal(a + b, [[11, 22], [33, 44]])
    assert_almost_equal(b - a, [[9, 18], [27, 36]])
    assert_almost_equal(a * 2, [[2, 4], [6, 8]])
    assert_almost_equal(2 * a, [[2, 4], [6, 8]])
    assert_almost_equal(1 / a, [[1, 0.5], [1 / 3, 0.25]], rtol=1e-6)
    assert_almost_equal(a ** 2, [[1, 4], [9, 16]])
    assert_almost_equal(-a, [[-1, -2], [-3, -4]])
    assert_almost_equal(10 - a, [[9, 8], [7, 6]])
    assert_almost_equal((a > 2), [[0, 0], [1, 1]])
    assert_almost_equal((a == 2), [[0, 1], [0, 0]])


def test_inplace():
    a = mx.nd.ones((2, 2))
    a += 1
    assert_almost_equal(a, [[2, 2], [2, 2]])
    a *= 3
    assert_almost_equal(a, [[6, 6], [6, 6]])


def test_broadcast():
    a = mx.nd.ones((2, 1, 3))
    b = mx.nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = mx.nd.ones((2, 3)).broadcast_to((4, 2, 3))
    assert c.shape == (4, 2, 3)


def test_shape_ops():
    a = mx.nd.arange(0, 24).reshape((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert mx.nd.tile(a, reps=(2, 1, 1)).shape == (4, 3, 4)
    parts = a.split(3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
    st = mx.nd.stack(mx.nd.ones((2,)), mx.nd.zeros((2,)), axis=0)
    assert st.shape == (2, 2)
    cc = mx.nd.concat(mx.nd.ones((2, 3)), mx.nd.zeros((2, 2)), dim=1)
    assert cc.shape == (2, 5)


def test_slicing():
    a = mx.nd.arange(0, 24).reshape((4, 6))
    assert_almost_equal(a[1], np.arange(6, 12))
    assert_almost_equal(a[1:3], np.arange(6, 18).reshape(2, 6))
    assert a.slice(begin=(1, 2), end=(3, 5)).shape == (2, 3)
    assert a.slice_axis(axis=1, begin=0, end=3).shape == (4, 3)
    a[0] = 100.0
    assert a.asnumpy()[0].tolist() == [100.0] * 6
    a[1, 2] = -1.0
    assert a.asnumpy()[1, 2] == -1.0


def test_reductions():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().asscalar() == 10
    assert_almost_equal(a.sum(axis=0), [4, 6])
    assert_almost_equal(a.sum(axis=1, keepdims=True), [[3], [7]])
    assert a.mean().asscalar() == 2.5
    assert a.max().asscalar() == 4
    assert a.min().asscalar() == 1
    assert a.prod().asscalar() == 24
    assert float(a.norm().asscalar()) == pytest.approx(np.sqrt(30), rel=1e-5)
    assert a.argmax(axis=1).asnumpy().tolist() == [1, 1]


def test_dot():
    a = mx.nd.array(np.random.randn(3, 4).astype(np.float32))
    b = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    assert_almost_equal(mx.nd.dot(a, b), a.asnumpy() @ b.asnumpy(), rtol=1e-4, atol=1e-5)
    # transpose flags
    assert_almost_equal(
        mx.nd.dot(a, b.T, transpose_b=True), a.asnumpy() @ b.asnumpy(), rtol=1e-4, atol=1e-5
    )
    # batch_dot
    x = mx.nd.array(np.random.randn(2, 3, 4).astype(np.float32))
    y = mx.nd.array(np.random.randn(2, 4, 5).astype(np.float32))
    assert_almost_equal(mx.nd.batch_dot(x, y), x.asnumpy() @ y.asnumpy(), rtol=1e-4, atol=1e-5)


def test_indexing_ops():
    w = mx.nd.arange(0, 12).reshape((4, 3))
    idx = mx.nd.array([0, 2])
    assert_almost_equal(mx.nd.take(w, idx), w.asnumpy()[[0, 2]])
    emb = mx.nd.Embedding(idx, w, input_dim=4, output_dim=3)
    assert_almost_equal(emb, w.asnumpy()[[0, 2]])
    oh = mx.nd.one_hot(mx.nd.array([0, 2]), depth=3)
    assert_almost_equal(oh, [[1, 0, 0], [0, 0, 1]])
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    picked = mx.nd.pick(x, mx.nd.array([0, 1]), axis=1)
    assert_almost_equal(picked, [1, 4])


def test_ordering():
    a = mx.nd.array([[3.0, 1.0, 2.0]])
    assert mx.nd.topk(a, k=2).asnumpy().tolist() == [[0, 2]]
    assert mx.nd.sort(a).asnumpy().tolist() == [[1, 2, 3]]
    assert mx.nd.argsort(a).asnumpy().tolist() == [[1, 2, 0]]
    both = mx.nd.topk(a, k=2, ret_typ="both")
    assert both[0].asnumpy().tolist() == [[3, 2]]


def test_astype_cast():
    a = mx.nd.array([1.5, 2.5])
    assert a.astype("int32").asnumpy().tolist() == [1, 2]
    assert a.astype(np.float16).dtype == np.float16


def test_context_placement():
    a = mx.nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b is a
    c = a.copyto(mx.cpu(0))
    assert c is not a


def test_scalar_conversions():
    assert float(mx.nd.array([3.5])) == 3.5
    assert int(mx.nd.array([3])) == 3
    assert mx.nd.array([2.0]).asscalar() == 2.0
    with pytest.raises(Exception):
        mx.nd.ones((2,)).asscalar()


def test_where_clip_misc():
    cond = mx.nd.array([1.0, 0.0])
    x = mx.nd.array([1.0, 2.0])
    y = mx.nd.array([10.0, 20.0])
    assert_almost_equal(mx.nd.where(cond, x, y), [1, 20])
    assert_almost_equal(mx.nd.clip(y, 0, 15), [10, 15])
    assert_almost_equal(mx.nd.abs(mx.nd.array([-1.0, 2.0])), [1, 2])


def test_sparse_roundtrip():
    dense = np.array([[0, 0], [1, 2], [0, 0], [3, 0]], dtype=np.float32)
    rsp = mx.nd.array(dense).tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.indices.asnumpy().tolist() == [1, 3]
    assert_almost_equal(rsp.tostype("default"), dense)
    csr = mx.nd.array(dense).tostype("csr")
    assert csr.stype == "csr"
    assert_almost_equal(csr.tostype("default"), dense)


def test_random_basic():
    mx.random.seed(42)
    u1 = mx.nd.random.uniform(0, 1, shape=(100,)).asnumpy()
    mx.random.seed(42)
    u2 = mx.nd.random.uniform(0, 1, shape=(100,)).asnumpy()
    assert np.allclose(u1, u2)
    assert (u1 >= 0).all() and (u1 < 1).all()
    n = mx.nd.random.normal(0, 1, shape=(1000,)).asnumpy()
    assert abs(n.mean()) < 0.2


def test_dlpack_interop():
    """DLPack round trips (reference MXNDArrayToDLPack/FromDLPack,
    SURVEY §2.2 'keep: dlpack is still the interop standard')."""
    import torch

    x = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    # mx -> torch, zero-copy through the protocol object
    t = torch.utils.dlpack.from_dlpack(x)
    np.testing.assert_array_equal(t.numpy(), x.asnumpy())
    # torch -> mx
    src = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    back = mx.nd.from_dlpack(src)
    assert isinstance(back, mx.nd.NDArray)
    np.testing.assert_array_equal(back.asnumpy(), src.numpy())
    # capsule form
    cap = mx.nd.to_dlpack_for_read(x)
    t2 = torch.utils.dlpack.from_dlpack(cap)
    np.testing.assert_array_equal(t2.numpy(), x.asnumpy())
    # ops compose on the imported array
    np.testing.assert_allclose((back + 1).asnumpy(), src.numpy() + 1)
