"""Gluon tests — mirrors reference tests/python/unittest/test_gluon.py
strategy: parameter lifecycle, block composition, hybridize consistency,
layer shape/numerics checks, trainer convergence, save/load round-trips."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=mx.cpu())
    assert p.name == "weight"
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_ctx() == [mx.cpu()]


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(Exception):
        p.data()


def test_parameter_dict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())
    # shared dict
    shared = gluon.ParameterDict("net_", shared=params)
    p2 = shared.get("weight")
    assert p2 is params["net_weight"]


def test_constant_param():
    const = np.random.uniform(size=(2, 2)).astype(np.float32)

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.c = self.params.get_constant("const", const)

        def hybrid_forward(self, F, x, c):
            return x + c

    net = Net()
    net.initialize()
    x = mx.nd.zeros((2, 2))
    out = net(x)
    np.testing.assert_allclose(out.asnumpy(), const)
    # constants get no gradient
    with autograd.record():
        y = net(x)
    assert net.c.grad_req == "null"


def test_dense():
    net = nn.Dense(5, use_bias=True, flatten=True, in_units=4)
    net.initialize()
    x = mx.nd.ones((3, 4))
    out = net(x)
    assert out.shape == (3, 5)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    np.testing.assert_allclose(out.asnumpy(), np.ones((3, 4)) @ w.T + b, rtol=1e-5)
    # no flatten: applies to last dim
    net2 = nn.Dense(5, flatten=False)
    net2.initialize()
    assert net2(mx.nd.ones((2, 3, 4))).shape == (2, 3, 5)


def test_deferred_init_and_reinit():
    net = nn.Dense(5)
    net.initialize()
    assert net.weight.shape == (5, 0)
    net(mx.nd.ones((2, 7)))
    assert net.weight.shape == (5, 7)


def test_sequential_and_getitem():
    net = nn.Sequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    net.initialize()
    out = net(mx.nd.ones((1, 5)))
    assert out.shape == (1, 2)
    sliced = net[1:]
    assert len(sliced) == 2


def test_hybrid_consistency():
    def make():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh"),
                    nn.LayerNorm(),
                    nn.Dense(4))
        return net

    mx.random.seed(7)
    net = make()
    net.initialize()
    x = mx.nd.array(np.random.randn(3, 6).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    compiled = net(x).asnumpy()
    np.testing.assert_allclose(eager, compiled, rtol=1e-5, atol=1e-6)


def test_hybrid_multi_input_output():
    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, a, b):
            return a + b, a * b

    net = Net()
    net.hybridize()
    a, b = mx.nd.ones((2, 2)), mx.nd.full((2, 2), 3.0)
    s, p = net(a, b)
    np.testing.assert_allclose(s.asnumpy(), 4.0)
    np.testing.assert_allclose(p.asnumpy(), 3.0)


def test_conv_layers():
    for layer, shape, expected in [
        (nn.Conv1D(4, 3), (1, 2, 10), (1, 4, 8)),
        (nn.Conv2D(4, 3, padding=1), (1, 2, 8, 8), (1, 4, 8, 8)),
        (nn.Conv2D(4, 3, strides=2, groups=2), (1, 2, 8, 8), (1, 4, 3, 3)),
        (nn.Conv3D(2, 2), (1, 2, 4, 4, 4), (1, 2, 3, 3, 3)),
        (nn.Conv2DTranspose(4, 2, strides=2), (1, 2, 4, 4), (1, 4, 8, 8)),
        (nn.MaxPool2D(2), (1, 2, 8, 8), (1, 2, 4, 4)),
        (nn.AvgPool2D(2, strides=1), (1, 2, 4, 4), (1, 2, 3, 3)),
        (nn.GlobalAvgPool2D(), (1, 3, 5, 5), (1, 3, 1, 1)),
        (nn.GlobalMaxPool1D(), (1, 3, 5), (1, 3, 1)),
    ]:
        layer.initialize()
        out = layer(mx.nd.ones(shape))
        assert out.shape == expected, (type(layer).__name__, out.shape, expected)


def test_pool_ceil_mode():
    x = mx.nd.ones((1, 2, 6, 6))
    assert nn.MaxPool2D(3, 2)(x).shape == (1, 2, 2, 2)
    assert nn.MaxPool2D(3, 2, ceil_mode=True)(x).shape == (1, 2, 3, 3)


def test_batchnorm_train_eval():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = mx.nd.array(np.random.randn(4, 3, 2, 2).astype(np.float32))
    with autograd.record():
        out = net(x)
    # train mode: normalized by batch stats → per-channel mean ~0
    m = out.asnumpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0, atol=1e-5)
    assert np.abs(net.running_mean.data().asnumpy()).sum() > 0
    # eval mode uses running stats
    out_eval = net(x)
    assert not np.allclose(out_eval.asnumpy(), out.asnumpy())


def test_embedding_flatten_dropout():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array([1, 2, 3])
    assert emb(idx).shape == (3, 4)

    fl = nn.Flatten()
    assert fl(mx.nd.ones((2, 3, 4))).shape == (2, 12)

    do = nn.Dropout(0.5)
    x = mx.nd.ones((10, 10))
    assert np.allclose(do(x).asnumpy(), 1.0)  # eval: identity
    with autograd.record():
        y = do(x)
    a = y.asnumpy()
    assert (a == 0).sum() > 0 and not np.allclose(a, 1.0)


def test_activations_layers():
    x = mx.nd.array([-2.0, 0.0, 2.0])
    assert np.allclose(nn.LeakyReLU(0.1)(x).asnumpy(), [-0.2, 0, 2])
    selu = nn.SELU()
    assert selu(x).shape == x.shape
    sw = nn.Swish()
    assert sw(x).shape == x.shape
    pr = nn.PReLU()
    pr.initialize()
    assert pr(x.reshape((1, 3))).shape == (1, 3)


def test_losses():
    from mxnet_tpu.gluon import loss as gloss

    pred = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    label_idx = mx.nd.array([0, 1, 2, 3])
    label_same = mx.nd.array(np.random.randn(4, 5).astype(np.float32))

    l = gloss.SoftmaxCrossEntropyLoss()(pred, label_idx)
    assert l.shape == (4,)
    # cross-check vs numpy
    p = pred.asnumpy()
    logp = p - p.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    expected = -logp[np.arange(4), label_idx.asnumpy().astype(int)]
    np.testing.assert_allclose(l.asnumpy(), expected, rtol=1e-5)

    assert gloss.L2Loss()(pred, label_same).shape == (4,)
    assert gloss.L1Loss()(pred, label_same).shape == (4,)
    assert gloss.SigmoidBCELoss()(pred, (label_same > 0)).shape == (4,)
    assert gloss.HuberLoss()(pred, label_same).shape == (4,)
    assert gloss.HingeLoss()(pred, label_same.sign()).shape == (4,)
    assert gloss.SquaredHingeLoss()(pred, label_same.sign()).shape == (4,)
    assert gloss.LogisticLoss()(pred.reshape((20,)), label_same.reshape((20,)).sign()).shape == (20,)
    assert gloss.KLDivLoss()(pred.log_softmax(), label_same.softmax()).shape == (4,)
    t = gloss.TripletLoss()(pred, label_same, -label_same)
    assert t.shape == (4,)


def test_ctc_loss():
    from mxnet_tpu.gluon import loss as gloss

    loss = gloss.CTCLoss()
    # uniform predictions over 4 classes, T=10, L=2
    pred = mx.nd.zeros((2, 10, 4))
    label = mx.nd.array([[1, 2], [2, 3]])
    l = loss(pred, label)
    assert l.shape == (2,)
    assert np.all(np.isfinite(l.asnumpy()))
    assert np.all(l.asnumpy() > 0)
    # grads flow
    pred.attach_grad()
    with autograd.record():
        l = loss(pred, label)
    l.backward()
    assert np.abs(pred.grad.asnumpy()).sum() > 0


def test_trainer_convergence():
    net = nn.Dense(1, in_units=2)
    net.initialize(init="zeros")
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    target_w = np.array([[2.0, -1.0]], dtype=np.float32)
    rng = np.random.RandomState(0)
    for _ in range(100):
        x_np = rng.randn(16, 2).astype(np.float32)
        y_np = x_np @ target_w.T
        x, y = mx.nd.array(x_np), mx.nd.array(y_np)
        with autograd.record():
            out = net(x)
            loss = ((out - y) ** 2).sum(axis=1)  # per-sample loss (gluon idiom)
        loss.backward()
        trainer.step(16)
    got = net.weight.data().asnumpy()
    np.testing.assert_allclose(got, target_w, atol=0.05)


def test_trainer_states_roundtrip(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    x = mx.nd.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(2)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr.load_states(f)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(f)
    x = mx.nd.ones((1, 3))
    np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(), rtol=1e-6)


def test_params_file_format(tmp_path):
    """The .params container must match the reference byte format
    (SURVEY Appendix B)."""
    import struct

    f = str(tmp_path / "fmt.params")
    mx.nd.save(f, {"w": mx.nd.ones((2, 3))})
    with open(f, "rb") as fin:
        buf = fin.read()
    magic, reserved = struct.unpack_from("<QQ", buf, 0)
    assert magic == 0x112
    count = struct.unpack_from("<Q", buf, 16)[0]
    assert count == 1
    nd_magic = struct.unpack_from("<I", buf, 24)[0]
    assert nd_magic == 0xF993FAC9
    loaded = mx.nd.load(f)
    np.testing.assert_allclose(loaded["w"].asnumpy(), 1.0)


def test_clip_global_norm_split_load():
    from mxnet_tpu.gluon import utils

    arrays = [mx.nd.full((2, 2), 3.0), mx.nd.full((2,), 4.0)]
    norm = utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-4

    splits = utils.split_and_load(mx.nd.arange(12).reshape((6, 2)),
                                  [mx.cpu(), mx.cpu()])
    assert len(splits) == 2 and splits[0].shape == (3, 2)


def test_block_naming_and_repr():
    net = nn.Dense(2)
    assert net.prefix.startswith("dense")
    with mx.name.Prefix("model_"):
        pass
    d1 = nn.Dense(2, prefix="d1_")
    assert d1.prefix == "d1_"
    assert d1.weight.name == "d1_weight"
    repr(net)


def test_summary_and_hooks():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    calls = []
    h = net.register_forward_hook(lambda blk, inp, out: calls.append(1))
    net(mx.nd.ones((1, 3)))
    assert calls
    h.detach()
    net(mx.nd.ones((1, 3)))
    assert len(calls) == 1
    net.summary(mx.nd.ones((1, 3)))


def test_zero_grad_and_cast():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    with autograd.record():
        loss = net(mx.nd.ones((1, 2))).sum()
    loss.backward()
    assert np.abs(net.weight.grad().asnumpy()).sum() > 0
    net.collect_params().zero_grad()
    assert np.abs(net.weight.grad().asnumpy()).sum() == 0
    net.cast("float16")
    assert net.weight.data().dtype == np.float16


def test_contrib_layers():
    from mxnet_tpu.gluon.contrib import nn as cnn

    c = cnn.HybridConcurrent(axis=1)
    c.add(nn.Dense(3), nn.Dense(3))
    c.initialize()
    out = c(mx.nd.ones((2, 4)))
    assert out.shape == (2, 6)
    ident = cnn.Identity()
    x = mx.nd.ones((2, 2))
    assert np.allclose(ident(x).asnumpy(), 1.0)
    se = cnn.SparseEmbedding(5, 3)
    se.initialize()
    assert se(mx.nd.array([0, 4])).shape == (2, 3)


def test_lambda_layers():
    lam = nn.Lambda("tanh")
    hl = nn.HybridLambda(lambda F, x: F.relu(x))
    x = mx.nd.array([-1.0, 1.0])
    assert np.allclose(lam(x).asnumpy(), np.tanh([-1, 1]), rtol=1e-5)
    assert np.allclose(hl(x).asnumpy(), [0, 1])


def test_model_store_pretrained_contract(tmp_path):
    """model_store locate/verify/load contract (reference
    model_store.py): a provisioned {name}-{sha1[:8]}.params artifact loads
    through pretrained=True; corrupted hashes and missing files fail
    loudly. No downloads — zero-egress build."""
    import hashlib

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.gluon.model_zoo import model_store, vision

    root = str(tmp_path)
    # provision: save a trained-elsewhere artifact under the zoo naming
    src = vision.resnet18_v1(classes=10)
    src.initialize()
    src(mx.nd.array(np.zeros((1, 3, 32, 32), np.float32)))
    tmp = tmp_path / "w.params"
    src.save_parameters(str(tmp))
    digest = hashlib.sha1(tmp.read_bytes()).hexdigest()
    artifact = tmp_path / ("resnet18_v1-%s.params" % digest[:8])
    tmp.rename(artifact)

    assert model_store.get_model_file("resnet18_v1", root) == str(artifact)
    net = vision.resnet18_v1(classes=10, pretrained=True, root=root)
    x = mx.nd.array(np.random.RandomState(0).rand(1, 3, 32, 32)
                    .astype(np.float32))
    np.testing.assert_allclose(net(x).asnumpy(), src(x).asnumpy(),
                               rtol=1e-5)

    # corrupted: hash prefix no longer matches the content
    artifact.write_bytes(artifact.read_bytes() + b"x")
    with pytest.raises(MXNetError, match="corrupted"):
        model_store.get_model_file("resnet18_v1", root)

    # missing: informative provisioning error
    with pytest.raises(MXNetError, match="no pretrained weights"):
        model_store.get_model_file("resnet999", root)
    model_store.purge(root)
    assert not list(tmp_path.glob("*.params"))


def test_dense_and_conv_no_bias():
    """use_bias=False layers pass bias=None positionally; the op kernels
    must skip it (regression: TypeError adding None in fully_connected,
    found by the transformer example)."""
    import numpy as np
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import nn

    d = nn.Dense(4, use_bias=False, flatten=False)
    c = nn.Conv2D(3, 3, padding=1, use_bias=False)
    d.initialize()
    c.initialize()
    x = nd.array(np.random.RandomState(0).rand(2, 5, 6).astype(np.float32))
    xc = nd.array(np.random.RandomState(1).rand(2, 2, 8, 8).astype(np.float32))
    assert d(x).shape == (2, 5, 4)
    assert c(xc).shape == (2, 3, 8, 8)
    # and under the tape (the path the transformer example exercises)
    for blk in (d, c):
        for p in list(blk.collect_params().values()):
            p.data().attach_grad()
    with autograd.record():
        loss = (d(x) ** 2).mean() + (c(xc) ** 2).mean()
    loss.backward()
