"""Launch-based multi-process distributed tests.

Runs ``tools/launch.py --launcher local -n 2`` on the nightly
dist_sync_kvstore script — the reference's CI pattern
(``ci/docker/runtime_functions.sh:805-812`` launching
``tests/nightly/dist_sync_kvstore.py`` with ``--launcher local``) — so the
suite executes the true multi-process jax.distributed path (gloo collectives
across two OS processes), not just the in-process virtual-device mesh.
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _launch(num_workers, script, extra_env=None, timeout=300):
    env = dict(os.environ)
    # each worker is its own single-CPU-device jax process; drop the
    # accelerator relay and the test mesh forcing
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "launch.py"), "-n", str(num_workers),
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=str(REPO))


@pytest.mark.slow
def test_dist_sync_kvstore_two_workers():
    out = _launch(2, REPO / "tests" / "nightly" / "dist_sync_kvstore.py")
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    for rank in (0, 1):
        assert ("rank %d: DIST_KVSTORE_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_TRAINER_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_HEARTBEAT_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_RING_ATTENTION_OK" % rank) in out.stdout, \
            out.stdout[-4000:]


def test_launch_cli_rejects_empty_command():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "launch.py"), "-n", "2"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode != 0


@pytest.mark.slow
def test_dist_sync_kvstore_four_workers():
    """Scale the exact-value kvstore assertions past n=2 (the reference's
    nightly runs 7 workers, ci/docker/runtime_functions.sh:805-812)."""
    out = _launch(4, REPO / "tests" / "nightly" / "dist_sync_kvstore.py",
                  timeout=600)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    for rank in range(4):
        for marker in ("DIST_KVSTORE_OK", "DIST_TRAINER_OK",
                       "DIST_HEARTBEAT_OK", "DIST_RING_ATTENTION_OK"):
            assert ("rank %d: %s" % (rank, marker)) in out.stdout, \
                out.stdout[-4000:]


@pytest.mark.slow
def test_all_reduce_branches_multiprocess():
    """Every all_reduce code path (per-device and pre-reduce fallback,
    sum/mean/max/min) with exact values across 2 OS processes."""
    out = _launch(2, REPO / "tests" / "nightly" / "dist_allreduce_branches.py")
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    for rank in (0, 1):
        for marker in ("BRANCH_PER_DEVICE_SUM_OK", "BRANCH_PER_DEVICE_MEAN_OK",
                       "BRANCH_PER_DEVICE_MAXMIN_OK",
                       "BRANCH_PREREDUCE_SUM_OK", "BRANCH_PREREDUCE_MEAN_OK",
                       "BRANCH_PREREDUCE_MAX_OK", "BRANCH_PREREDUCE_MIN_OK"):
            assert ("rank %d: %s" % (rank, marker)) in out.stdout, \
                out.stdout[-4000:]


@pytest.mark.slow
def test_worker_kill_detection_and_elastic_resume():
    """Rank 2 dies hard mid-job; survivors must observe it via
    get_dead_nodes and run_elastic must resume from the last committed
    checkpoint (reference GetDeadNodes + is_recovery flow)."""
    out = _launch(3, REPO / "tests" / "nightly" / "dist_elastic_kill.py",
                  timeout=300)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    assert "rank 2: DYING_NOW" in out.stdout
    for rank in (0, 1):
        assert ("rank %d: DEAD_NODE_DETECTED" % rank) in out.stdout, \
            out.stdout[-4000:]
        assert ("rank %d: ELASTIC_RESUME_OK" % rank) in out.stdout, \
            out.stdout[-4000:]


@pytest.mark.slow
def test_dist_async_kvstore_two_workers():
    """Cross-process dist_async contract: aggregation works, the
    PS-requiring updater form fails loudly on every rank (reference
    tests/nightly/dist_async_kvstore.py counterpart)."""
    out = _launch(2, REPO / "tests" / "nightly" / "dist_async_kvstore.py")
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    for rank in (0, 1):
        assert ("rank %d: ASYNC_PUSHPULL_OK" % rank) in out.stdout
        assert ("rank %d: ASYNC_UPDATER_REJECTED_OK" % rank) in out.stdout
