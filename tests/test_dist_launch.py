"""Launch-based multi-process distributed tests.

Runs ``tools/launch.py --launcher local -n 2`` on the nightly
dist_sync_kvstore script — the reference's CI pattern
(``ci/docker/runtime_functions.sh:805-812`` launching
``tests/nightly/dist_sync_kvstore.py`` with ``--launcher local``) — so the
suite executes the true multi-process jax.distributed path (gloo collectives
across two OS processes), not just the in-process virtual-device mesh.
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _launch(num_workers, script, extra_env=None, timeout=300):
    env = dict(os.environ)
    # each worker is its own single-CPU-device jax process; drop the
    # accelerator relay and the test mesh forcing
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "launch.py"), "-n", str(num_workers),
         "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=str(REPO))


@pytest.mark.slow
def test_dist_sync_kvstore_two_workers():
    out = _launch(2, REPO / "tests" / "nightly" / "dist_sync_kvstore.py")
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    for rank in (0, 1):
        assert ("rank %d: DIST_KVSTORE_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_TRAINER_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_HEARTBEAT_OK" % rank) in out.stdout, out.stdout[-4000:]
        assert ("rank %d: DIST_RING_ATTENTION_OK" % rank) in out.stdout, \
            out.stdout[-4000:]


def test_launch_cli_rejects_empty_command():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "launch.py"), "-n", "2"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode != 0
