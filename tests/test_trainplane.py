"""Training-plane tests: the whole-step SPMD jit behind MXNET_TRAINSTEP.

The PR-5 discipline one level up: fp32 training through the graph plane
must be BIT-IDENTICAL to the eager fastpath (same host scalar prologue,
same tree kernel, same all-ones backward seed), telemetry must prove ONE
device dispatch per step, non-traceable models must fall back (never
crash), and the step counter must stay coherent when eager and in-graph
steps interleave. Runs on the conftest 8-virtual-device CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel, telemetry, trainplane
from mxnet_tpu.gluon import nn

B = 8  # power of two: 1/B loss scaling is exact, so the eager path's
#        seed-ones-then-rescale and the graph plane's in-graph rescale
#        cannot differ by rounding


def _make_mlp(prefix):
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(8))
    return net


def _init(net, xs):
    net.initialize()
    with mx.autograd.pause():
        net(nd.array(xs[:B]))


def _copy_params(src, dst):
    sp = src.collect_params()
    for name, p in dst.collect_params().items():
        tail = name.split("_", 1)[1]
        match = [n for n in sp if n.split("_", 1)[1] == tail]
        assert len(match) == 1
        p.set_data(nd.array(np.asarray(sp[match[0]].data()._data)))


def _data(seed=3):
    rs = np.random.RandomState(seed)
    return (rs.rand(5 * B, 6).astype(np.float32),
            rs.randint(0, 8, (5 * B,)))


# ---------------------------------------------------------------------------
# bit-identity vs the eager fastpath
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("opt,opt_params,ndev,bitwise", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 1, True),
    ("adam", {"learning_rate": 0.01}, 1, True),
    # on a sharded mesh the dp-partial gradient reduction (per-device
    # matmul + psum) can differ from the single-device contraction order
    # by 1 ulp — the update math itself is still the identical kernel, so
    # the runs track within float32 rounding of the grad sum
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, 2, False),
    ("adam", {"learning_rate": 0.01}, 2, False),
])
def test_graph_plane_matches_eager_fastpath(monkeypatch, opt, opt_params,
                                            ndev, bitwise):
    """Trainer-driven MLP via MXNET_TRAINSTEP=1 == the eager fastpath,
    bit-identical in fp32, over 5 steps (acceptance criterion)."""
    if len(jax.devices()) < ndev:
        pytest.skip("needs %d devices" % ndev)
    xs, ys = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tag = "%s%d_" % (opt, ndev)

    net_e = _make_mlp("e" + tag)
    _init(net_e, xs)
    net_e.hybridize()
    tr_e = gluon.Trainer(net_e.collect_params(), opt, dict(opt_params))

    net_g = _make_mlp("g" + tag)
    _init(net_g, xs)
    _copy_params(net_e, net_g)
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    tr_g = gluon.Trainer(net_g.collect_params(), opt, dict(opt_params))
    plane = trainplane.TrainPlane(net_g, loss_fn, tr_g,
                                  mesh=parallel.device_mesh(ndev))

    for s in range(5):
        x, y = xs[s * B:(s + 1) * B], ys[s * B:(s + 1) * B]
        with mx.autograd.record():
            le = loss_fn(net_e(nd.array(x)), nd.array(y))
        le.backward()
        tr_e.step(B)
        lg = plane.step(nd.array(x), nd.array(y))
        if bitwise:
            np.testing.assert_array_equal(lg.asnumpy(), le.asnumpy())
        else:
            np.testing.assert_allclose(lg.asnumpy(), le.asnumpy(),
                                       rtol=1e-5, atol=1e-6)
    assert plane.plane == "graph"

    pe, pg = net_e.collect_params(), net_g.collect_params()
    for name, p in pg.items():
        tail = name.split("_", 1)[1]
        ref = next(v for n, v in pe.items()
                   if n.split("_", 1)[1] == tail)
        if bitwise:
            np.testing.assert_array_equal(
                np.asarray(p.data()._data), np.asarray(ref.data()._data),
                err_msg=name)
        else:
            np.testing.assert_allclose(
                np.asarray(p.data()._data), np.asarray(ref.data()._data),
                rtol=1e-5, atol=1e-6, err_msg=name)
    # optimizer state lives in the trainer's updater, same layout as eager
    st_g = tr_g._updaters[0].states
    st_e = tr_e._updaters[0].states
    assert set(st_g) == set(st_e)


def test_graph_plane_one_dispatch_per_step(monkeypatch):
    """Telemetry proof of the acceptance criterion: exactly 1 jit dispatch
    per step for the whole fwd+bwd+update — the step counter ticks once
    per call and the optimizer-update counters not at all."""
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    xs, ys = _data(11)
    net = _make_mlp("disp_")
    _init(net, xs)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  tr, mesh=parallel.device_mesh(1))
    plane.step(nd.array(xs[:B]), nd.array(ys[:B]))  # activate + compile
    g0 = telemetry.STEP_DISPATCHES.value(plane="graph")
    o0 = (telemetry.OPT_DISPATCHES.value(path="perparam")
          + telemetry.OPT_DISPATCHES.value(path="fused"))
    for s in range(1, 4):
        plane.step(nd.array(xs[s * B:(s + 1) * B]),
                   nd.array(ys[s * B:(s + 1) * B]))
    assert telemetry.STEP_DISPATCHES.value(plane="graph") - g0 == 3
    assert (telemetry.OPT_DISPATCHES.value(path="perparam")
            + telemetry.OPT_DISPATCHES.value(path="fused")) - o0 == 0


# ---------------------------------------------------------------------------
# automatic fallback (acceptance: non-traceable models never crash)
# ---------------------------------------------------------------------------


class _HostSyncBlock(gluon.HybridBlock):
    """Untraceable: forces a device->host sync inside hybrid_forward."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.dense = nn.Dense(4)

    def hybrid_forward(self, F, x):
        _ = float(x.asnumpy().sum())  # concretization error under trace
        return self.dense(x)


class _PlainBlock(gluon.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.dense = nn.Dense(4)

    def forward(self, x):
        return self.dense(x)


@pytest.mark.parametrize("cls,reason", [
    (_HostSyncBlock, "host sync in hybrid_forward"),
    (_PlainBlock, "plain Block"),
])
def test_nontraceable_falls_back_to_eager(monkeypatch, cls, reason):
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    xs, _ = _data(17)
    ys = np.random.RandomState(18).rand(5 * B, 4).astype(np.float32)
    net = cls(prefix="fb%s_" % cls.__name__[:5].lower())
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(net, gluon.loss.L2Loss(), tr,
                                  mesh=parallel.device_mesh(1))
    losses = [float(plane.step(nd.array(xs[s * B:(s + 1) * B]),
                               nd.array(ys[s * B:(s + 1) * B]))
                    .asnumpy().mean()) for s in range(5)]
    assert plane.plane == "eager", reason
    assert losses[-1] < losses[0]  # it trained, eagerly


def test_ragged_final_batch_does_not_crash(monkeypatch):
    """The last partial batch of an epoch (not divisible by the dp axis)
    degrades to a replicated layout instead of raising in device_put —
    the never-a-crash contract covers mid-epoch shape changes too."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    xs, ys = _data(33)
    net = _make_mlp("rag_")
    _init(net, xs)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  tr, mesh=parallel.device_mesh(2))
    plane.step(nd.array(xs[:B]), nd.array(ys[:B]))
    assert plane.plane == "graph"
    ragged = B - 3  # 5: not divisible by the 2-wide dp axis
    loss = plane.step(nd.array(xs[B:B + ragged]),
                      nd.array(ys[B:B + ragged]))
    assert plane.plane == "graph"
    assert np.isfinite(loss.asnumpy()).all() and loss.shape == (ragged,)


def test_failed_probe_leaves_params_unreplicated(monkeypatch):
    """A probe failure on a multi-device mesh must demote WITHOUT leaving
    params re-pointed at mesh-replicated arrays, or the promised eager
    fallback itself would die mixing single-device batches with
    mesh-committed params."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    monkeypatch.setenv("MXNET_TRAINSTEP", "auto")
    xs, _ = _data(34)
    ys = np.random.RandomState(35).rand(5 * B, 4).astype(np.float32)
    net = _HostSyncBlock(prefix="probe2_")
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(net, gluon.loss.L2Loss(), tr,
                                  mesh=parallel.device_mesh(2))
    loss = plane.step(nd.array(xs[:B]), nd.array(ys[:B]))
    assert plane.plane == "eager"
    assert np.isfinite(loss.asnumpy()).all()
    for p in net.collect_params().values():
        assert len(p.data()._data.sharding.device_set) == 1


def test_trainstep_zero_forces_eager(monkeypatch):
    monkeypatch.setenv("MXNET_TRAINSTEP", "0")
    xs, ys = _data(21)
    net = _make_mlp("off_")
    _init(net, xs)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    plane = trainplane.TrainPlane(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  tr, mesh=parallel.device_mesh(1))
    plane.step(nd.array(xs[:B]), nd.array(ys[:B]))
    assert plane.plane == "eager"


# ---------------------------------------------------------------------------
# bf16 training mode
# ---------------------------------------------------------------------------


def test_bf16_mode_master_weights_and_loss(monkeypatch):
    """MXNET_TRAIN_DTYPE=bf16: params train in bfloat16, the optimizer
    keeps f32 master weights (multi-precision), and the graph-plane loss
    matches an explicit eager bf16 run within bf16 tolerance."""
    xs, ys = _data(31)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # eager bf16 reference: manual cast + multi_precision, the status quo
    net_e = _make_mlp("ebf_")
    _init(net_e, xs)
    net_e.cast("bfloat16")
    tr_e = gluon.Trainer(net_e.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9,
                          "multi_precision": True})

    net_g = _make_mlp("gbf_")
    _init(net_g, xs)
    _copy_params(net_e, net_g)  # fp32 values == bf16-cast values upcast
    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    monkeypatch.setenv("MXNET_TRAIN_DTYPE", "bf16")
    tr_g = gluon.Trainer(net_g.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    plane = trainplane.TrainPlane(net_g, loss_fn, tr_g,
                                  mesh=parallel.device_mesh(1))

    for s in range(3):
        x = xs[s * B:(s + 1) * B]
        y = ys[s * B:(s + 1) * B]
        xe = mx.nd.NDArray(jnp.asarray(x, jnp.bfloat16), mx.cpu())
        with mx.autograd.record():
            le = loss_fn(net_e(xe), nd.array(y))
        le.backward()
        tr_e.step(B)
        lg = plane.step(nd.array(x), nd.array(y))
        np.testing.assert_allclose(
            lg.asnumpy().astype(np.float32),
            le.asnumpy().astype(np.float32), rtol=1e-2, atol=1e-2)

    assert plane.plane == "graph"
    for p in net_g.collect_params().values():
        assert p.data()._data.dtype == jnp.bfloat16
    # master weights stay f32 (the mp (master, base) state pair)
    states = tr_g._updaters[0].states
    for st in states.values():
        master, _base = st
        assert master.dtype == jnp.float32


# ---------------------------------------------------------------------------
# step-counter coherence (in-graph + eager interleave)
# ---------------------------------------------------------------------------


def test_mixed_trainstep_eager_counter_and_lr_schedule():
    """TrainStep._t and Optimizer.num_update share one source of truth:
    2 eager + 3 in-graph + 2 eager steps advance the lr schedule exactly
    like 7 eager steps would (regression for lr-schedule drift)."""
    from mxnet_tpu import lr_scheduler

    xs, _ = _data(41)
    lbl = np.random.RandomState(42).rand(B, 4).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    def eager_step(net, tr):
        with mx.autograd.record():
            l = loss_fn(net(nd.array(xs[:B])), nd.array(lbl))
        l.backward()
        tr.step(B)

    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    opt = mx.optimizer.create("sgd", learning_rate=0.8, lr_scheduler=sched)
    net = nn.Dense(4, prefix="mix_")
    net.initialize()
    with mx.autograd.pause():
        net(nd.array(xs[:B]))
    tr = gluon.Trainer(net.collect_params(), opt)
    step = parallel.TrainStep(net, loss_fn, opt, parallel.device_mesh(1))

    for _ in range(2):
        eager_step(net, tr)
    assert opt.num_update == 2
    for _ in range(3):
        step(nd.array(xs[:B]), nd.array(lbl))
    assert step._t == 5 and opt.num_update == 5
    for _ in range(2):
        eager_step(net, tr)
    assert opt.num_update == 7

    # reference: a pure-eager 7-step run reads the same schedule point
    ref_sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
    ref_sched.base_lr = 0.8
    assert opt.learning_rate == ref_sched(7)


def test_sync_num_update_seeds_fresh_indices():
    """An index first touched eagerly AFTER graph-only steps continues the
    counter at t + 1 — graph steps never populate _index_update_count, so
    sync must advance begin_num_update too, or Adam's bias correction
    would replay step 1 at step t + 1."""
    opt = mx.optimizer.create("adam", learning_rate=0.01)
    opt.sync_num_update(10)
    assert opt._index_update_count == {}  # graph steps left it empty
    opt._update_count(0)
    assert opt._index_update_count[0] == 11
    assert opt.num_update == 11


# ---------------------------------------------------------------------------
# Module.fit / model.fit routing
# ---------------------------------------------------------------------------


def _mlp_symbol(classes):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _fit_module(trainstep, xs, ys, monkeypatch):
    from mxnet_tpu import io as io_mod
    from mxnet_tpu.module import Module

    monkeypatch.setenv("MXNET_TRAINSTEP", trainstep)
    mx.random.seed(7)
    it = io_mod.NDArrayIter(xs, ys, batch_size=B, shuffle=False)
    mod = Module(_mlp_symbol(4), context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(rnd_type="uniform"))
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_module_fit_graph_plane_bitwise(monkeypatch):
    """Module.fit through the graph plane (MXNET_TRAINSTEP=1) trains
    bit-identically to the eager executor path, with ONE whole-step
    dispatch per batch and zero separate optimizer dispatches."""
    rs = np.random.RandomState(51)
    xs = rs.rand(4 * B, 6).astype(np.float32)
    ys = rs.randint(0, 4, (4 * B,)).astype(np.float32)

    g0 = telemetry.STEP_DISPATCHES.value(plane="graph")
    o0 = (telemetry.OPT_DISPATCHES.value(path="perparam")
          + telemetry.OPT_DISPATCHES.value(path="fused"))
    graph_params = _fit_module("1", xs, ys, monkeypatch)
    assert telemetry.STEP_DISPATCHES.value(plane="graph") - g0 == 8  # 2x4
    assert (telemetry.OPT_DISPATCHES.value(path="perparam")
            + telemetry.OPT_DISPATCHES.value(path="fused")) - o0 == 0

    eager_params = _fit_module("0", xs, ys, monkeypatch)
    assert set(graph_params) == set(eager_params)
    for name in graph_params:
        np.testing.assert_array_equal(graph_params[name],
                                      eager_params[name], err_msg=name)


def test_module_plane_demotes_on_grad_req_add(monkeypatch):
    """A param with grad_req='add' (accumulation across calls — a side
    effect the compiled step can't honor) demotes the WHOLE module to the
    eager path; it must never be silently frozen as a jit constant while
    the write-req params train."""
    from mxnet_tpu import io as io_mod
    from mxnet_tpu.module import Module

    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    rs = np.random.RandomState(71)
    xs = rs.rand(2 * B, 6).astype(np.float32)
    ys = rs.randint(0, 4, (2 * B,)).astype(np.float32)
    it = io_mod.NDArrayIter(xs, ys, batch_size=B)
    mod = Module(_mlp_symbol(4), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert trainplane.module_plane(mod) is not None  # eligible as bound

    mod._exec_group.execs[0].grad_req["fc1_weight"] = "add"
    assert trainplane.module_plane(mod) is None  # mixed write/add demotes


def test_feedforward_fit_rides_module_plane(monkeypatch):
    """model.fit (FeedForward) trains through Module.fit and therefore the
    plane; smoke: it runs under MXNET_TRAINSTEP=1 and learns."""
    from mxnet_tpu import io as io_mod
    from mxnet_tpu.model import FeedForward

    monkeypatch.setenv("MXNET_TRAINSTEP", "1")
    rs = np.random.RandomState(61)
    xs = rs.rand(4 * B, 6).astype(np.float32)
    ys = (xs.sum(axis=1) > 3.0).astype(np.float32)
    it = io_mod.NDArrayIter(xs, ys, batch_size=B)
    ff = FeedForward(_mlp_symbol(2), num_epoch=2, optimizer="sgd",
                     learning_rate=0.5)
    ff.fit(it)
    out = ff.predict(io_mod.NDArrayIter(xs, ys, batch_size=B))
    assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# fit() helper + pre-sharded feed
# ---------------------------------------------------------------------------


def test_fit_helper_sharded_feed(monkeypatch):
    """trainplane.fit drives epochs through the graph plane with the
    DevicePrefetchIter pre-sharded feed; training makes progress."""
    from mxnet_tpu import io as io_mod

    monkeypatch.setenv("MXNET_TRAINSTEP", "auto")
    monkeypatch.setenv("MXNET_SHARDED_FEED", "1")
    rs = np.random.RandomState(71)
    xs = rs.rand(8 * B, 6).astype(np.float32)
    ys = rs.randint(0, 4, (8 * B,)).astype(np.float32)
    net = _make_mlp("fith_")
    _init(net, xs)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2})
    it = io_mod.NDArrayIter(xs, ys, batch_size=B, shuffle=False)

    seen = []
    plane = trainplane.fit(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                           it, epochs=2,
                           batch_end_callback=lambda e, n, l: seen.append(
                               float(l.asnumpy().mean())))
    assert plane.plane == "graph"
    assert plane.step_count == 16  # 2 epochs x 8 batches
    assert seen[-1] < seen[0]


def test_device_prefetch_iter_skips_resident_batches():
    """Bugfix regression: an array already laid out on the target device/
    sharding passes through _stage untouched — no wasted D2D re-put."""
    from mxnet_tpu import io as io_mod

    rs = np.random.RandomState(81)
    xs = rs.rand(2 * B, 4).astype(np.float32)
    ys = rs.rand(2 * B).astype(np.float32)
    base = io_mod.NDArrayIter(xs, ys, batch_size=B)
    it = io_mod.DevicePrefetchIter(base, ctx=mx.cpu())
    batch = next(it)
    arr = batch.data[0]
    staged = it._stage(io_mod.DataBatch([arr], [batch.label[0]], pad=0))
    assert staged.data[0] is arr  # identity, not a copy
    assert staged.label[0] is batch.label[0]


def test_device_prefetch_iter_sharding_target():
    """sharding= lays batches out over the mesh's dp axis ahead of the
    step (callable ndim -> NamedSharding form)."""
    from mxnet_tpu import io as io_mod

    ndev = min(2, len(jax.devices()))
    mesh = parallel.device_mesh(ndev)
    rs = np.random.RandomState(91)
    xs = rs.rand(2 * B, 4).astype(np.float32)
    ys = rs.rand(2 * B).astype(np.float32)
    base = io_mod.NDArrayIter(xs, ys, batch_size=B)
    it = io_mod.DevicePrefetchIter(
        base, ctx=mx.cpu(),
        sharding=lambda ndim: parallel.batch_sharding(mesh, ndim))
    batch = next(it)
    data = batch.data[0]._data
    target = parallel.batch_sharding(mesh, data.ndim)
    assert data.sharding.is_equivalent_to(target, data.ndim)
    # the step's own shard pass is now the no-op equivalence check
    assert parallel.shard_to_mesh(batch.data[0], mesh) is data


def test_dataloader_sharding_stages_batches():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ndev = min(2, len(jax.devices()))
    mesh = parallel.device_mesh(ndev)
    rs = np.random.RandomState(95)
    ds = ArrayDataset(nd.array(rs.rand(4 * B, 5).astype(np.float32)),
                      nd.array(rs.rand(4 * B).astype(np.float32)))
    loader = DataLoader(
        ds, batch_size=B,
        sharding=lambda ndim: parallel.batch_sharding(mesh, ndim))
    for data, label in loader:
        tgt = parallel.batch_sharding(mesh, data._data.ndim)
        assert data._data.sharding.is_equivalent_to(tgt, data._data.ndim)
        break


def test_dataloader_sharding_keeps_namedtuple_batches():
    """The staged feed rebuilds containers field-for-field — a batchify_fn
    returning a namedtuple must come back as the same namedtuple."""
    import collections

    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    from mxnet_tpu.gluon.data.dataloader import default_batchify_fn

    Batch = collections.namedtuple("Batch", ["data", "label"])
    mesh = parallel.device_mesh(1)
    rs = np.random.RandomState(97)
    ds = ArrayDataset(nd.array(rs.rand(2 * B, 5).astype(np.float32)),
                      nd.array(rs.rand(2 * B).astype(np.float32)))
    loader = DataLoader(
        ds, batch_size=B,
        batchify_fn=lambda samples: Batch(*default_batchify_fn(samples)),
        sharding=lambda ndim: parallel.batch_sharding(mesh, ndim))
    batch = next(iter(loader))
    assert isinstance(batch, Batch)
    tgt = parallel.batch_sharding(mesh, batch.data._data.ndim)
    assert batch.data._data.sharding.is_equivalent_to(
        tgt, batch.data._data.ndim)


# ---------------------------------------------------------------------------
# fresh replication (TrainStep init HBM fix)
# ---------------------------------------------------------------------------


def test_fresh_replicate_never_aliases_source():
    """The replicated buffer must be fresh — the step jit donates it, and
    an alias would let donation delete the caller's array."""
    mesh1 = parallel.device_mesh(1)
    x = jax.device_put(jnp.arange(8, dtype=jnp.float32), jax.devices()[0])
    out = parallel.fresh_replicate(x, mesh1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert out.unsafe_buffer_pointer() != x.unsafe_buffer_pointer()

    if len(jax.devices()) >= 2:
        mesh2 = parallel.device_mesh(2)
        out2 = parallel.fresh_replicate(x, mesh2)
        np.testing.assert_array_equal(np.asarray(out2), np.asarray(x))
        ptrs = {s.data.unsafe_buffer_pointer()
                for s in out2.addressable_shards}
        assert x.unsafe_buffer_pointer() not in ptrs
    # host source: one put, fresh by construction
    out3 = parallel.fresh_replicate(np.ones(4, np.float32), mesh1)
    np.testing.assert_array_equal(np.asarray(out3), np.ones(4))


def test_trainstep_net_params_survive_donating_steps():
    """After the fresh-replicate init, the net's own buffers stay valid
    across donating TrainStep calls (the isolation fresh_replicate buys)."""
    xs = np.random.RandomState(5).rand(B, 4).astype(np.float32)
    ys = np.random.RandomState(6).rand(B, 1).astype(np.float32)
    net = nn.Dense(1, prefix="iso_")
    net.initialize()
    with mx.autograd.pause():
        net(nd.array(xs))
    before = {n: np.asarray(p.data()._data).copy()
              for n, p in net.collect_params().items()}
    step = parallel.TrainStep(net, gluon.loss.L2Loss(), "sgd",
                              parallel.device_mesh(1),
                              optimizer_params={"learning_rate": 0.1})
    for _ in range(2):
        step(nd.array(xs), nd.array(ys))
    for n, p in net.collect_params().items():
        np.testing.assert_array_equal(np.asarray(p.data()._data),
                                      before[n], err_msg=n)
